"""Snapshot retention: keep the newest N, retire the rest — safely.

Incremental snapshots make deletion ordering matter: an increment is
only readable while its base snapshots exist. ``apply_retention`` walks
a directory of snapshots, decides what to keep, MATERIALIZES any kept
snapshot that references a doomed base (copying the referenced blobs in,
checksum-verified, before anything is deleted), and only then removes
the rest. A crash at any point leaves every kept snapshot readable:
materialization commits atomically, and deletion happens last.

Delta-stream roots (tpusnap.delta) are just directories of incremental
snapshots, so the same pass IS chain compaction: keeping the newest N
micro-commits materializes any kept head whose chain members are
doomed, then retires the rest — a kept delta head can never lose a base
or intermediate increment it references (``_referenced_bases`` walks
delta links transitively, so even hand-built non-collapsed chains stay
pinned end to end).

Local filesystems only (deletion needs directory listing/removal, which
the storage-plugin API deliberately doesn't expose for object stores —
cloud retention belongs in bucket lifecycle rules, with
``python -m tpusnap materialize`` to cut references first).

Exposed as ``python -m tpusnap retain <root> --keep N [--dry-run]``.
No reference counterpart.
"""

from __future__ import annotations

import os
import shutil
from dataclasses import dataclass, field
from typing import Dict, List, Optional
from urllib.parse import urlsplit

from .inspect import iter_blobs, load_snapshot_metadata, materialize_snapshot

__all__ = ["RetentionPlan", "apply_retention"]


@dataclass
class RetentionPlan:
    keep: List[str] = field(default_factory=list)  # newest first
    delete: List[str] = field(default_factory=list)
    materialize: List[str] = field(default_factory=list)  # subset of keep
    executed: bool = False
    bytes_copied: int = 0

    def summary(self) -> str:
        verb = "materialized" if self.executed else "to materialize"
        dverb = "deleted" if self.executed else "to delete"
        return (
            f"{len(self.keep)} kept, {len(self.materialize)} {verb} "
            f"({self.bytes_copied / 1e6:.1f} MB copied), "
            f"{len(self.delete)} {dverb}"
        )


def _local_root(root: str) -> str:
    parts = urlsplit(root)
    if parts.scheme not in ("", "file"):
        raise ValueError(
            f"retention requires a local filesystem root, got {root!r} — "
            "for object stores, materialize the survivors and use bucket "
            "lifecycle rules"
        )
    return os.path.abspath(parts.path or root)


def _list_snapshots(root: str) -> List[str]:
    """Snapshot directories directly under ``root`` (contain
    ``.snapshot_metadata``), oldest first by commit time.

    Ordering uses the ``created_at`` recorded IN the metadata at take
    time — file mtimes are unreliable (``materialize`` atomically
    rewrites the metadata file, rsync/copies reset mtimes; ordering by
    mtime could mark the true newest checkpoint as oldest and delete
    it). Pre-``created_at`` snapshots fall back to mtime."""
    out = []
    for name in os.listdir(root):
        path = os.path.join(root, name)
        meta = os.path.join(path, ".snapshot_metadata")
        if not os.path.isfile(meta):
            continue
        created = load_snapshot_metadata(path).created_at
        if created is None:
            created = os.path.getmtime(meta)
        out.append((created, path))
    out.sort()
    return [p for _, p in out]


def _direct_bases(snap_path: str) -> List[str]:
    """Absolute paths of base snapshots ``snap_path`` DIRECTLY
    references (has a ``../`` blob location into)."""
    from .inspect import base_root_of_location

    md = load_snapshot_metadata(snap_path)
    bases = set()
    for blob in iter_blobs(md.manifest):
        if blob.location.startswith("../"):
            base = base_root_of_location(blob.location, md.base_roots)
            bases.add(os.path.abspath(os.path.join(snap_path, base)))
    return sorted(bases)


def _referenced_bases(snap_path: str) -> List[str]:
    """Every base snapshot ``snap_path`` depends on, TRANSITIVELY: a
    kept delta head must pin its whole chain. Incremental writers
    collapse chained references (a head's direct refs name every member
    physically holding its bytes), so the direct set is normally
    complete — the transitive walk is defense in depth against
    hand-built or pre-collapse chains, where deleting a base-of-a-base
    would break a kept snapshot retention itself never inspected.
    Cycle-safe; unreadable bases end the walk on that branch (they are
    already broken — materialization of the keeper will surface it)."""
    out: List[str] = []
    seen = {os.path.abspath(snap_path)}
    frontier = _direct_bases(snap_path)
    while frontier:
        base = frontier.pop()
        if base in seen:
            continue
        seen.add(base)
        out.append(base)
        try:
            frontier.extend(_direct_bases(base))
        except Exception:
            continue
    return sorted(out)


def apply_retention(
    root: str,
    keep_last: int,
    dry_run: bool = False,
    storage_options: Optional[Dict] = None,
) -> RetentionPlan:
    """Keep the newest ``keep_last`` snapshots under ``root``; retire the
    rest. Kept snapshots referencing a doomed base are materialized
    (self-contained, verified) BEFORE any deletion. ``dry_run`` returns
    the plan without touching anything.

    Kept snapshots that reference bases OUTSIDE ``root`` keep those
    references — only snapshots under ``root`` are ever deleted."""
    if keep_last < 1:
        raise ValueError("keep_last must be >= 1")
    root = _local_root(root)
    snaps = _list_snapshots(root)
    plan = RetentionPlan(
        keep=list(reversed(snaps[-keep_last:])),
        delete=snaps[:-keep_last],
    )
    doomed = set(plan.delete)
    for snap in plan.keep:
        if any(base in doomed for base in _referenced_bases(snap)):
            plan.materialize.append(snap)
    if dry_run:
        return plan
    for snap in plan.materialize:
        stats = materialize_snapshot(snap, storage_options)
        plan.bytes_copied += stats["bytes_copied"]
    # Defense in depth: re-check no kept snapshot still references a
    # doomed path (materialize rewrote them; a logic regression here
    # must fail BEFORE data is destroyed).
    for snap in plan.keep:
        remaining = [b for b in _referenced_bases(snap) if b in doomed]
        if remaining:  # pragma: no cover - guarded invariant
            raise RuntimeError(
                f"{snap} still references doomed base(s) {remaining}; "
                "aborting before deletion"
            )
    for snap in plan.delete:
        shutil.rmtree(snap)
    plan.executed = True
    return plan
