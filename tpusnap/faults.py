"""Deterministic storage fault injection: seeded ``FaultPlan`` + a
``StoragePlugin`` wrapper, exposed as ``chaos+<scheme>://`` URLs.

The chaos layer sits UNDER the retry middleware
(``Retrying(FaultInjection(real plugin))``), so injected faults exercise
exactly the production retry/abort paths:

- transient exceptions (``InjectedFaultError`` subclasses
  ``ConnectionError`` → classified transient by every plugin);
- injected per-op latency (seeded jitter);
- torn writes: a failing ``write`` persists a seeded prefix of the
  buffer through the real plugin before raising — the exact failure
  whole-op retry and metadata-written-last commit exist to survive;
- short reads: a failing ``read`` delivers a truncated buffer before
  raising — discarded by the retry wrapper's fresh-ReadIO-per-attempt;
- crash-after-op: SIGKILL the process after the Nth successful op of a
  kind (crash-matrix windows inside storage I/O, no monkeypatching).

Usage — no code changes needed, just the URL (and optionally a spec)::

    Snapshot.take("chaos+fs:///tmp/snap", app_state,
                  storage_options={"fault_plan": FaultPlan(seed=3,
                                                           transient_per_op=1)})
    # or via the environment, e.g. in an example/benchmark run:
    #   TPUSNAP_FAULT_SPEC="seed=3,transient_per_op=1,latency_ms=2"

Determinism: all randomness derives from ``FaultPlan.seed``; op indices
are assigned in arrival order. Under concurrent scheduling the mapping
of logical blobs to op indices can vary run to run, but the injected
fault COUNT and shape per seed are fixed — which is what the chaos soak
asserts convergence and integrity against.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from . import flight, telemetry
from .io_types import ReadIO, StoragePlugin, WriteIO

logger = logging.getLogger(__name__)

_FAULT_SPEC_ENV_VAR = "TPUSNAP_FAULT_SPEC"


class InjectedFaultError(ConnectionError):
    """A deliberately injected transient storage failure. Subclasses
    ``ConnectionError`` so every transient classifier retries it."""


@dataclass
class FaultPlan:
    """Seeded, deterministic description of how a backend misbehaves.

    - ``transient_per_op``: the first K attempts of every distinct
      (kind, path) op raise transient errors — "≥1 transient error per
      storage op" with guaranteed convergence under retry.
    - ``transient_every``: additionally, every Nth op overall raises
      (0 = off). Only FIRST attempts of an op can draw this fault
      (retries are exempt, though they advance the counter), so any N —
      including 1 — converges under retry.
    - ``torn_writes``: failing writes persist a seeded prefix through
      the real plugin before raising (object-store ``write_atomic``
      failures stay clean: tearing there would fabricate a failure the
      real backend cannot produce).
    - ``short_reads``: failing reads deliver a seeded truncation of the
      real bytes before raising.
    - ``latency_sec``: seeded-jittered sleep on every op.
    - ``crash_after_op``: ("write", 7) → SIGKILL this process right
      after the 7th successful write (1-based).
    - ``stall_op``: ("write", 3, 5.0) → the 3rd write ATTEMPT sleeps
      5 s inside the op before proceeding normally (index 0 stalls
      every attempt of the kind). The op stays in flight for the whole
      sleep — the deterministic hang the stall watchdog
      (:mod:`tpusnap.progress`) is tested against.
    - ``outage``: ("write", 0.0, 10.0) → a SUSTAINED unavailability
      window: every matching op (kind, or ``*`` for all) raises a
      transient error from ``start`` seconds after this plugin's first
      op until ``start + duration``. Deterministic in TIME rather than
      per-op probability — "cloud down for 10 s mid-drain" as one spec
      token (``outage=write:10``, ``outage=*:5:10``), the failure shape
      the write-back tier's circuit breaker exists for.
    - ``bandwidth_gbps``: a WRITE-PATH pipe ceiling — a shared token
      bucket serializes write/write_atomic payload bytes at this GB/s
      across all concurrent ops, so the plugin behaves like a slow
      network pipe rather than per-op latency (which would tax
      compressed and raw bytes identically). The deterministic
      bandwidth-bound regime the compression auto policy exists for;
      bench.py's compression section and ci_gate's compression smoke
      run on it.
    - ``rank``: RANK FILTER — the whole plan applies only on the
      process whose distributed rank (jax.distributed process_id, 0
      when uninitialized) matches; every other rank's plugin behaves
      fault-free. One shared ``TPUSNAP_FAULT_SPEC`` can thus
      deterministically kill or wedge exactly one rank of a
      multi-process world (``rank=1,crash_after_op=write:2``) — the
      rank-failure crash matrix and ci_gate's rank-failure smoke run
      on it.
    - ``wedge``: ("write", 3) → the 3rd write ATTEMPT SIGSTOPs the
      whole process (index 0/``*`` = first attempt of the kind). Unlike
      ``stall_op`` — which hangs one op while heartbeat/lease threads
      keep running (a SLOW rank) — SIGSTOP freezes every thread, so
      from the peers' view the rank is DEAD (leases expire, liveness
      raises RankFailedError) while the parent test can still SIGCONT
      or SIGKILL the frozen process. The deterministic "host froze"
      fault the lease layer exists for.
    - ``preempt``: ("write", 3, 30.0) → the 3rd write ATTEMPT delivers
      SIGTERM to this process (index 0/``*`` = first attempt of the
      kind), then SIGKILLs it ``grace_s`` seconds later if it is still
      alive — the graceful-leave twin of ``wedge``: a cloud preemption
      NOTICE with a hard deadline. A process whose SIGTERM handler
      drains its work and leaves (e.g. ``DeltaStream.leave()``) within
      the grace exits cleanly; one that ignores the notice dies like a
      ``wedge``-then-kill. Fires at most once per plugin instance.
    """

    seed: int = 0
    transient_per_op: int = 0
    transient_every: int = 0
    torn_writes: bool = False
    short_reads: bool = False
    latency_sec: float = 0.0
    crash_after_op: Optional[Tuple[str, int]] = None
    stall_op: Optional[Tuple[str, int, float]] = None
    outage: Optional[Tuple[str, float, float]] = None
    bandwidth_gbps: float = 0.0
    rank: Optional[int] = None
    wedge: Optional[Tuple[str, int]] = None
    preempt: Optional[Tuple[str, int, float]] = None

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse ``"seed=3,transient_per_op=1,latency_ms=2,torn_writes=1"``.
        Keys mirror the field names; ``latency_ms`` is accepted as a
        convenience; ``crash_after_op=write:7``."""
        plan = cls()
        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            key, _, value = item.partition("=")
            key = key.strip()
            value = value.strip()
            if key == "latency_ms":
                plan.latency_sec = float(value) / 1000.0
            elif key == "latency_sec":
                plan.latency_sec = float(value)
            elif key == "bandwidth_gbps":
                plan.bandwidth_gbps = float(value)
            elif key in ("seed", "transient_per_op", "transient_every"):
                setattr(plan, key, int(value))
            elif key in ("torn_writes", "short_reads"):
                setattr(plan, key, value not in ("0", "false", "False", ""))
            elif key == "rank":
                plan.rank = int(value)
            elif key == "crash_after_op":
                kind, _, idx = value.partition(":")
                plan.crash_after_op = (kind, int(idx))
            elif key == "wedge":
                # "write:3" → 3rd write attempt SIGSTOPs the process
                # ("write:*" or index 0 → the first attempt).
                kind, _, idx = value.partition(":")
                plan.wedge = (kind, 0 if idx in ("", "*") else int(idx))
            elif key == "stall_op":
                # "write:3:5.0" → 3rd write attempt sleeps 5 s
                # ("write:*:5.0" or index 0 → every attempt).
                kind, idx, secs = value.split(":")
                plan.stall_op = (
                    kind,
                    0 if idx == "*" else int(idx),
                    float(secs),
                )
            elif key == "preempt":
                # "write:3:30" → 3rd write attempt gets SIGTERM with a
                # 30 s SIGKILL deadline ("write:*:30" or index 0 → the
                # first attempt).
                kind, idx, secs = value.split(":")
                plan.preempt = (
                    kind,
                    0 if idx == "*" else int(idx),
                    float(secs),
                )
            elif key == "outage":
                # "write:10" → writes down for the first 10 s;
                # "*:5:10" → ALL ops down from t=5 s to t=15 s
                # (t anchored at this plugin's first op).
                parts = value.split(":")
                if len(parts) == 2:
                    plan.outage = (parts[0], 0.0, float(parts[1]))
                elif len(parts) == 3:
                    plan.outage = (parts[0], float(parts[1]), float(parts[2]))
                else:
                    raise ValueError(
                        f"outage spec {value!r}: expected <kind>:<secs> "
                        "or <kind>:<start>:<secs>"
                    )
            else:
                raise ValueError(f"Unknown fault spec key {key!r} in {spec!r}")
        return plan

    @classmethod
    def coerce(cls, value) -> "FaultPlan":
        """FaultPlan | spec-string | dict | None → FaultPlan. ``None``
        consults TPUSNAP_FAULT_SPEC, defaulting to one transient error
        per op (a chaos URL with no plan should still misbehave)."""
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            return cls.from_spec(value)
        if isinstance(value, dict):
            return cls(**value)
        if value is None:
            env = os.environ.get(_FAULT_SPEC_ENV_VAR)
            if env:
                return cls.from_spec(env)
            return cls(transient_per_op=1)
        raise TypeError(f"Cannot build a FaultPlan from {value!r}")


@dataclass
class _FaultState:
    """Mutable per-plugin-instance counters (the plan itself is data)."""

    rng: random.Random
    op_count: int = 0
    kind_success: Dict[str, int] = field(default_factory=dict)
    kind_attempts: Dict[str, int] = field(default_factory=dict)
    wedge_attempts: Dict[str, int] = field(default_factory=dict)
    preempt_attempts: Dict[str, int] = field(default_factory=dict)
    preempt_fired: bool = False
    per_op_attempts: Dict[Tuple[str, str], int] = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)
    # Outage-window anchor (monotonic, set at this plugin's first op)
    # and the edge-trigger flag for its one flight breadcrumb.
    outage_anchor: Optional[float] = None
    outage_announced: bool = False
    # Write-bandwidth token bucket: the monotonic time the shared pipe
    # frees up (concurrent writers queue behind it, like a real link).
    bw_release: float = 0.0


# Monotonic seam for the outage window (tests pin it to a fake clock so
# the window is exact without sleeps).
_mono = time.monotonic


def _process_rank() -> int:
    """This process's distributed rank for the ``rank=`` plan filter —
    jax.distributed's coordination state (the same source comm.py
    reads; never initializes a device backend), 0 when uninitialized."""
    try:
        from jax._src import distributed as _jd

        return int(_jd.global_state.process_id or 0)
    except Exception:
        return 0


class FaultInjectionStoragePlugin(StoragePlugin):
    """Wraps any ``StoragePlugin``, misbehaving per a seeded ``FaultPlan``.
    Scheduling-transparent like the retry wrapper (in-place reads,
    overhead accounting, draining all delegate)."""

    def __init__(self, inner: StoragePlugin, plan: Optional[FaultPlan] = None):
        self.inner = inner
        self.plan = FaultPlan.coerce(plan)
        if self.plan.rank is not None and self.plan.rank != _process_rank():
            # Rank-filtered plan on a non-matching rank: behave
            # fault-free (an inert plan, not a bypassed wrapper, so the
            # plugin surface stays identical on every rank).
            self.plan = FaultPlan(seed=self.plan.seed)
        self._state = _FaultState(rng=random.Random(self.plan.seed))

    # --- scheduling transparency -----------------------------------------

    @property
    def supports_in_place_reads(self) -> bool:  # type: ignore[override]
        return self.inner.supports_in_place_reads

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        return self.inner.in_place_read_overhead_bytes(nbytes)

    def drain_in_flight(self) -> None:
        self.inner.drain_in_flight()

    def classify_transient(self, exc: BaseException) -> bool:
        # The retry wrapper asks the plugin it wraps; delegate to the
        # real backend's classifier (InjectedFaultError is a
        # ConnectionError, transient under every default).
        from .retry import default_classify_transient

        inner_classify = getattr(
            self.inner, "classify_transient", default_classify_transient
        )
        return isinstance(exc, InjectedFaultError) or inner_classify(exc)

    # --- fault decisions --------------------------------------------------

    @staticmethod
    def _kind_for(kind: str, path: str) -> str:
        """Ops on lifecycle-journal sidecars count under their own kind
        (``journal``) so crash-matrix specs can SIGKILL around journal
        writes by name (``crash_after_op=journal:1``) without the index
        arithmetic drifting as blob counts change; everything else keeps
        the raw op kind. ``list`` (fsck/gc enumeration) is already its
        own kind. CAS ref records get the same treatment
        (``crash_after_op=cas_ref:1`` kills precisely after the first
        ref flush — the mid-ref-write chaos window)."""
        from .io_types import CAS_REFS_DIR
        from .lifecycle import is_journal_path

        if is_journal_path(path):
            return "journal"
        if path.startswith(CAS_REFS_DIR + "/"):
            return "cas_ref"
        return kind

    def _decide(self, kind: str, path: str) -> Tuple[bool, float]:
        """One decision per op attempt: (inject_transient, latency)."""
        plan, st = self.plan, self._state
        with st.lock:
            st.op_count += 1
            n = st.op_count
            latency = (
                plan.latency_sec * (0.5 + st.rng.random())
                if plan.latency_sec
                else 0.0
            )
            inject = False
            key = (kind, path)
            attempts = st.per_op_attempts.get(key, 0)
            st.per_op_attempts[key] = attempts + 1
            if plan.transient_per_op and attempts < plan.transient_per_op:
                inject = True
            if (
                plan.transient_every
                and attempts == 0
                and n % plan.transient_every == 0
            ):
                # First attempts only: a RETRY of an op that drew the
                # every-Nth fault must not draw it again (with
                # transient_every=1 every attempt would fault and the
                # op could never converge under retry).
                inject = True
            return inject, latency

    def _record_success(self, kind: str) -> None:
        plan, st = self.plan, self._state
        with st.lock:
            st.kind_success[kind] = st.kind_success.get(kind, 0) + 1
            crash = (
                plan.crash_after_op is not None
                and plan.crash_after_op[0] == kind
                and st.kind_success[kind] == plan.crash_after_op[1]
            )
        if crash:
            logger.warning(
                "FaultPlan crash_after_op=%s: SIGKILLing pid %d",
                plan.crash_after_op,
                os.getpid(),
            )
            os.kill(os.getpid(), signal.SIGKILL)

    def _torn_len(self, total: int) -> int:
        with self._state.lock:
            return self._state.rng.randrange(0, max(total, 1))

    def _stall_seconds(self, kind: str) -> float:
        """Injected in-op sleep for this attempt of ``kind`` (the
        ``stall_op`` plan): 1-based attempt index, 0/``*`` = every."""
        plan, st = self.plan, self._state
        if plan.stall_op is None or plan.stall_op[0] != kind:
            return 0.0
        with st.lock:
            n = st.kind_attempts.get(kind, 0) + 1
            st.kind_attempts[kind] = n
        idx = plan.stall_op[1]
        return plan.stall_op[2] if idx == 0 or n == idx else 0.0

    def _check_outage(self, kind: str, path: str) -> None:
        """Raise while a planned sustained-outage window covers this op
        (deterministic in time, anchored at the plugin's first op)."""
        plan, st = self.plan, self._state
        if plan.outage is None:
            return
        okind, start, duration = plan.outage
        now = _mono()
        with st.lock:
            # Anchor at the plugin's FIRST op of any kind (as the spec
            # documents) — a kind-filtered anchor would shift the
            # window by however long the plugin spent listing/reading
            # before its first matching op.
            if st.outage_anchor is None:
                st.outage_anchor = now
            t = now - st.outage_anchor
        if okind not in ("*", kind):
            return
        with st.lock:
            in_window = start <= t < start + duration
            announce = in_window and not st.outage_announced
            if announce:
                st.outage_announced = True
        if not in_window:
            return
        telemetry.incr(f"faults.outage.{kind}")
        if announce:
            # One flight breadcrumb per window, not one per rejected op.
            telemetry.event(
                "outage_injected", kind=okind, start=start, seconds=duration
            )
            flight.record(
                "fault_outage", op=okind, start=start, seconds=duration
            )
        raise InjectedFaultError(
            f"injected outage: {kind}({path!r}) rejected "
            f"({t - start:.2f}s into a {duration:.2f}s window)"
        )

    async def _throttle_bandwidth(self, nbytes: int) -> None:
        """Serialize ``nbytes`` of write payload through the planned
        pipe ceiling: a shared token bucket (not per-op sleep), so N
        concurrent writes still drain at ``bandwidth_gbps`` aggregate
        and compressed payloads genuinely cost fewer pipe-seconds."""
        bw = self.plan.bandwidth_gbps
        if bw <= 0 or nbytes <= 0:
            return
        cost = nbytes / (bw * 1e9)
        st = self._state
        with st.lock:
            start = max(_mono(), st.bw_release)
            st.bw_release = start + cost
            release = st.bw_release
        delay = release - _mono()
        if delay > 0:
            telemetry.incr("faults.bandwidth_throttled")
            await asyncio.sleep(delay)

    def _check_wedge(self, kind: str) -> None:
        """SIGSTOP this process on the planned attempt of ``kind``: the
        whole process freezes (heartbeat pump and lease publisher
        included), so peers' liveness leases expire and survivors raise
        RankFailedError — a dead rank from their view, while the parent
        test keeps a SIGCONT/SIGKILL handle on the frozen pid."""
        plan, st = self.plan, self._state
        if plan.wedge is None or plan.wedge[0] != kind:
            return
        with st.lock:
            n = st.wedge_attempts.get(kind, 0) + 1
            st.wedge_attempts[kind] = n
        idx = plan.wedge[1]
        if idx != 0 and n != idx:
            return
        telemetry.incr(f"faults.wedged.{kind}")
        flight.record("fault_wedge", op=kind)
        # Flush the black box NOW: a frozen process never reaches its
        # next heartbeat flush, and the wedge breadcrumb is exactly
        # what the post-mortem needs.
        try:
            flight.recorder().maybe_flush(force=True)
        except Exception:
            logger.debug("pre-wedge flight flush failed", exc_info=True)
        logger.warning(
            "FaultPlan wedge=%s: SIGSTOPping pid %d", plan.wedge, os.getpid()
        )
        os.kill(os.getpid(), signal.SIGSTOP)

    def _check_preempt(self, kind: str) -> None:
        """Deliver a preemption NOTICE on the planned attempt of
        ``kind``: SIGTERM to this process now, SIGKILL ``grace_s``
        seconds later if it is still alive (a daemon timer — a process
        that exits within the grace implicitly cancels the kill). The
        handler the app installed on SIGTERM gets a real, bounded
        window to leave gracefully — the deterministic "spot instance
        reclaim" fault elastic-leave tests run on."""
        plan, st = self.plan, self._state
        if plan.preempt is None or plan.preempt[0] != kind:
            return
        with st.lock:
            if st.preempt_fired:
                return
            n = st.preempt_attempts.get(kind, 0) + 1
            st.preempt_attempts[kind] = n
            idx = plan.preempt[1]
            if idx != 0 and n != idx:
                return
            st.preempt_fired = True
        grace_s = plan.preempt[2]
        telemetry.incr("faults.preempt")
        flight.record("fault_preempt", op=kind, grace_s=grace_s)
        # Flush the black box NOW: the SIGTERM handler may exit the
        # process before the next heartbeat flush, and the preemption
        # breadcrumb is what the post-mortem needs to tell a graceful
        # leave from a silent death.
        try:
            flight.recorder().maybe_flush(force=True)
        except Exception:
            logger.debug("pre-preempt flight flush failed", exc_info=True)
        logger.warning(
            "FaultPlan preempt=%s: SIGTERM to pid %d (SIGKILL in %.1fs)",
            plan.preempt,
            os.getpid(),
            grace_s,
        )
        pid = os.getpid()

        def _hard_kill() -> None:
            logger.warning(
                "FaultPlan preempt grace expired: SIGKILLing pid %d", pid
            )
            os.kill(pid, signal.SIGKILL)

        timer = threading.Timer(grace_s, _hard_kill)
        timer.daemon = True
        timer.start()
        os.kill(pid, signal.SIGTERM)

    async def _pre(self, kind: str, path: str) -> bool:
        """Apply latency + injected stalls; return whether this attempt
        must fail."""
        self._check_outage(kind, path)
        self._check_wedge(kind)
        self._check_preempt(kind)
        inject, latency = self._decide(kind, path)
        if latency:
            telemetry.incr("faults.latency_injections")
            await asyncio.sleep(latency)
        stall = self._stall_seconds(kind)
        if stall:
            # The op is already in flight (the scheduler's op token is
            # held across this await), so the sleep is exactly the
            # no-forward-progress hang the watchdog must detect.
            telemetry.incr(f"faults.stalled.{kind}")
            telemetry.event("stall_injected", kind=kind, path=path, seconds=stall)
            flight.record(
                "fault_stall", op=kind, path=path, seconds=stall
            )
            await asyncio.sleep(stall)
        if inject:
            # Always-on counter + instant trace event: a chaos take's
            # persisted trace shows exactly which ops drew faults.
            telemetry.incr(f"faults.injected.{kind}")
            telemetry.event("fault_injected", kind=kind, path=path)
            flight.record("fault", op=kind, path=path)
        return inject

    # --- plugin interface -------------------------------------------------

    async def write(self, write_io: WriteIO) -> None:
        kind = self._kind_for("write", write_io.path)
        if await self._pre(kind, write_io.path):
            if self.plan.torn_writes and len(write_io.buf) > 0:
                keep = self._torn_len(len(write_io.buf))
                torn = memoryview(write_io.buf).cast("B")[:keep]
                try:
                    await self.inner.write(WriteIO(path=write_io.path, buf=torn))
                except Exception:
                    # tpusnap: waive=TPS004 the torn write itself may
                    # fail; the InjectedFaultError below raises either way
                    pass
                raise InjectedFaultError(
                    f"injected torn write: {keep}/{len(write_io.buf)} bytes "
                    f"of {write_io.path!r} persisted"
                )
            raise InjectedFaultError(f"injected write failure: {write_io.path!r}")
        await self._throttle_bandwidth(len(write_io.buf))
        await self.inner.write(write_io)
        self._record_success(kind)

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        kind = self._kind_for("write_atomic", write_io.path)
        if await self._pre(kind, write_io.path):
            # Never tear an atomic write: the wrapped plugin's contract is
            # that a failed write_atomic leaves no trace, and chaos must
            # not fabricate failures the real backend cannot produce.
            raise InjectedFaultError(
                f"injected write_atomic failure: {write_io.path!r}"
            )
        await self._throttle_bandwidth(len(write_io.buf))
        await self.inner.write_atomic(write_io, durable=durable)
        self._record_success(kind)

    async def read(self, read_io: ReadIO) -> None:
        if await self._pre("read", read_io.path):
            if self.plan.short_reads:
                # Deliver a seeded truncation of the real bytes, then fail
                # the op — simulating a connection dropped mid-transfer.
                trial = ReadIO(path=read_io.path, byte_range=read_io.byte_range)
                try:
                    await self.inner.read(trial)
                    data = trial.buf.getvalue()
                    import io as _io

                    read_io.buf = _io.BytesIO(data[: self._torn_len(len(data))])
                except Exception:
                    # tpusnap: waive=TPS004 the trial read may fail too;
                    # the InjectedFaultError below raises either way
                    pass
                raise InjectedFaultError(
                    f"injected short read: {read_io.path!r}"
                )
            raise InjectedFaultError(f"injected read failure: {read_io.path!r}")
        await self.inner.read(read_io)
        self._record_success("read")

    async def delete(self, path: str) -> None:
        kind = self._kind_for("delete", path)
        if await self._pre(kind, path):
            raise InjectedFaultError(f"injected delete failure: {path!r}")
        await self.inner.delete(path)
        self._record_success(kind)

    async def list_with_sizes(self):
        # fsck/gc's enumeration is a faultable op of its own kind, so
        # soaks can target lifecycle tooling (``crash_after_op=list:1``,
        # transient faults on listing) by name.
        if await self._pre("list", ""):
            raise InjectedFaultError("injected list failure")
        out = await self.inner.list_with_sizes()
        self._record_success("list")
        return out

    async def flush_created_dirs(self) -> None:
        await self.inner.flush_created_dirs()

    async def close(self) -> None:
        await self.inner.close()
