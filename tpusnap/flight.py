"""Black-box flight recorder: crash-surviving event log + forensic reader.

PRs 3/4/7 made a take crash-SAFE (journal/fsck), live-observable
(heartbeats) and attributable (telemetry/analyze) — but every one of
those persists its richest evidence at or after the commit barrier. The
one take an operator most needs to understand — the SIGKILLed, wedged
or aborted one — left only a journal marker and a stale heartbeat. This
module is the black box that survives the crash:

- **FlightRecorder** — an always-on, bounded, lock-light ring buffer of
  structured events: monotonic timestamp (plus a wall anchor recorded
  once per process so readers can map back), kind, op, small detail
  dict. Fed from the seams that already exist: telemetry span
  open/close and phase transitions, journal writes and blob-completion
  records, retry attempts, injected faults, barrier enter/exit, stall
  episodes, roofline probes. Recording is one lock'd ``deque.append``;
  memory and flush cost are O(ring), never O(take).

- **Crash persistence** — the ring is rewritten ATOMICALLY (temp +
  rename, like the progress sidecar) to two destinations at a bounded
  cadence: the destination sidecar ``.tpusnap/flight/rank_<k>.jsonl``
  (local-filesystem destinations; journal-exempt like the progress
  sidecar) and a local ``TPUSNAP_TELEMETRY_DIR`` copy keyed by a path
  digest (survives even when the destination is remote or the
  destination dir itself is lost). The flush piggybacks on the
  heartbeat pump plus ``atexit``/SIGTERM handlers — SIGKILL cannot be
  caught, so the flush cadence (default: the heartbeat interval) IS the
  documented loss bound: after any crash, at most one flush interval of
  events is missing.

- **Forensic reader** — :func:`load_flight_logs` /
  :func:`merge_timeline` / :func:`estimate_skew` /
  :func:`postmortem_verdict` power ``python -m tpusnap timeline``:
  all ranks' logs merged into one causally-ordered timeline using
  barrier-anchored clock-skew estimation (every rank logs the same
  barrier release; the reader aligns ranks on the shared anchors and
  reports the residual skew bound), plus a post-mortem verdict for torn
  paths: per-rank last event, in-flight op, last completed phase,
  bytes staged/written vs planned, journal.d completion evidence,
  stall episodes, and the missing-rank set.

Everything here is best-effort observability: a recorder or flush
failure can never fail a take, and the reader treats absent/partial
logs as evidence gaps, not errors.
"""

from __future__ import annotations

import atexit
import collections
import hashlib
import json
import logging
import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from .io_types import FLIGHT_DIR
from .knobs import (
    get_job_id,
    get_flight_flush_interval_s,
    get_flight_ring_size,
    get_telemetry_dir,
    is_flight_enabled,
)

logger = logging.getLogger(__name__)

# Wall-clock seam: the per-process wall anchor only (all event
# timestamps and flush throttling run on the monotonic clock); direct
# wall-clock CALLS are lint-forbidden here (TPS002) — only this bare
# reference is allowed.
_wall = time.time


def flight_rank_path(rank: int) -> str:
    """Snapshot-relative path of one rank's flight log."""
    return f"{FLIGHT_DIR}/rank_{rank}.jsonl"


def _path_digest(path: str) -> str:
    # Same normalization contract as progress._path_digest: every
    # spelling of one local destination digests identically.
    from .progress import local_root_of

    norm = path.rstrip("/")
    root = local_root_of(norm)
    if root is not None:
        norm = os.path.abspath(root)
    return hashlib.sha1(norm.encode("utf-8")).hexdigest()[:12]


def local_flight_dir(snapshot_path: str) -> str:
    """The local (TPUSNAP_TELEMETRY_DIR) copy of the flight logs for
    ``snapshot_path`` — the fallback the timeline reader consults when
    the destination itself carries none (remote backends, or a
    destination directory that was lost with the machine that held
    it)."""
    return os.path.join(
        get_telemetry_dir(), f"flight_{_path_digest(snapshot_path)}"
    )


# ---------------------------------------------------------------- recorder


class FlightRecorder:
    """Bounded ring of (monotonic_ts, kind, op, detail) events.

    One per process (see :func:`recorder`); always on unless
    ``TPUSNAP_FLIGHT=0``. The lock is a LEAF in the process lock order:
    nothing is called while it is held (lockwatch-clean by
    construction), and :meth:`record` never raises."""

    def __init__(self, ring_size: Optional[int] = None) -> None:
        self.enabled = is_flight_enabled()
        self._ring: collections.deque = collections.deque(
            maxlen=ring_size if ring_size is not None else get_flight_ring_size()
        )
        self._lock = threading.Lock()
        # Serializes concurrent flushers (pump thread vs end_take vs
        # the SIGTERM handler): both would otherwise pass the throttle
        # check and interleave writes into the SAME pid-keyed temp file
        # before renaming. Taken non-blocking — a contended flush means
        # one is already in progress with near-identical content, and a
        # signal handler interrupting this very thread's flush must
        # not self-deadlock.
        self._flush_lock = threading.Lock()
        self.events_total = 0
        # Wall/monotonic anchor pair: readers map an event's monotonic
        # timestamp to wall time via wall_anchor + (t - mono_anchor).
        self.mono_anchor = time.monotonic()
        self.wall_anchor = _wall()
        # Per-take flush destinations (configure_take).
        self.rank = 0
        self.take_id: Optional[str] = None
        self.world_size = 1
        self._sidecar_dir: Optional[str] = None
        self._copy_dir: Optional[str] = None
        self._flush_interval_s = get_flight_flush_interval_s()
        self._last_flush_t: Optional[float] = None
        self._context: Dict[str, Any] = {}
        self.flushes = 0  # tests assert the throttle

    # --- recording ------------------------------------------------------

    def record(self, kind: str, op: Optional[str] = None, **detail: Any) -> None:
        """Append one event; cheap (one lock'd deque append) and
        non-raising — the recorder must never fail the code it
        observes."""
        if not self.enabled:
            return
        try:
            t = time.monotonic()
            with self._lock:
                self._ring.append((t, kind, op, detail or None))
                self.events_total += 1
        except Exception:
            pass

    def record_nowait(self, kind: str) -> bool:
        """Signal-handler-safe record: a handler runs on whatever thread
        the signal interrupted — if THAT frame holds the ring lock, a
        blocking acquire would self-deadlock the non-reentrant lock, so
        try-acquire and drop the event when contended (the flush that
        follows tells the story either way)."""
        if not self.enabled:
            return False
        try:
            t = time.monotonic()
            if not self._lock.acquire(False):
                return False
            try:
                self._ring.append((t, kind, None, None))
                self.events_total += 1
            finally:
                self._lock.release()
            return True
        except Exception:
            return False

    def snapshot_events(self) -> list:
        """The current ring as event dicts in the flushed-line shape
        (``{"t", "k", "op"?, ...detail}``) — the in-process read API
        tests and tooling use without round-tripping a sidecar."""
        with self._lock:
            events = list(self._ring)
        out = []
        for t, kind, op, detail in events:
            ev: Dict[str, Any] = {"t": round(t, 6), "k": kind}
            if op is not None:
                ev["op"] = op
            if detail:
                ev.update(detail)
            out.append(ev)
        return out

    def mark_take_start(self) -> None:
        """Reset the ring for a new take (called from
        ``telemetry.begin_take``, before the first phase event): the
        sidecar is a per-take artifact, so a SIGKILLed take's flushed
        log — and the verdict's stall/eviction accounting — must not
        carry the previous takes' events."""
        with self._lock:
            self._ring.clear()
            self.events_total = 0

    # --- flush ----------------------------------------------------------

    def configure_take(
        self,
        rank: int,
        take_id: str,
        world_size: int,
        path: str,
        local_root: Optional[str],
    ) -> None:
        """Arm the per-take flush destinations (called at take begin,
        after the take_id and coalesced path are agreed). Re-samples the
        knob so overrides apply per take, installs the exit handlers
        once, and resets the flush throttle so the first pump tick
        flushes immediately."""
        self.enabled = is_flight_enabled()
        if not self.enabled:
            self._sidecar_dir = self._copy_dir = None
            return
        self.rank = rank
        self.take_id = take_id
        self.world_size = world_size
        self._flush_interval_s = get_flight_flush_interval_s()
        self._sidecar_dir = (
            os.path.join(local_root, FLIGHT_DIR) if local_root else None
        )
        try:
            self._copy_dir = local_flight_dir(path)
        except Exception:
            self._copy_dir = None
        self._last_flush_t = None
        self._context = {}
        self.record("take_begin", op=take_id[:8], world_size=world_size)
        _install_exit_handlers()

    def set_context(self, context: Dict[str, Any]) -> None:
        """Live progress context carried in the flushed header (phase,
        in-flight ops, bytes planned/staged/written) — what the
        post-mortem verdict reads for "what was this rank doing when it
        died". The heartbeat pump refreshes it every tick."""
        self._context = context

    def maybe_flush(self, force: bool = False) -> bool:
        """Flush at most once per interval (the SIGKILL loss bound);
        ``force`` for the final commit/abort/exit flush. Never raises.
        A periodic flush already in progress on another thread is
        skipped, not waited for — its content is near-identical and the
        cadence bound covers the gap. A ``force`` flush (the terminal
        commit/abort/exit state must land) waits briefly instead, with
        a timeout so a signal handler interrupting THIS thread's
        in-progress flush can never self-deadlock."""
        if not self.enabled or (
            self._sidecar_dir is None and self._copy_dir is None
        ):
            return False
        if not self._flush_lock.acquire(force, 2.0 if force else -1):
            return False
        try:
            now = time.monotonic()
            if (
                not force
                and self._last_flush_t is not None
                and now - self._last_flush_t < self._flush_interval_s
            ):
                return False
            self._last_flush_t = now
            try:
                payload = self._serialize(now)
            except Exception:
                logger.debug("flight serialize failed", exc_info=True)
                return False
            wrote = False
            for d in (self._sidecar_dir, self._copy_dir):
                if d is None:
                    continue
                try:
                    os.makedirs(d, exist_ok=True)
                    out = os.path.join(d, f"rank_{self.rank}.jsonl")
                    tmp = f"{out}.tmp.{os.getpid()}"
                    with open(tmp, "w") as f:
                        f.write(payload)
                    os.replace(tmp, out)
                    wrote = True
                except Exception:
                    logger.debug(
                        "flight flush to %r failed", d, exc_info=True
                    )
            if wrote:
                self.flushes += 1
            return wrote
        finally:
            self._flush_lock.release()

    def end_take(self, state: str) -> None:
        """Record the terminal event and force the final flush. The
        destinations stay armed until the next take so the atexit flush
        still lands the tail of THIS take's events."""
        self.record("take_end", op=state)
        self._context = dict(self._context, state=state)
        self.maybe_flush(force=True)

    def _serialize(self, now: float) -> str:
        # Timeout acquire, mirroring _flush_lock: a SIGTERM handler's
        # forced flush may run on a thread whose interrupted frame
        # holds the ring lock — bail (the caller swallows) instead of
        # self-deadlocking; the previous flush is at most one interval
        # stale.
        if not self._lock.acquire(timeout=2.0):
            raise RuntimeError("flight ring lock contended")
        try:
            events = list(self._ring)
            total = self.events_total
        finally:
            self._lock.release()
        header = {
            "k": "meta",
            "v": 1,
            "rank": self.rank,
            "job_id": get_job_id(),
            "take_id": self.take_id,
            "world_size": self.world_size,
            "pid": os.getpid(),
            "wall_anchor": self.wall_anchor,
            "mono_anchor": self.mono_anchor,
            "flush_mono": now,
            "events_total": total,
            "dropped": max(0, total - len(events)),
            "context": self._context,
        }
        lines = [json.dumps(header, default=str)]
        for t, kind, op, detail in events:
            ev: Dict[str, Any] = {"t": round(t, 6), "k": kind}
            if op is not None:
                ev["op"] = op
            if detail:
                ev.update(detail)
            lines.append(json.dumps(ev, default=str))
        return "\n".join(lines) + "\n"


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def recorder() -> FlightRecorder:
    """The process-global flight recorder (created on first use)."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            rec = _recorder
            if rec is None:
                rec = _recorder = FlightRecorder()
    return rec


def record(kind: str, op: Optional[str] = None, **detail: Any) -> None:
    """Module-level seam every instrumented layer calls: append one
    event to the process ring. Cheap and never raises."""
    global _recorder
    rec = _recorder
    if rec is None:
        # Creation is rare (once per process); record() itself stays a
        # single attribute check + append afterwards.
        rec = recorder()
    rec.record(kind, op, **detail)


def reset_for_tests(ring_size: Optional[int] = None) -> FlightRecorder:
    """Replace the process recorder (test aid; production never calls)."""
    global _recorder
    with _recorder_lock:
        _recorder = FlightRecorder(ring_size=ring_size)
    return _recorder


# ------------------------------------------------------- exit persistence

_handlers_installed = False


def _flush_at_exit() -> None:
    rec = _recorder
    if rec is not None:
        rec.record("process_exit")
        rec.maybe_flush(force=True)


def _install_exit_handlers() -> None:
    """atexit + SIGTERM: flush the ring on every CATCHABLE exit.
    SIGKILL cannot be caught by design — that is why the periodic flush
    cadence, not a handler, is the loss bound. Installed once, lazily,
    at the first take (not at import: a library must not take over
    process signal handling just by being imported). The flush-then-die
    SIGTERM handler is installed ONLY when SIGTERM still has its
    default disposition — an application that ignores or handles
    SIGTERM itself keeps its semantics untouched, and relies on the
    periodic cadence (plus atexit on clean exits) instead."""
    global _handlers_installed
    if _handlers_installed:
        return
    _handlers_installed = True
    atexit.register(_flush_at_exit)
    try:
        prev = signal.getsignal(signal.SIGTERM)
        if prev is not signal.SIG_DFL:
            # The application (or a C extension — getsignal() returns
            # None then) already decided what SIGTERM means: ignoring
            # it, or handling it itself. An observability library must
            # not change process-lifetime semantics, so only the
            # default-death case gets the flush-then-die handler; the
            # rest rely on the periodic cadence (and atexit, when the
            # app's own handling exits cleanly).
            return

        def _on_sigterm(signum, frame):
            rec = _recorder
            if rec is not None:
                # record_nowait + the timeout acquires inside
                # maybe_flush: the handler may be interrupting the very
                # frame that holds a recorder lock — never block on one.
                rec.record_nowait("sigterm")
                rec.maybe_flush(force=True)
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError, RuntimeError):
        # Not the main thread (or an embedded interpreter): atexit still
        # covers normal exits; SIGTERM then behaves like SIGKILL and the
        # cadence bound applies.
        logger.debug("flight SIGTERM handler not installed", exc_info=True)


# ---------------------------------------------------------------- reader


def parse_flight_log(text: str) -> Optional[Dict[str, Any]]:
    """One rank's flushed log → ``{"meta": {...}, "events": [...]}``.
    Tolerant: unparseable lines are skipped (the writer renames
    atomically, but a reader must survive anything)."""
    meta: Optional[Dict[str, Any]] = None
    events: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except Exception:
            continue
        if not isinstance(doc, dict):
            continue
        if doc.get("k") == "meta":
            meta = doc
        else:
            events.append(doc)
    if meta is None and not events:
        return None
    return {"meta": meta or {}, "events": events}


def load_flight_logs(
    path: str,
    files: Optional[Dict[str, int]] = None,
    resources: Optional[Tuple[Any, Any]] = None,
) -> Dict[int, Dict[str, Any]]:
    """All ranks' flight logs for ``path``: the destination sidecar
    first (read through the storage plugin, so any listable backend
    works), falling back to the local TPUSNAP_TELEMETRY_DIR copies.
    Returns ``{rank: {"meta", "events"}}``; empty when no flight data
    exists anywhere."""
    import asyncio

    from .io_types import ReadIO

    out: Dict[int, Dict[str, Any]] = {}
    owns = resources is None
    # A caller-provided listing with zero flight entries already proves
    # the destination carries none (flight sidecars are written by
    # DIRECT file I/O into local destinations only, and a backend that
    # cannot list has none either) — skip the plugin entirely and go
    # straight to the local-copy fallback.
    known_empty = files is not None and not any(
        p.startswith(FLIGHT_DIR + "/") for p in files
    )
    event_loop = storage = None
    try:
        if not known_empty:
            if owns:
                from .storage_plugin import (
                    url_to_storage_plugin_in_event_loop,
                )

                event_loop = asyncio.new_event_loop()
                storage = url_to_storage_plugin_in_event_loop(
                    path, event_loop
                )
            else:
                event_loop, storage = resources
            if files is None:
                try:
                    files = storage.sync_list_with_sizes(event_loop)
                except Exception:
                    files = None
        names = (
            [p for p in files if p.startswith(FLIGHT_DIR + "/")]
            if files is not None
            else []
        )
        for name in sorted(names):
            base = name.rsplit("/", 1)[-1]
            if not (base.startswith("rank_") and base.endswith(".jsonl")):
                continue
            try:
                rank = int(base[len("rank_") : -len(".jsonl")])
            except ValueError:
                continue
            read_io = ReadIO(path=name)
            try:
                storage.sync_read(read_io, event_loop)
                doc = parse_flight_log(
                    read_io.buf.getvalue().decode("utf-8", errors="replace")
                )
            except Exception:
                continue
            if doc is not None:
                out[rank] = doc
    except Exception:
        logger.debug("flight sidecar read failed", exc_info=True)
    finally:
        if owns:
            if storage is not None:
                try:
                    storage.sync_close(event_loop)
                except Exception:
                    logger.debug("flight plugin close failed", exc_info=True)
            if event_loop is not None:
                event_loop.close()
    if out:
        return out
    # Fallback: the local copy dir (remote destinations, or a destroyed
    # destination directory).
    try:
        cdir = local_flight_dir(path)
        for name in sorted(os.listdir(cdir)):
            if not (name.startswith("rank_") and name.endswith(".jsonl")):
                continue
            try:
                rank = int(name[len("rank_") : -len(".jsonl")])
                with open(os.path.join(cdir, name), "r") as f:
                    doc = parse_flight_log(f.read())
            except Exception:
                continue
            if doc is not None:
                out[rank] = doc
    except OSError:
        pass
    return out


def _event_wall(meta: Dict[str, Any], t: float) -> float:
    return float(meta.get("wall_anchor", 0.0)) + (
        t - float(meta.get("mono_anchor", 0.0))
    )


# Barrier-release event kinds usable as cross-rank clock anchors: every
# rank records the SAME op string for the same barrier, at (nearly) the
# same instant — release propagation is bounded by the polling barrier's
# 50 ms poll, which is the floor of the reported skew bound.
_ANCHOR_KINDS = ("barrier_exit",)


def estimate_skew(
    logs: Dict[int, Dict[str, Any]],
) -> Dict[int, Dict[str, Any]]:
    """Barrier-anchored clock-skew estimate per rank, relative to the
    lowest-numbered rank with data: for every shared barrier anchor the
    two ranks both logged, the wall-time delta at its release is a skew
    sample; the median is the offset (ADDED to the rank's wall times to
    align them) and the max deviation from it is the ± bound. Ranks
    without shared anchors get offset 0 and ``anchors == 0`` — their
    ordering against other ranks is wall-clock-trust only."""
    if not logs:
        return {}
    ref_rank = min(logs)
    ref = logs[ref_rank]

    def anchor_walls(doc: Dict[str, Any]) -> Dict[str, float]:
        meta = doc.get("meta") or {}
        out: Dict[str, float] = {}
        for ev in doc.get("events") or []:
            if ev.get("k") in _ANCHOR_KINDS and ev.get("op"):
                # Last release of a given anchor wins (anchors are
                # sequence-numbered, so repeats only happen on ring
                # eviction edge cases).
                out[str(ev["op"])] = _event_wall(meta, float(ev["t"]))
        return out

    ref_anchors = anchor_walls(ref)
    skew: Dict[int, Dict[str, Any]] = {
        ref_rank: {"offset_s": 0.0, "bound_s": 0.0, "anchors": None}
    }
    for rank, doc in logs.items():
        if rank == ref_rank:
            continue
        theirs = anchor_walls(doc)
        shared = sorted(set(ref_anchors) & set(theirs))
        if not shared:
            skew[rank] = {"offset_s": 0.0, "bound_s": None, "anchors": 0}
            continue
        deltas = sorted(ref_anchors[a] - theirs[a] for a in shared)
        offset = deltas[len(deltas) // 2]
        bound = max(abs(d - offset) for d in deltas)
        skew[rank] = {
            "offset_s": round(offset, 6),
            "bound_s": round(bound, 6),
            "anchors": len(shared),
        }
    return skew


def merge_timeline(
    logs: Dict[int, Dict[str, Any]],
    skew: Optional[Dict[int, Dict[str, Any]]] = None,
) -> List[Dict[str, Any]]:
    """All ranks' events merged into one causally-ordered list. Each
    event gains ``rank`` and ``wall`` (the skew-aligned wall time);
    ordering is by aligned wall time, tie-broken by rank."""
    skew = skew if skew is not None else estimate_skew(logs)
    merged: List[Dict[str, Any]] = []
    for rank, doc in logs.items():
        meta = doc.get("meta") or {}
        offset = (skew.get(rank) or {}).get("offset_s") or 0.0
        for ev in doc.get("events") or []:
            try:
                wall = _event_wall(meta, float(ev["t"])) + offset
            except Exception:
                continue
            out = dict(ev)
            out["rank"] = rank
            out["wall"] = wall
            merged.append(out)
    merged.sort(key=lambda e: (e["wall"], e["rank"]))
    return merged


def _journal_evidence(
    files: Optional[Dict[str, int]],
    path: str,
    resources: Optional[Tuple[Any, Any]] = None,
) -> Dict[int, Dict[str, Any]]:
    """Per-rank blob-completion evidence from ``journal.d``: how many
    blobs each rank PROVABLY finished writing, and their bytes —
    cross-checked against the listing like salvage does (a record whose
    blob is gone or resized does not count as written evidence)."""
    import asyncio

    from .io_types import JOURNAL_RECORDS_DIR, ReadIO

    out: Dict[int, Dict[str, Any]] = {}
    if files is None:
        return out
    rec_files = sorted(
        p for p in files if p.startswith(JOURNAL_RECORDS_DIR + "/")
    )
    if not rec_files:
        return out
    owns = resources is None
    event_loop = storage = None
    try:
        if owns:
            from .storage_plugin import url_to_storage_plugin_in_event_loop

            event_loop = asyncio.new_event_loop()
            storage = url_to_storage_plugin_in_event_loop(path, event_loop)
        else:
            event_loop, storage = resources
        for rec_path in rec_files:
            base = rec_path.rsplit("/", 1)[-1]
            if not base.startswith("rank_") or ".tmp." in base:
                continue
            try:
                rank = int(base[len("rank_") :])
            except ValueError:
                continue
            read_io = ReadIO(path=rec_path)
            try:
                storage.sync_read(read_io, event_loop)
                recs = json.loads(read_io.buf.getvalue().decode("utf-8"))
            except Exception:
                continue
            if not isinstance(recs, dict):
                continue
            blobs = bytes_done = 0
            for loc, rec in recs.items():
                try:
                    n = int(rec[0])
                except (IndexError, TypeError, ValueError):
                    continue
                if files.get(loc) == n:
                    blobs += 1
                    bytes_done += n
            out[rank] = {"blobs_completed": blobs, "bytes_completed": bytes_done}
    except Exception:
        logger.debug("journal evidence read failed", exc_info=True)
    finally:
        if owns:
            if storage is not None:
                try:
                    storage.sync_close(event_loop)
                except Exception:
                    logger.debug("flight plugin close failed", exc_info=True)
            if event_loop is not None:
                event_loop.close()
    return out


def postmortem_verdict(
    path: str,
    state: str,
    logs: Dict[int, Dict[str, Any]],
    world_size: Optional[int] = None,
    journal_evidence: Optional[Dict[int, Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The forensic verdict for a torn (or otherwise uncommitted) path:
    per rank — the last event, the flushed live context (last completed
    phase, in-flight op, bytes staged/written vs planned), the
    journal.d completion evidence, stall episodes — plus the
    missing-rank set (ranks the take's world size expected but no
    flight log survived for: SIGKILLed before their first flush, a
    remote destination, or a host whose disk died with it)."""
    journal_evidence = journal_evidence or {}
    if world_size is None:
        sizes = [
            (d.get("meta") or {}).get("world_size") for d in logs.values()
        ]
        sizes = [s for s in sizes if isinstance(s, int)]
        world_size = max(sizes) if sizes else (max(logs) + 1 if logs else 0)
    ranks: Dict[int, Dict[str, Any]] = {}
    for rank, doc in sorted(logs.items()):
        meta = doc.get("meta") or {}
        events = doc.get("events") or []
        ctx = meta.get("context") or {}
        last = events[-1] if events else None
        flush_mono = meta.get("flush_mono")
        r: Dict[str, Any] = {
            "last_event": (
                {
                    "k": last.get("k"),
                    "op": last.get("op"),
                    "wall": _event_wall(meta, float(last["t"])),
                    # How stale the tail can be: the flush wrote this
                    # log flush_age seconds after the last event — and
                    # up to one flush interval of NEWER events died with
                    # the process.
                    "flush_age_s": (
                        round(float(flush_mono) - float(last["t"]), 3)
                        if flush_mono is not None
                        else None
                    ),
                }
                if last is not None
                else None
            ),
            "phase": ctx.get("phase"),
            "inflight_op": ctx.get("op"),
            "inflight_ops": ctx.get("ops"),
            "state": ctx.get("state", "running"),
            "bytes_planned": ctx.get("bytes_planned"),
            "bytes_staged": ctx.get("bytes_staged"),
            "bytes_written": ctx.get("bytes_written"),
            "percent": ctx.get("percent"),
            "stall_episodes": sum(
                1 for e in events if e.get("k") == "stall"
            ),
            # Peers THIS rank's liveness monitor declared dead (lease
            # expired) — the black box's dead-vs-slow distinction.
            "dead_ranks_seen": sorted(
                {
                    e.get("rank")
                    for e in events
                    if e.get("k") == "rank_dead"
                    and isinstance(e.get("rank"), int)
                }
            )
            or None,
            # Ranks observed announcing a graceful departure (a
            # ``rank_left`` event — their own, or a peer's observation):
            # LEFT, not DEAD, in every rendering.
            "left_ranks_seen": sorted(
                {
                    e.get("rank")
                    for e in events
                    if e.get("k") == "rank_left"
                    and isinstance(e.get("rank"), int)
                }
            )
            or None,
            "events": len(events),
            "dropped": meta.get("dropped", 0),
            "take_id": meta.get("take_id"),
        }
        if rank in journal_evidence:
            r["journal"] = journal_evidence[rank]
        ranks[rank] = r
    missing = sorted(set(range(world_size)) - set(logs))
    # The union of every survivor's lease-expiry observations: the
    # ranks the take DIED on, as opposed to ranks whose log merely
    # never flushed (missing_ranks covers those too).
    dead: set = set()
    left: set = set()
    for r in ranks.values():
        dead.update(r.get("dead_ranks_seen") or ())
        left.update(r.get("left_ranks_seen") or ())
    # A rank that announced departure before its lease went stale LEFT;
    # it must never be reported dead (the whole point of the `left`
    # lease state).
    dead -= left
    return {
        "path": path,
        "state": state,
        "world_size": world_size,
        "ranks": ranks,
        "missing_ranks": missing,
        "dead_ranks": sorted(dead),
        "left_ranks": sorted(left),
        "stall_episodes": sum(
            r["stall_episodes"] for r in ranks.values()
        ),
    }


def make_tick_hook(
    rec: FlightRecorder,
) -> Callable[[Optional[Dict[str, Any]]], None]:
    """The heartbeat pump's flush hook: refresh the live context from
    the pump's progress record (when it built one this tick) and run
    the throttled flush. Never raises."""

    def hook(record_ctx: Optional[Dict[str, Any]]) -> None:
        try:
            if record_ctx is not None:
                rec.set_context(
                    {
                        k: record_ctx.get(k)
                        for k in (
                            "state",
                            "phase",
                            "op",
                            "ops",
                            "bytes_planned",
                            "bytes_staged",
                            "bytes_written",
                            "percent",
                        )
                    }
                )
            rec.maybe_flush()
        except Exception:
            logger.debug("flight tick hook failed", exc_info=True)

    return hook
