"""Typed manifest model describing everything persisted in a snapshot.

TPU-native counterpart of the reference's manifest
(/root/reference/torchsnapshot/manifest.py:28-329). Same taxonomy:

- ``TensorEntry``     — one dense array blob (location, serializer, dtype,
                        shape, replicated flag, optional byte range when the
                        blob lives inside a batched slab).
- ``ShardedEntry``    — an array sharded over a device mesh; a list of
                        ``Shard{offsets, sizes, tensor}``. In JAX this covers
                        DP/FSDP/TP/SP/EP uniformly: any
                        ``jax.sharding.NamedSharding`` reduces to per-shard
                        offsets/sizes in the global shape.
- ``ChunkedTensorEntry`` — one large array split into ≤max_chunk_size chunks
                        along dim 0 for pipelined DtoH/IO.
- ``ObjectEntry``     — arbitrary pickled object blob.
- ``PrimitiveEntry``  — int/str/bool/float/bytes inlined into the metadata
                        (floats bit-exact via base64-packed C double, same
                        trick as reference manifest.py:187-270).
- ``DictEntry`` / ``ListEntry`` / ``OrderedDictEntry`` — containers, so the
  original nesting can be rebuilt on restore.

``SnapshotMetadata`` is serialized as JSON (a subset of YAML — same speed
trick as reference manifest.py:283-289) and parsed with json-first,
yaml-fallback.
"""

from __future__ import annotations

import base64
import json
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import yaml

Manifest = Dict[str, "Entry"]


class MetadataError(RuntimeError):
    """The ``.snapshot_metadata`` file is torn or bit-rotted: it fails
    its self-checksum, is not valid UTF-8, or does not parse. Raised
    instead of a bare JSON/Unicode traceback so operators see a
    storage-integrity verdict, not a parser internals dump."""


@dataclass
class Entry:
    """Base for all manifest entries; ``type`` is the tagged-union key."""

    type: str


@dataclass
class TensorEntry(Entry):
    location: str
    serializer: str
    dtype: str
    shape: List[int]
    replicated: bool
    byte_range: Optional[List[int]] = None  # [start, end) within location
    # "<algo>:<8-hex>" over this entry's serialized bytes (its byte_range
    # within location, or the whole blob). Recorded at stage time; verified
    # on read unless TPUSNAP_DISABLE_CHECKSUM=1. Beyond the reference,
    # which cannot detect a flipped bit on restore.
    checksum: Optional[str] = None
    # Tile-grain checksums for memory-budgeted partial reads: the blob is
    # hashed ONCE at stage time in row-tiles of ``tile_rows`` rows; the
    # whole-blob ``checksum`` is derived by CRC combine. Budget-tiled
    # reads align to these boundaries and verify each read range by
    # combining the covered tiles' values — so the huge-tensor-under-
    # budget path detects corruption too, at no extra hash pass anywhere.
    tile_rows: Optional[int] = None
    tile_checksums: Optional[List[str]] = None
    # Second, independent hash backing incremental-dedup equality
    # ("<algo>:<16-hex>", algo xxh64 native / sha256-64 fallback). A
    # single 32-bit CRC leaves a ~2^-32 silent-collision channel per
    # blob-take; dedup of a tile-LESS blob requires BOTH the CRC and
    # this value to match. Tiled blobs dedup whole on their multiple
    # independent tile CRCs; ``tile_dedup_hashes`` (recorded on
    # incremental takes) additionally gives each TILE a 64-bit value so
    # tile-grain dedup decisions are equally strong.
    dedup_hash: Optional[str] = None
    tile_dedup_hashes: Optional[List[str]] = None
    # Fused tile compression (tpusnap.compress). When ``codec`` is set
    # the STORED blob is the concatenation of independently compressed
    # checksum tiles: ``comp_tile_sizes[i]`` is tile i's stored size
    # (a tile stored raw has size == its uncompressed tile size — the
    # codec never stores a same-size compressed stream), tile i starts
    # at sum(comp_tile_sizes[:i]) within the blob, and
    # ``uncompressed_nbytes`` is the logical payload size. ALL recorded
    # checksums/dedup hashes of a codec entry — ``checksum``,
    # ``tile_checksums``, ``dedup_hash``, ``tile_dedup_hashes`` — are
    # over the STORED (compressed) bytes, so the journal/salvage/
    # upload-journal dual-hash evidence rule and scrub hold unchanged.
    # Absent on uncompressed entries; old snapshots parse identically.
    # ``uncompressed_dedup_hash`` (dedup-recording takes only) is the
    # ONE exception to the stored-bytes rule: a dual hash
    # ("<crc-algo>:<crc32>+xxh64:<xxh64>") of the RAW payload, recorded
    # so the NEXT incremental take can prove an unchanged blob with a
    # multi-GB/s hash pass instead of re-running the codec — the codec
    # is deterministic, so equal raw bytes imply equal stored bytes.
    # Never used to verify storage; purely write-skip evidence.
    codec: Optional[str] = None
    uncompressed_nbytes: Optional[int] = None
    comp_tile_sizes: Optional[List[int]] = None
    uncompressed_dedup_hash: Optional[str] = None

    def __init__(
        self,
        location: str,
        serializer: str,
        dtype: str,
        shape: Sequence[int],
        replicated: bool,
        byte_range: Optional[Sequence[int]] = None,
        checksum: Optional[str] = None,
        tile_rows: Optional[int] = None,
        tile_checksums: Optional[Sequence[str]] = None,
        dedup_hash: Optional[str] = None,
        tile_dedup_hashes: Optional[Sequence[str]] = None,
        codec: Optional[str] = None,
        uncompressed_nbytes: Optional[int] = None,
        comp_tile_sizes: Optional[Sequence[int]] = None,
        uncompressed_dedup_hash: Optional[str] = None,
    ) -> None:
        super().__init__(type="Tensor")
        self.location = location
        self.serializer = serializer
        self.dtype = dtype
        self.shape = list(shape)
        self.replicated = replicated
        self.byte_range = list(byte_range) if byte_range is not None else None
        self.checksum = checksum
        self.tile_rows = tile_rows
        self.tile_checksums = (
            list(tile_checksums) if tile_checksums is not None else None
        )
        self.dedup_hash = dedup_hash
        self.tile_dedup_hashes = (
            list(tile_dedup_hashes) if tile_dedup_hashes is not None else None
        )
        self.codec = codec
        self.uncompressed_nbytes = uncompressed_nbytes
        self.comp_tile_sizes = (
            list(comp_tile_sizes) if comp_tile_sizes is not None else None
        )
        self.uncompressed_dedup_hash = uncompressed_dedup_hash

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TensorEntry":
        return cls(
            location=d["location"],
            serializer=d["serializer"],
            dtype=d["dtype"],
            shape=d["shape"],
            replicated=d["replicated"],
            byte_range=d.get("byte_range"),
            checksum=d.get("checksum"),
            tile_rows=d.get("tile_rows"),
            tile_checksums=d.get("tile_checksums"),
            dedup_hash=d.get("dedup_hash"),
            tile_dedup_hashes=d.get("tile_dedup_hashes"),
            codec=d.get("codec"),
            uncompressed_nbytes=d.get("uncompressed_nbytes"),
            comp_tile_sizes=d.get("comp_tile_sizes"),
            uncompressed_dedup_hash=d.get("uncompressed_dedup_hash"),
        )


@dataclass
class Shard:
    offsets: List[int]
    sizes: List[int]
    tensor: TensorEntry

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Shard":
        return cls(
            offsets=list(d["offsets"]),
            sizes=list(d["sizes"]),
            tensor=TensorEntry.from_dict(d["tensor"]),
        )


@dataclass
class ShardedEntry(Entry):
    shards: List[Shard]
    dtype: str = ""
    shape: List[int] = field(default_factory=list)

    def __init__(
        self,
        shards: List[Shard],
        dtype: str = "",
        shape: Optional[Sequence[int]] = None,
    ) -> None:
        super().__init__(type="Sharded")
        self.shards = shards
        self.dtype = dtype or (shards[0].tensor.dtype if shards else "")
        if shape is not None:
            self.shape = list(shape)
        elif shards:
            # Global shape inferred as the max extent covered by any shard.
            ndim = len(shards[0].offsets)
            self.shape = [
                max(s.offsets[d] + s.sizes[d] for s in shards) for d in range(ndim)
            ]
        else:
            self.shape = []

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ShardedEntry":
        return cls(
            shards=[Shard.from_dict(s) for s in d["shards"]],
            dtype=d.get("dtype", ""),
            shape=d.get("shape"),
        )


# A chunk of a ChunkedTensorEntry has the same (offsets, sizes, tensor)
# structure as a shard; reuse the type (reference manifest.py:113-116 types
# chunks as List[Shard] for the same reason).
Chunk = Shard


@dataclass
class ChunkedTensorEntry(Entry):
    dtype: str
    shape: List[int]
    chunks: List[Chunk]
    replicated: bool

    def __init__(
        self,
        dtype: str,
        shape: Sequence[int],
        chunks: List[Chunk],
        replicated: bool,
    ) -> None:
        super().__init__(type="ChunkedTensor")
        self.dtype = dtype
        self.shape = list(shape)
        self.chunks = chunks
        self.replicated = replicated

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ChunkedTensorEntry":
        return cls(
            dtype=d["dtype"],
            shape=d["shape"],
            chunks=[Chunk.from_dict(c) for c in d["chunks"]],
            replicated=d["replicated"],
        )


@dataclass
class ObjectEntry(Entry):
    location: str
    serializer: str
    obj_type: str
    replicated: bool
    nbytes: Optional[int] = None  # serialized size; drives read memory budget
    checksum: Optional[str] = None  # "<algo>:<8-hex>" (see TensorEntry)
    dedup_hash: Optional[str] = None  # "<algo>:<16-hex>" (see TensorEntry)

    def __init__(
        self,
        location: str,
        serializer: str,
        obj_type: str,
        replicated: bool,
        nbytes: Optional[int] = None,
        checksum: Optional[str] = None,
        dedup_hash: Optional[str] = None,
    ) -> None:
        super().__init__(type="object")
        self.location = location
        self.serializer = serializer
        self.obj_type = obj_type
        self.replicated = replicated
        self.nbytes = nbytes
        self.checksum = checksum
        self.dedup_hash = dedup_hash

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ObjectEntry":
        return cls(
            location=d["location"],
            serializer=d["serializer"],
            obj_type=d["obj_type"],
            replicated=d["replicated"],
            nbytes=d.get("nbytes"),
            checksum=d.get("checksum"),
            dedup_hash=d.get("dedup_hash"),
        )


@dataclass
class ListEntry(Entry):
    def __init__(self) -> None:
        super().__init__(type="list")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ListEntry":
        return cls()


@dataclass
class TupleEntry(Entry):
    """JAX extension: optax/flax pytrees are full of tuples/NamedTuples;
    the reference would have pickled them whole (io_preparer.py:125). We
    flatten them like lists and rebuild a tuple on inflate."""

    def __init__(self) -> None:
        super().__init__(type="tuple")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TupleEntry":
        return cls()


@dataclass
class DictEntry(Entry):
    keys: List[Union[str, int]]

    def __init__(self, keys: List[Union[str, int]]) -> None:
        super().__init__(type="dict")
        self.keys = keys

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DictEntry":
        return cls(keys=d["keys"])


@dataclass
class OrderedDictEntry(Entry):
    keys: List[Union[str, int]]

    def __init__(self, keys: List[Union[str, int]]) -> None:
        super().__init__(type="OrderedDict")
        self.keys = keys

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OrderedDictEntry":
        return cls(keys=d["keys"])


@dataclass
class PrimitiveEntry(Entry):
    """int/float/bool/str/bytes inlined directly into the metadata.

    Floats are stored bit-exactly (base64 of the IEEE-754 double) WITH a
    human-readable companion value so manifests stay auditable — the
    reference stores both for the same reason (manifest.py:221-245).
    Restore always uses the bit-exact form.
    """

    dtype: str
    layout: str
    serialized_value: str
    replicated: bool
    readable: Optional[str] = None

    def __init__(
        self,
        dtype: str,
        layout: str,
        serialized_value: str,
        replicated: bool,
        readable: Optional[str] = None,
    ) -> None:
        super().__init__(type="primitive")
        self.dtype = dtype
        self.layout = layout
        self.serialized_value = serialized_value
        self.replicated = replicated
        self.readable = readable

    SUPPORTED_TYPES = (int, float, bool, str, bytes)

    @classmethod
    def supported(cls, obj: Any) -> bool:
        # bool is a subclass of int; keep explicit for clarity.
        return type(obj) in cls.SUPPORTED_TYPES

    @classmethod
    def from_object(cls, obj: Any, replicated: bool = False) -> "PrimitiveEntry":
        t = type(obj)
        if t is int:
            return cls("int", "text", str(obj), replicated)
        if t is bool:
            return cls("bool", "text", str(obj), replicated)
        if t is str:
            return cls("str", "text", obj, replicated)
        if t is float:
            packed = base64.b64encode(struct.pack("<d", obj)).decode("ascii")
            return cls("float", "b64_le_f64", packed, replicated, readable=repr(obj))
        if t is bytes:
            return cls("bytes", "b64", base64.b64encode(obj).decode("ascii"), replicated)
        raise TypeError(f"Unsupported primitive type: {t}")

    def get_value(self) -> Any:
        if self.dtype == "int":
            return int(self.serialized_value)
        if self.dtype == "bool":
            return self.serialized_value == "True"
        if self.dtype == "str":
            return self.serialized_value
        if self.dtype == "float":
            if self.layout == "b64_le_f64":
                return struct.unpack("<d", base64.b64decode(self.serialized_value))[0]
            return float(self.serialized_value)
        if self.dtype == "bytes":
            return base64.b64decode(self.serialized_value)
        raise TypeError(f"Unsupported primitive dtype: {self.dtype}")

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PrimitiveEntry":
        return cls(
            dtype=d["dtype"],
            layout=d["layout"],
            serialized_value=d["serialized_value"],
            replicated=d["replicated"],
            readable=d.get("readable"),
        )


_ENTRY_TYPES = {
    "Tensor": TensorEntry,
    "Sharded": ShardedEntry,
    "ChunkedTensor": ChunkedTensorEntry,
    "object": ObjectEntry,
    "list": ListEntry,
    "tuple": TupleEntry,
    "dict": DictEntry,
    "OrderedDict": OrderedDictEntry,
    "primitive": PrimitiveEntry,
}


def _entry_to_dict(entry: Entry) -> Dict[str, Any]:
    def convert(v: Any) -> Any:
        if isinstance(v, Shard):
            return {
                "offsets": v.offsets,
                "sizes": v.sizes,
                "tensor": _entry_to_dict(v.tensor),
            }
        if isinstance(v, Entry):
            return _entry_to_dict(v)
        if isinstance(v, list):
            return [convert(x) for x in v]
        return v

    # The "type" tag rides along in entry.__dict__ and is what
    # entry_from_dict dispatches on.
    return {k: convert(v) for k, v in entry.__dict__.items() if v is not None}


def entry_from_dict(d: Dict[str, Any]) -> Entry:
    type_ = d["type"]
    if type_ not in _ENTRY_TYPES:
        raise ValueError(f"Unknown entry type: {type_}")
    body = {k: v for k, v in d.items() if k != "type"}
    return _ENTRY_TYPES[type_].from_dict(body)


def is_replicated(entry: Entry) -> bool:
    """Mirror of reference manifest.py:321-325."""
    return (
        isinstance(entry, (TensorEntry, ChunkedTensorEntry, ObjectEntry, PrimitiveEntry))
        and entry.replicated
    )


def is_container_entry(entry: Entry) -> bool:
    return isinstance(entry, (ListEntry, TupleEntry, DictEntry, OrderedDictEntry))


@dataclass
class SnapshotMetadata:
    version: str
    world_size: int
    manifest: Manifest
    # Commit wall-clock (rank 0's time.time() at take) — recorded IN the
    # metadata because file mtimes are unreliable ordering signals
    # (materialize's atomic rewrite, rsync/copies reset them; retention
    # ordering by mtime could delete the newest checkpoints). Optional:
    # absent in pre-field snapshots.
    created_at: Optional[float] = None
    # Base-snapshot roots (relative, "../"-prefixed) this incremental
    # snapshot's external blob locations point into — recorded at take
    # time so retention/info/materialize never have to GUESS where a
    # base root ends inside a location string (a base path containing a
    # purely numeric directory, e.g. "../exp/1000/final/0/w", is
    # ambiguous to grammar parsing — ADVICE r3). Absent/empty for
    # self-contained snapshots and pre-field increments (readers fall
    # back to parsing).
    base_roots: Optional[List[str]] = None
    # Free-form, JSON-serializable sidecar data riding the committed
    # metadata (e.g. the cross-rank telemetry rollup rank 0 folds in
    # before the commit). Readers must tolerate absence and unknown
    # keys; nothing restore-critical may live here.
    extras: Optional[Dict[str, Any]] = None

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "version": self.version,
            "world_size": self.world_size,
        }
        if self.created_at is not None:
            d["created_at"] = self.created_at
        if self.base_roots:
            d["base_roots"] = list(self.base_roots)
        if self.extras:
            d["extras"] = self.extras
        d["manifest"] = {
            k: _entry_to_dict(v) for k, v in self.manifest.items()
        }
        return d

    def to_yaml(self) -> str:
        # JSON is a subset of YAML; json.dumps is much faster than yaml.dump
        # for large manifests (reference manifest.py:283-289).
        return json.dumps(self.to_dict(), sort_keys=False)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SnapshotMetadata":
        manifest = {k: entry_from_dict(v) for k, v in d["manifest"].items()}
        return cls(
            version=d["version"],
            world_size=d["world_size"],
            manifest=manifest,
            created_at=d.get("created_at"),
            base_roots=d.get("base_roots"),
            extras=d.get("extras"),
        )

    @classmethod
    def from_yaml(cls, s: str) -> "SnapshotMetadata":
        try:
            d = json.loads(s)
        except json.JSONDecodeError:
            d = yaml.safe_load(s)
        if "self_checksum" in d:
            d = {k: v for k, v in d.items() if k != "self_checksum"}
        return cls.from_dict(d)


# ------------------------------------------------- durable metadata encoding

_SELF_CHECKSUM_KEY = "self_checksum"


def encode_metadata(metadata: SnapshotMetadata) -> bytes:
    """Serialize metadata WITH a self-checksum: the document is plain
    JSON (external tooling keeps working with ``json.load``) whose FIRST
    key is ``self_checksum`` — ``"<algo>:<8-hex>"`` over the exact file
    bytes with the checksum value replaced by zeros. Readers that don't
    know the field ignore it; :func:`decode_metadata` verifies it, so a
    torn or bit-rotted metadata file is detected instead of silently
    parsed (or dumped as a JSON traceback)."""
    from . import _native

    algo = _native.checksum_algorithm()
    placeholder = f"{algo}:" + "0" * 8
    d = {_SELF_CHECKSUM_KEY: placeholder, **metadata.to_dict()}
    body = json.dumps(d, sort_keys=False)
    crc = _native.crc32c(body.encode("utf-8")) & 0xFFFFFFFF
    # The self_checksum field is the document's first key, so the first
    # occurrence of the placeholder is the field itself; the replacement
    # is byte-length-preserving, keeping the checksum definition exact.
    return body.replace(placeholder, f"{algo}:{crc:08x}", 1).encode("utf-8")


def decode_metadata(data: bytes) -> SnapshotMetadata:
    """Parse ``.snapshot_metadata`` bytes, verifying the self-checksum
    when present (files written before the field verify nothing; an
    algorithm mismatch across builds is skipped with a warning, matching
    blob-checksum policy). Raises :class:`MetadataError` on torn or
    bit-rotted content."""
    import logging

    from . import _native

    try:
        text = data.decode("utf-8")
    except UnicodeDecodeError as e:
        raise MetadataError(
            f"snapshot metadata is not valid UTF-8 ({e}) — the file is "
            "torn or bit-rotted"
        ) from None
    try:
        d = json.loads(text)
    except json.JSONDecodeError:
        try:
            d = yaml.safe_load(text)
        except yaml.YAMLError:
            d = None
    if not isinstance(d, dict):
        # Covers valid-but-wrong-shape parses too (a corrupted file whose
        # bytes happen to be a JSON array/scalar) — still a storage-
        # integrity verdict, never a parser traceback.
        raise MetadataError(
            "snapshot metadata does not parse as a JSON/YAML mapping — "
            "the file is torn (partial write) or corrupted"
        ) from None
    recorded = d.get(_SELF_CHECKSUM_KEY)
    # Only the canonical JSON encoding (self_checksum first) defines the
    # checksummed byte stream; YAML-reformatted copies skip verification.
    if isinstance(recorded, str) and text.startswith(
        '{"%s": ' % _SELF_CHECKSUM_KEY
    ):
        algo, _, value = recorded.partition(":")
        if algo != _native.checksum_algorithm():
            logging.getLogger(__name__).warning(
                "skipping metadata self-checksum verification: file used "
                "%s, this build computes %s",
                algo,
                _native.checksum_algorithm(),
            )
        else:
            zeroed = text.replace(recorded, f"{algo}:" + "0" * 8, 1)
            actual = _native.crc32c(zeroed.encode("utf-8")) & 0xFFFFFFFF
            try:
                expect = int(value, 16)
            except ValueError:
                raise MetadataError(
                    f"malformed metadata self-checksum {recorded!r}"
                ) from None
            if actual != expect:
                raise MetadataError(
                    f"snapshot metadata self-checksum mismatch: recorded "
                    f"{recorded}, file bytes hash to {algo}:{actual:08x} — "
                    "the metadata was torn or bit-rotted in storage"
                )
    if _SELF_CHECKSUM_KEY in d:
        d = {k: v for k, v in d.items() if k != _SELF_CHECKSUM_KEY}
    try:
        return SnapshotMetadata.from_dict(d)
    except (KeyError, TypeError, ValueError) as e:
        raise MetadataError(
            f"snapshot metadata parses but is structurally invalid ({e!r})"
        ) from e
