"""GCS storage plugin — the north-star cloud target.

Counterpart of /root/reference/torchsnapshot/storage_plugins/gcs.py:
hand-rolled resumable uploads and chunked (100MB) ranged downloads over an
``AuthorizedSession``, run in a thread-pool executor so many transfers
proceed concurrently under asyncio; transient-error classification
(gcs.py:89-109) and the collective-progress retry strategy (gcs.py:216-272):
instead of a fixed per-request retry budget, a shared deadline is refreshed
whenever *any* concurrent transfer makes progress — so a pod-wide slowdown
doesn't abort the snapshot while the storage backend is merely saturated,
but a genuinely wedged backend still times out.
"""

import asyncio
import io
import logging
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ..io_types import ReadIO, StoragePlugin, WriteIO
from ..retry import (
    TRANSIENT_HTTP_STATUS,
    ProgressDeadline,
    RetryPolicy,
    http_status_of,
)

logger = logging.getLogger(__name__)

_UPLOAD_CHUNK_SIZE = 100 * 1024 * 1024
_DOWNLOAD_CHUNK_SIZE = 100 * 1024 * 1024
_DEFAULT_DEADLINE_SEC = 600


class _NoProgressError(ConnectionError):
    """Resumable-upload PUT was accepted (308) but persisted no bytes;
    subclasses ConnectionError so ``_is_transient`` retries it under the
    collective deadline."""


def _is_transient(exc: Exception) -> bool:
    if http_status_of(exc) in TRANSIENT_HTTP_STATUS:
        return True
    # connection-level failures are transient
    import requests

    return isinstance(
        exc, (requests.ConnectionError, requests.Timeout, ConnectionError, TimeoutError)
    )


class _RetryStrategy:
    """Collective-progress retry (reference gcs.py:216-272): a shared
    deadline refreshed whenever any concurrent coroutine completes a
    transfer, with the shared middleware's backoff shape. Composed from
    the extracted tpusnap.retry primitives; kept as a local class
    because the plugin retries at CHUNK grain inside its resumable
    upload loop — finer than the whole-op wrapper can."""

    def __init__(self, deadline_sec: float = _DEFAULT_DEADLINE_SEC) -> None:
        self._progress = ProgressDeadline(deadline_sec)
        # Base 2.0 preserves the historical GCS backoff (2s, 4s, ... 30s).
        self._policy = RetryPolicy(
            deadline_sec=deadline_sec, backoff_base_sec=2.0, backoff_cap_sec=30.0
        )

    def report_progress(self) -> None:
        self._progress.report_progress()

    def expired(self) -> bool:
        return self._progress.expired()

    async def backoff(self, attempt: int) -> None:
        await asyncio.sleep(self._policy.backoff_sec(attempt))


class GCSStoragePlugin(StoragePlugin):
    supports_in_place_reads = True
    # Retries internally at chunk grain under the collective-progress
    # deadline; the registry must not double-wrap it in whole-op retry.
    handles_own_retries = True

    def classify_transient(self, exc: BaseException) -> bool:
        return _is_transient(exc)

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        # One download chunk is materialized at a time.
        return min(nbytes, _DOWNLOAD_CHUNK_SIZE)

    def __init__(
        self, root: str, storage_options: Optional[Dict[str, Any]] = None
    ) -> None:
        components = root.split("/", 1)
        if len(components) != 2 or not components[0]:
            raise ValueError(f"Invalid gcs root: {root!r} (expected gs://bucket/prefix)")
        self.bucket, self.root = components[0], components[1]
        storage_options = storage_options or {}
        # Emulator/fake-server support (same convention as the official
        # client libraries): STORAGE_EMULATOR_HOST or an explicit
        # api_endpoint skip auth entirely and use a plain session.
        endpoint = storage_options.get("api_endpoint") or os.environ.get(
            "STORAGE_EMULATOR_HOST"
        )
        if endpoint:
            import requests

            if "://" not in endpoint:
                # fake-gcs-server convention: scheme-less host:port. The
                # official client libraries prepend http:// too.
                endpoint = f"http://{endpoint}"
            self._endpoint = endpoint.rstrip("/")
            self._session = requests.Session()
        else:
            try:
                import google.auth
                from google.auth.transport.requests import AuthorizedSession
            except ImportError as e:
                raise RuntimeError(
                    "GCS support requires google-auth (pip install google-auth)"
                ) from e
            scopes = ["https://www.googleapis.com/auth/devstorage.read_write"]
            credentials, _ = google.auth.default(scopes=scopes)
            self._endpoint = "https://storage.googleapis.com"
            self._session = AuthorizedSession(credentials)
        self._executor = ThreadPoolExecutor(
            max_workers=int(storage_options.get("max_workers", 16)),
            thread_name_prefix="tpusnap-gcs",
        )
        self._retry = _RetryStrategy(
            float(storage_options.get("deadline_sec", _DEFAULT_DEADLINE_SEC))
        )

    def _object_name(self, path: str) -> str:
        # normpath collapses "../" segments: incremental snapshots
        # reference base-snapshot blobs relative to their own root.
        import posixpath

        return posixpath.normpath(f"{self.root}/{path}")

    # --- blocking primitives, run in the executor ------------------------

    def _initiate_resumable_upload(self, name: str) -> str:
        from urllib.parse import quote

        url = (
            f"{self._endpoint}/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=resumable&name={quote(name, safe='')}"
        )
        resp = self._session.post(url, json={})
        resp.raise_for_status()
        return resp.headers["Location"]

    def _upload_chunk(
        self, session_url: str, chunk: memoryview, offset: int, total: int
    ) -> int:
        """PUT one chunk; returns the session's new persisted offset. On a
        308 the response's Range header — not the request size — is
        authoritative for how much was actually persisted."""
        end = offset + len(chunk)
        headers = {
            "Content-Length": str(len(chunk)),
            "Content-Range": f"bytes {offset}-{end - 1}/{total}",
        }
        resp = self._session.put(session_url, data=bytes(chunk), headers=headers)
        if resp.status_code in (200, 201):
            return total
        if resp.status_code == 308:
            persisted = resp.headers.get("Range")
            if persisted is None:
                return offset  # nothing persisted from this chunk
            return int(persisted.rsplit("-", 1)[1]) + 1
        resp.raise_for_status()
        return end

    def _query_persisted_offset(self, session_url: str, total: int) -> int:
        """Ask the resumable session how many bytes it has durably stored
        (the protocol-mandated status check after an interrupted chunk:
        PUT with ``Content-Range: bytes */total``)."""
        resp = self._session.put(
            session_url,
            headers={"Content-Range": f"bytes */{total}", "Content-Length": "0"},
        )
        if resp.status_code in (200, 201):
            return total  # upload actually completed
        if resp.status_code == 308:
            persisted = resp.headers.get("Range")  # e.g. "bytes=0-524287"
            if persisted is None:
                return 0
            return int(persisted.rsplit("-", 1)[1]) + 1
        resp.raise_for_status()
        return 0

    def _upload_empty(self, name: str) -> None:
        from urllib.parse import quote

        url = (
            f"{self._endpoint}/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=media&name={quote(name, safe='')}"
        )
        resp = self._session.post(url, data=b"")
        resp.raise_for_status()

    def _download_range(self, name: str, start: int, end: int) -> bytes:
        from urllib.parse import quote

        url = (
            f"{self._endpoint}/storage/v1/b/{self.bucket}"
            f"/o/{quote(name, safe='')}?alt=media"
        )
        headers = {"Range": f"bytes={start}-{end - 1}"}
        resp = self._session.get(url, headers=headers)
        resp.raise_for_status()
        return resp.content

    def _object_size(self, name: str) -> int:
        from urllib.parse import quote

        url = (
            f"{self._endpoint}/storage/v1/b/{self.bucket}"
            f"/o/{quote(name, safe='')}"
        )
        resp = self._session.get(url)
        resp.raise_for_status()
        return int(resp.json()["size"])

    def _delete_blocking(self, name: str) -> None:
        from urllib.parse import quote

        url = (
            f"{self._endpoint}/storage/v1/b/{self.bucket}"
            f"/o/{quote(name, safe='')}"
        )
        resp = self._session.delete(url)
        resp.raise_for_status()

    # --- retry wrapper ---------------------------------------------------

    async def _retry_gate(self, e: Exception, attempt: int) -> None:
        """Shared transient-or-raise + backoff step for all retry loops."""
        if not _is_transient(e) or self._retry.expired():
            raise e
        logger.warning("Transient GCS error (attempt %d): %s; retrying", attempt, e)
        await self._retry.backoff(attempt)

    async def _with_retry(self, fn, *args, counts_as_progress: bool = True):
        loop = asyncio.get_running_loop()
        attempt = 0
        while True:
            try:
                result = await loop.run_in_executor(self._executor, fn, *args)
                if counts_as_progress:
                    # Only data-carrying operations refresh the collective
                    # deadline; cheap status probes succeeding must not keep
                    # a wedged upload alive forever.
                    self._retry.report_progress()
                return result
            except Exception as e:
                attempt += 1
                await self._retry_gate(e, attempt)

    # --- plugin interface ------------------------------------------------

    async def write(self, write_io: WriteIO) -> None:
        name = self._object_name(write_io.path)
        buf = memoryview(write_io.buf).cast("B")
        total = buf.nbytes
        if total == 0:
            await self._with_retry(self._upload_empty, name)
            return
        session_url = await self._with_retry(self._initiate_resumable_upload, name)
        loop = asyncio.get_running_loop()
        offset = 0
        attempt = 0
        while offset < total:
            chunk = buf[offset : offset + _UPLOAD_CHUNK_SIZE]
            try:
                new_offset = await loop.run_in_executor(
                    self._executor, self._upload_chunk, session_url, chunk, offset, total
                )
                if new_offset <= offset:
                    # A 308 that persisted nothing (no/stale Range header)
                    # must count as a failed attempt — otherwise a wedged
                    # session would re-PUT the same chunk in a tight loop,
                    # never consulting the collective deadline.
                    raise _NoProgressError(
                        f"GCS resumable upload made no progress at offset "
                        f"{offset}/{total}"
                    )
            except Exception as e:
                attempt += 1
                await self._retry_gate(e, attempt)
                # A partially-persisted chunk moves the session's write
                # head; blindly re-PUTting the old Content-Range would be
                # rejected as an offset mismatch. Resynchronize first (a
                # status probe — must not refresh the progress deadline).
                offset = await self._with_retry(
                    self._query_persisted_offset,
                    session_url,
                    total,
                    counts_as_progress=False,
                )
                continue
            self._retry.report_progress()
            offset = new_offset
            attempt = 0

    async def read(self, read_io: ReadIO) -> None:
        name = self._object_name(read_io.path)
        if read_io.byte_range is not None:
            start, end = read_io.byte_range
        else:
            start, end = 0, await self._with_retry(self._object_size, name)
        n = end - start
        if read_io.into is not None:
            if n != read_io.into.nbytes:
                # The destination was sized from the manifest; fail
                # loudly instead of buffering an unbudgeted full-size
                # copy on the way to the same size/checksum error.
                raise IOError(
                    f"GCS object {name!r} has {n} readable bytes, "
                    f"expected {read_io.into.nbytes} — the snapshot "
                    "blob is truncated or corrupt"
                )
            # In-place download: chunks land directly in the restore
            # target (no BytesIO assembly, no deserialize/copy pass in
            # the consume stage), with the checksum accumulated chunk by
            # chunk over the just-landed (cache-warm) bytes. This is the
            # 7B-from-GCS restore path.
            await self._read_into(read_io, name, start, end)
            return
        out = io.BytesIO()
        for offset in range(start, end, _DOWNLOAD_CHUNK_SIZE):
            chunk_end = min(offset + _DOWNLOAD_CHUNK_SIZE, end)
            out.write(await self._with_retry(self._download_range, name, offset, chunk_end))
        out.seek(0)
        read_io.buf = out

    async def _read_into(
        self, read_io: ReadIO, name: str, start: int, end: int
    ) -> None:
        from .. import _native
        from ..memoryview_stream import MemoryviewStream

        dst = read_io.into
        n = end - start
        crc: Optional[int] = 0 if read_io.want_crc else None
        for offset in range(start, end, _DOWNLOAD_CHUNK_SIZE):
            chunk_end = min(offset + _DOWNLOAD_CHUNK_SIZE, end)
            data = await self._with_retry(
                self._download_range, name, offset, chunk_end
            )
            if len(data) != chunk_end - offset:
                raise IOError(
                    f"short GCS read: got {len(data)} of "
                    f"{chunk_end - offset} bytes at offset {offset} of "
                    f"{name!r}"
                )
            lo = offset - start

            def land(lo=lo, data=data):
                # Copy + hash off the event loop: a 100 MiB memcpy on
                # the loop thread would stall every concurrent stream.
                # Hash after the chunk fully landed (retry-safe: a
                # re-downloaded chunk overwrites the same region before
                # it is ever hashed).
                dst[lo : lo + len(data)] = data
                if crc is not None:
                    return _native.crc32c(dst[lo : lo + len(data)], crc)
                return None

            new_crc = await self._submit_tracked(self._executor, land)
            if crc is not None:
                crc = new_crc
        read_io.in_place = True
        if crc is not None:
            read_io.crc32c = crc
            read_io.crc_algo = _native.checksum_algorithm()
        read_io.buf = MemoryviewStream(dst[:n])

    async def delete(self, path: str) -> None:
        await self._with_retry(self._delete_blocking, self._object_name(path))

    async def close(self) -> None:
        from ..io_types import shutdown_plugin_executor

        shutdown_plugin_executor(self._executor)
