"""Local/posix filesystem storage plugin.

Counterpart of /root/reference/torchsnapshot/storage_plugins/fs.py:26-49:
aiofiles-backed async I/O, a mkdir cache so each directory is created once,
and ranged reads by seek. Additionally uses the native helper
(tpusnap._native) for large GIL-released positional writes when available —
the reference leans on torch's native file I/O for the same effect.
"""

import asyncio
import io
import os
import pathlib
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Set

try:
    import aiofiles
except ModuleNotFoundError:  # gated dep: fall back to thread-pool I/O
    aiofiles = None
import numpy as np

from ..io_types import ReadIO, StoragePlugin, WriteIO
from ..memoryview_stream import MemoryviewStream

# Buffers >= this go through the thread-pool native writer; small writes
# stay on the aiofiles path where syscall overhead doesn't matter.
_NATIVE_WRITE_THRESHOLD = 4 * 1024 * 1024


class FSStoragePlugin(StoragePlugin):
    supports_in_place_reads = True
    # Whole-op retry middleware (tpusnap.retry) wraps this plugin when it
    # is built from a URL: local filesystems rarely throw transient
    # errors, but network mounts (NFS/FUSE) and chaos runs do, and the
    # default errno/connection classifier covers both.
    wants_retry_middleware = True

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        """Per-stream bounce memory of the native in-place read engine
        ((qd+1) x 8 MiB chunks, clamped to the read window — see
        ts_read_range_into_crc)."""
        from ..knobs import get_direct_io_qd

        qd = min(max(get_direct_io_qd(), 1), 8)  # native clamps identically
        return min(nbytes, (qd + 1) * 8 * 1024 * 1024)

    def __init__(self, root: str, storage_options=None) -> None:
        self.root = root
        self._dir_cache: Set[pathlib.Path] = set()
        self._executor: Optional[ThreadPoolExecutor] = None

    def _ensure_parent(self, path: pathlib.Path) -> None:
        parent = path.parent
        if parent not in self._dir_cache:
            parent.mkdir(parents=True, exist_ok=True)
            self._dir_cache.add(parent)

    def _get_executor(self) -> ThreadPoolExecutor:
        if self._executor is None:
            # 8 concurrent streams measurably out-run 4 on direct I/O
            # (deeper device queue); each stream is GIL-released in native
            # code so the extra threads cost nothing on the Python side.
            self._executor = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="tpusnap-fs"
            )
        return self._executor

    async def write(self, write_io: WriteIO) -> None:
        path = pathlib.Path(os.path.join(self.root, write_io.path))
        self._ensure_parent(path)
        buf = write_io.buf
        if len(buf) >= _NATIVE_WRITE_THRESHOLD or aiofiles is None:
            # One blocking write in a thread: releases the GIL for the whole
            # transfer and avoids aiofiles' per-chunk hop overhead. Also the
            # small-write path when aiofiles is not installed.
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(self._get_executor(), _write_file, path, buf)
        else:
            async with aiofiles.open(path, "wb") as f:
                await f.write(buf)
        if _durable_commit():
            # Durable-commit mode: every blob's DATA must be on stable
            # storage before the metadata commit declares the snapshot
            # durable — fsync on the metadata file alone does not write
            # back other files' dirty pages (small blobs and fallback
            # engines go through the page cache). Dirent durability is
            # handled at commit time (write_atomic fsyncs every
            # directory this plugin created).
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(
                self._get_executor(), _fsync_path, str(path)
            )

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        """Temp-file + rename: a crash mid-write never destroys an
        existing file at the destination. With ``durable=True`` the temp
        file is fsync'd before the rename and the parent directory
        after, so a power loss after return can never leave the rename
        durable with the DATA not (an empty/torn ``.snapshot_metadata``)
        nor lose the commit. The fsync is caller-opted because its cost
        is NOT metadata-sized: an fsync right after a multi-GB take
        flushes the storage cache of everything just written (~2 s
        measured here) — callers rewriting already-committed metadata
        always opt in, the take commit does so via
        TPUSNAP_DURABLE_COMMIT (see io_types.write_atomic)."""
        path = pathlib.Path(os.path.join(self.root, write_io.path))
        self._ensure_parent(path)
        tmp = path.with_name(path.name + f".tmp.{os.getpid()}")
        loop = asyncio.get_running_loop()

        def work():
            try:
                _write_file(tmp, write_io.buf)
                if durable:
                    _fsync_path(str(tmp))
                os.replace(tmp, path)
                if durable:
                    # Every directory this plugin created, plus the
                    # commit's own parent: the dirents of the blobs
                    # written before this commit become durable with it.
                    for d in {str(p) for p in self._dir_cache} | {
                        str(path.parent)
                    }:
                        _fsync_path(d)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise

        await loop.run_in_executor(self._get_executor(), work)

    async def read(self, read_io: ReadIO) -> None:
        path = os.path.join(self.root, read_io.path)
        if read_io.byte_range is not None:
            offset, end = read_io.byte_range
        else:
            offset, end = 0, os.path.getsize(path)
        n = end - offset
        # Exact-size match only: a truncated blob (n = actual file size <
        # destination) must fall through to the generic path, whose
        # deserialize raises on the size mismatch even with checksums off.
        if read_io.into is not None and n == read_io.into.nbytes:
            await self._native_read_into(read_io, path, offset, n)
            return
        if n >= _NATIVE_WRITE_THRESHOLD:
            read_io.buf = await self._native_read(path, offset, n, read_io)
            return
        if aiofiles is None:

            def work():
                with open(path, "rb") as f:
                    if offset:
                        f.seek(offset)
                    return f.read(n)

            loop = asyncio.get_running_loop()
            data = await loop.run_in_executor(self._get_executor(), work)
            read_io.buf = io.BytesIO(data)
            return
        async with aiofiles.open(path, "rb") as f:
            if offset:
                await f.seek(offset)
            read_io.buf = io.BytesIO(await f.read(n))

    async def _native_read_into(self, read_io: ReadIO, path: str, offset: int, n: int) -> None:
        """In-place read: bytes land directly in the consumer-provided
        destination (the restore target's memory) with the checksum fused
        into the native copy-out — no scratch buffer, no separate verify
        pass, no deserialize+copy pass in the consume stage."""
        dst = read_io.into

        def work():
            from .. import _native

            return _native.read_range_into(
                path, offset, n, dst, want_crc=read_io.want_crc
            )

        got, crc, algo = await self._submit_tracked(self._get_executor(), work)
        if got != n:
            raise IOError(
                f"short read: got {got} of {n} bytes at offset {offset} "
                f"from {path} — the snapshot blob is truncated"
            )
        read_io.in_place = True
        read_io.crc32c = crc
        read_io.crc_algo = algo
        read_io.buf = MemoryviewStream(dst[:n])

    async def _native_read(self, path: str, offset: int, n: int, read_io=None):
        """Single GIL-released pread in a thread (native helper), landing
        in an *uninitialized* numpy buffer — preallocating via BytesIO
        would zero-fill n bytes first. The allocation itself also happens
        on the worker thread: large np.empty calls contend on the
        process's mmap lock under concurrent read page-fault traffic and
        would stall the event loop for tens of ms each.

        When the request asks for a checksum (``want_crc``) it is
        computed here on the read thread — overlapping other streams'
        I/O — so the consume stage verifies a 4-byte value instead of
        re-reading the buffer (sharded-shard reads use this; dense numpy
        targets go further via the in-place ``into`` path)."""
        want_crc = read_io is not None and read_io.want_crc

        def work():
            from .. import _native

            # 4096-aligned so the native direct read preads straight into
            # this buffer (zero-copy) instead of bouncing every chunk.
            arr = _native.aligned_empty(n)
            if want_crc:
                got, crc, algo = _native.read_range_into(
                    path, offset, n, arr, want_crc=True
                )
                return arr, got, crc, algo
            got = _read_range(path, offset, n, arr.data)
            return arr, got, None, None

        arr, got, crc, algo = await self._submit_tracked(self._get_executor(), work)
        if want_crc and got == n:
            read_io.crc32c = crc
            read_io.crc_algo = algo
        view = memoryview(arr)[:got] if got != n else memoryview(arr)
        return MemoryviewStream(view)

    async def delete(self, path: str) -> None:
        full = os.path.join(self.root, path)
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, os.remove, full)

    async def list_with_sizes(self) -> Optional[dict]:
        """``{relative_path: size}`` for every regular file under the
        root (lifecycle tooling: fsck orphan enumeration, gc). Missing
        root → empty dict (an un-taken snapshot path is simply empty)."""
        loop = asyncio.get_running_loop()

        def work():
            out = {}
            root = os.path.abspath(self.root)
            if not os.path.isdir(root):
                return out
            for dirpath, _dirnames, filenames in os.walk(root):
                for name in filenames:
                    full = os.path.join(dirpath, name)
                    rel = os.path.relpath(full, root).replace(os.sep, "/")
                    try:
                        out[rel] = os.path.getsize(full)
                    except OSError:
                        continue  # racing deletion (concurrent gc/abort)
            return out

        return await loop.run_in_executor(self._get_executor(), work)

    async def flush_created_dirs(self) -> None:
        """fsync every directory this instance created (durable-commit
        mode: each rank runs this after its writes drain, so dirents of
        all ranks' blobs are stable before rank 0 commits)."""
        dirs = {str(p) for p in self._dir_cache} | {self.root}
        loop = asyncio.get_running_loop()

        def work():
            for d in dirs:
                try:
                    _fsync_path(d)
                except OSError:
                    pass  # deleted/renamed since creation

        await loop.run_in_executor(self._get_executor(), work)

    async def close(self) -> None:
        if self._executor is not None:
            from ..io_types import shutdown_plugin_executor

            shutdown_plugin_executor(self._executor)
            self._executor = None


def _durable_commit() -> bool:
    from ..knobs import is_durable_commit_enabled

    return is_durable_commit_enabled()


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_file(path: pathlib.Path, buf) -> None:
    from .. import _native as native

    if native.available():
        native.write_file(str(path), buf)
        return
    native._write_all(str(path), memoryview(buf).cast("B"))


def _read_range(path: str, offset: int, n: int, out: bytearray) -> int:
    from .. import _native as native

    return native.read_range(path, offset, n, out)
