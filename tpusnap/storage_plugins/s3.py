"""S3 storage plugin (counterpart of
/root/reference/torchsnapshot/storage_plugins/s3.py:39-66).

Uses aiobotocore when installed; ranged reads via the HTTP Range header.
Import of aiobotocore is deferred to construction so environments without
it can still use every other plugin.
"""

import asyncio
import io
from typing import Any, Dict, Optional

from ..io_types import ReadIO, StoragePlugin, WriteIO
from ..memoryview_stream import MemoryviewStream


class S3StoragePlugin(StoragePlugin):
    supports_in_place_reads = True
    # Wrapped in the whole-op retry middleware when built from a URL
    # (S3 PUTs are per-object atomic, so whole-op retry is torn-write
    # safe by construction).
    wants_retry_middleware = True

    # S3 error codes that mean "back off and try again" even when the
    # HTTP status alone is ambiguous.
    _TRANSIENT_ERROR_CODES = frozenset(
        {
            "SlowDown",
            "InternalError",
            "RequestTimeout",
            "RequestTimeoutException",
            "Throttling",
            "ThrottlingException",
            "ServiceUnavailable",
        }
    )

    def classify_transient(self, exc: BaseException) -> bool:
        from ..retry import default_classify_transient

        if default_classify_transient(exc):
            return True
        # botocore ClientError shape, sniffed without importing botocore.
        response = getattr(exc, "response", None)
        if isinstance(response, dict):
            code = (response.get("Error") or {}).get("Code")
            if code in self._TRANSIENT_ERROR_CODES:
                return True
        return False

    def __init__(
        self, root: str, storage_options: Optional[Dict[str, Any]] = None
    ) -> None:
        components = root.split("/", 1)
        if len(components) != 2 or not components[0]:
            raise ValueError(
                f"Invalid s3 root: {root!r} (expected s3://bucket/prefix)"
            )
        self.bucket, self.root = components[0], components[1]
        self._client = None
        self._client_ctx = None
        self._storage_options = storage_options or {}
        self._executor = None
        # The aiobotocore import is deferred to first use so construction
        # works without the package — tests inject a stub via _client, and
        # environments without S3 can still import/route every plugin.

    def _get_executor(self):
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor

            self._executor = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="tpusnap-s3"
            )
        return self._executor

    async def _get_client(self):
        if self._client is None:
            try:
                from aiobotocore.session import get_session
            except ImportError as e:
                raise RuntimeError(
                    "S3 support requires aiobotocore (pip install aiobotocore)"
                ) from e
            self._client_ctx = get_session().create_client(
                "s3", **self._storage_options.get("client_kwargs", {})
            )
            self._client = await self._client_ctx.__aenter__()
        return self._client

    def _key(self, path: str) -> str:
        # normpath collapses "../" segments: incremental snapshots
        # reference base-snapshot blobs relative to their own root.
        import posixpath

        return posixpath.normpath(f"{self.root}/{path}")

    async def write(self, write_io: WriteIO) -> None:
        client = await self._get_client()
        buf = write_io.buf
        body = MemoryviewStream(buf) if isinstance(buf, memoryview) else io.BytesIO(buf)
        await client.put_object(
            Bucket=self.bucket, Key=self._key(write_io.path), Body=body
        )

    async def read(self, read_io: ReadIO) -> None:
        client = await self._get_client()
        kwargs: Dict[str, Any] = {
            "Bucket": self.bucket,
            "Key": self._key(read_io.path),
        }
        if read_io.byte_range is not None:
            start, end = read_io.byte_range
            # HTTP Range is inclusive on both ends.
            kwargs["Range"] = f"bytes={start}-{end - 1}"
        response = await client.get_object(**kwargs)
        async with response["Body"] as stream:
            body = await stream.read()
        if read_io.into is not None:
            if len(body) != read_io.into.nbytes:
                # The destination was sized from the manifest; a
                # different body means the stored object was truncated
                # or drifted. Fail loudly — falling back to buffering
                # would hold an unbudgeted full-size copy on the way to
                # the same error.
                raise IOError(
                    f"S3 object {kwargs['Key']!r} returned {len(body)} "
                    f"bytes, expected {read_io.into.nbytes} — the "
                    "snapshot blob is truncated or corrupt"
                )
            # In-place delivery: bytes land in the restore target, the
            # checksum is computed once, and the consume stage verifies
            # a 4-byte value with no deserialize/copy pass. The copy +
            # hash run in a worker thread (blocking the event loop for
            # a multi-GB memcpy would stall every concurrent stream),
            # tracked so an aborted restore can wait it out before the
            # error reaches the caller.
            from .. import _native

            def deliver():
                read_io.into[: len(body)] = body
                if read_io.want_crc:
                    read_io.crc32c = _native.crc32c(body)
                    read_io.crc_algo = _native.checksum_algorithm()

            await self._submit_tracked(self._get_executor(), deliver)
            read_io.in_place = True
            read_io.buf = MemoryviewStream(read_io.into[: len(body)])
            return
        read_io.buf = io.BytesIO(body)

    async def delete(self, path: str) -> None:
        client = await self._get_client()
        await client.delete_object(Bucket=self.bucket, Key=self._key(path))

    async def close(self) -> None:
        if self._client_ctx is not None:
            await self._client_ctx.__aexit__(None, None, None)
            self._client = None
            self._client_ctx = None
        if self._executor is not None:
            from ..io_types import shutdown_plugin_executor

            shutdown_plugin_executor(self._executor)
            self._executor = None
