"""Generic fsspec bridge plugin: ``fsspec+<protocol>://path``.

Not in the reference — a tpusnap extension that opens every
fsspec-supported backend (memory, http, sftp, az, …) through the same
StoragePlugin interface, with blocking fsspec calls run in a thread pool.
"""

import asyncio
import io
import os
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional

from ..io_types import ReadIO, StoragePlugin, WriteIO


class FsspecStoragePlugin(StoragePlugin):
    # Wrapped in the whole-op retry middleware when built from a URL:
    # fsspec backends span everything from in-memory dicts to SFTP — the
    # default connection/timeout/errno classifier is the right generic
    # net for them.
    wants_retry_middleware = True

    def __init__(
        self,
        protocol: str,
        root: str,
        storage_options: Optional[Dict[str, Any]] = None,
    ) -> None:
        import fsspec

        self._fs = fsspec.filesystem(protocol, **(storage_options or {}))
        self.root = root
        self._executor = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="tpusnap-fsspec"
        )

    def _full(self, path: str) -> str:
        # normpath collapses "../" segments: incremental snapshots
        # reference base-snapshot blobs relative to their own root.
        import posixpath

        return posixpath.normpath(f"{self.root}/{path}") if self.root else path

    def _write_blocking(self, path: str, buf) -> None:
        full = self._full(path)
        parent = full.rsplit("/", 1)[0]
        if parent:
            try:
                self._fs.makedirs(parent, exist_ok=True)
            except Exception:
                pass  # object stores have no directories
        with self._fs.open(full, "wb") as f:
            f.write(bytes(buf))

    def _read_blocking(self, path: str, byte_range) -> bytes:
        full = self._full(path)
        with self._fs.open(full, "rb") as f:
            if byte_range is None:
                return f.read()
            offset, end = byte_range
            f.seek(offset)
            return f.read(end - offset)

    async def write(self, write_io: WriteIO) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(
            self._executor, self._write_blocking, write_io.path, write_io.buf
        )

    async def read(self, read_io: ReadIO) -> None:
        loop = asyncio.get_running_loop()
        data = await loop.run_in_executor(
            self._executor, self._read_blocking, read_io.path, read_io.byte_range
        )
        read_io.buf = io.BytesIO(data)

    async def delete(self, path: str) -> None:
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(self._executor, self._fs.rm, self._full(path))

    async def close(self) -> None:
        from ..io_types import shutdown_plugin_executor

        shutdown_plugin_executor(self._executor)
