"""StateDict — a dict that satisfies the Stateful protocol, for tracking
plain values (progress counters, hyperparameters, metrics) in app state.

Counterpart of /root/reference/torchsnapshot/state_dict.py:13.
"""

from typing import Any, Dict


class StateDict(Dict[str, Any]):
    def state_dict(self) -> Dict[str, Any]:
        return self

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        self.clear()
        self.update(state_dict)
