"""Dtype-aware fused tile compression: codec model + probe-driven auto policy.

The native engine's staging hot path already makes one fused pass per
tile (clone + CRC32C + XXH64); on network-bound destinations (cloud,
virtio, the write-back tier's remote drain) the storage pipe, not the
host, is the ceiling, so a codec stage rides the same pass: a
byte-shuffle filter keyed on dtype element size (bf16/f32/f64 exponent
bytes group into near-constant planes; fp8/int8 skip the filter)
followed by LZ4 block compression, per checksum tile, preserving
tile-grain random access on the restore path.

The policy is MEASURED, not configured (``TPUSNAP_COMPRESS=auto``, the
default): compress when the pipe's probe-reported write ceiling is
clearly slower than the codec's measured throughput, bypass when local
disk outruns it. Ceilings come from the in-take roofline probes
(``TPUSNAP_PROBE=1``, scheduler._ProbeRunner feeds every sample here)
or — when no sample exists yet and the take is large enough to amortize
it — from a one-shot policy mini-probe through the take's own plugin
stack. Codec throughput is measured once per process on a synthetic
bf16-precision buffer. All checksums/dedup hashes of a compressed blob
are recorded over the STORED (compressed) bytes, so the journal/salvage/
upload-journal dual-hash evidence rule, scrub and fsck hold unchanged.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# Auto mode never probes (or compresses) a take whose eligible payload
# is below this floor: a small take cannot amortize the policy probe or
# the codec bookkeeping, and bypass is within noise there anyway.
AUTO_MIN_TAKE_BYTES = 64 << 20

# Compress only when the codec clearly outruns the pipe: at parity the
# codec would serialize the take behind the CPU for ~zero effective
# gain, and the probe ceiling itself carries measurement noise.
COMPRESS_MARGIN = 1.3

# Policy mini-probe: streams x bytes written through the take's own
# plugin stack (PROBE_DIR namespace: journal-exempt sidecar space, a
# crash's leftovers are orphan-visible to fsck/gc).
_POLICY_PROBE_STREAMS = 2
_POLICY_PROBE_STREAM_BYTES = 4 << 20


def codec_for_dtype(dtype_str: str) -> Optional[str]:
    """The codec family for a manifest dtype string, or None when the
    dtype is not compressible (unknown/odd element sizes). Element size
    keys the byte-shuffle filter: ``shuf4+lz4`` for f32, ``shuf2+lz4``
    for bf16/f16, plain ``lz4`` for 1-byte dtypes (fp8/int8/uint8,
    where a shuffle is the identity)."""
    from .serialization import tensor_nbytes

    try:
        itemsize = tensor_nbytes(dtype_str, [1])
    except Exception:
        return None
    if itemsize == 1:
        return "lz4"
    if itemsize in (2, 4, 8):
        return f"shuf{itemsize}+lz4"
    return None


def codec_elem(codec: str) -> int:
    """Byte-shuffle element size encoded in a codec name. Raises
    ValueError for codec families this build cannot decode — the
    restore path surfaces that as a clear error instead of garbage."""
    if codec == "lz4":
        return 1
    if codec.startswith("shuf") and codec.endswith("+lz4"):
        try:
            elem = int(codec[4:-4])
        except ValueError:
            raise ValueError(f"unknown codec {codec!r}") from None
        if elem in (2, 4, 8):
            return elem
    raise ValueError(
        f"unknown codec {codec!r} — this snapshot was written by a newer "
        "build; upgrade to restore it"
    )


# ---------------------------------------------------------------- ceilings

# Process-global pipe ceilings by (storage label, lane), fed by every
# roofline probe sample (each probe measures both its write and read
# legs) and by the policy mini-probe. Lanes are "write" and "read":
# asymmetric backends (write-back tiers, read-optimized mounts) get
# separate ceilings so the restore roofline never divides by a write
# number. Newest sample wins: the probe's whole point is that the
# ceiling is a live measurement, not a config belief.
_ceilings: Dict[Tuple[str, str], float] = {}
_ceilings_lock = threading.Lock()


def pipe_ceiling_key(storage) -> str:
    """Registry key for a plugin stack's pipe ceiling: the innermost
    backend class name PLUS the device/bucket it points at, so two
    same-class backends with different bandwidth — a fast local NVMe
    dir and a slow NFS/virtio fs:// mount in one process — never share
    (and poison) one sample. Filesystem plugins key on ``st_dev`` of
    the root's nearest existing ancestor (different mounts → different
    devices; sibling snapshot dirs on one disk → one shared ceiling,
    which is the reuse the probe feed exists for); object stores key on
    their bucket."""
    import os

    from .storage_plugin import StoragePlugin, storage_plugin_label

    label = storage_plugin_label(storage)
    base = storage
    while isinstance(getattr(base, "inner", None), StoragePlugin):
        base = base.inner
    root = getattr(base, "root", None)
    if root:
        p = os.path.abspath(str(root))
        while True:
            try:
                return f"{label}@dev{os.stat(p).st_dev}"
            except OSError:
                parent = os.path.dirname(p)
                if parent == p:
                    break
                p = parent
    for attr in ("bucket", "bucket_name", "netloc"):
        v = getattr(base, attr, None)
        if v:
            return f"{label}@{v}"
    return label


def note_pipe_ceiling(label: str, gbps: float, lane: str = "write") -> None:
    if not label or gbps <= 0:
        return
    with _ceilings_lock:
        _ceilings[(label, lane)] = float(gbps)


def pipe_ceiling(label: str, lane: str = "write") -> Optional[float]:
    with _ceilings_lock:
        return _ceilings.get((label, lane))


def pipe_ceilings_snapshot() -> Dict[Tuple[str, str], float]:
    """Copy of every (label, lane) ceiling known to this process — the
    tune planner's view of what the probes have measured."""
    with _ceilings_lock:
        return dict(_ceilings)


def _reset_ceilings() -> None:
    """Test seam."""
    with _ceilings_lock:
        _ceilings.clear()


# ------------------------------------------------------- codec throughput

_codec_gbps: Optional[float] = None
_codec_lock = threading.Lock()


def codec_throughput_gbps() -> float:
    """Measured compression throughput of this host (GB/s of input
    consumed), cached per process. The sample is an 8 MiB f32 buffer
    holding bf16-precision values — the mixed-precision-export shape
    the policy most often judges — compressed through the same fused
    native pass takes use. 0.0 when the native codec is unavailable
    (the policy then always bypasses)."""
    global _codec_gbps
    with _codec_lock:
        if _codec_gbps is not None:
            return _codec_gbps
        from . import _native
        from .knobs import get_native_copy_threads

        if not _native.compression_available():
            _codec_gbps = 0.0
            return _codec_gbps
        import numpy as np

        rng = np.random.default_rng(0x7C0)
        arr = rng.standard_normal(2 << 20).astype(np.float32)
        arr = (arr.view(np.uint32) & np.uint32(0xFFFF0000)).view(np.float32)
        buf = arr.tobytes()
        t0 = time.monotonic()
        _native.compress_tiles(
            buf, 4 << 20, 4, False, nthreads=get_native_copy_threads()
        )
        elapsed = max(time.monotonic() - t0, 1e-9)
        _codec_gbps = round(len(buf) / elapsed / 1e9, 4)
        logger.info("measured codec throughput: %.3f GB/s", _codec_gbps)
        return _codec_gbps


def _reset_codec_throughput() -> None:
    """Test seam."""
    global _codec_gbps
    with _codec_lock:
        _codec_gbps = None


# ---------------------------------------------------------------- decision


@dataclass
class CompressDecision:
    """One take's resolved compression policy, recorded in the take's
    telemetry meta (→ summary → history event) and readable after the
    fact via ``LAST_DECISION`` (ci_gate's smoke asserts on it)."""

    mode: str
    compress: bool
    reason: str
    codec_gbps: float = 0.0
    pipe_gbps: Optional[float] = None
    eligible_bytes: int = 0

    def to_meta(self) -> Dict[str, object]:
        d: Dict[str, object] = {
            "mode": self.mode,
            "decision": "compress" if self.compress else "bypass",
            "reason": self.reason,
            "codec_gbps": self.codec_gbps,
            "eligible_bytes": self.eligible_bytes,
        }
        if self.pipe_gbps is not None:
            d["pipe_gbps"] = round(self.pipe_gbps, 4)
        return d


LAST_DECISION: Optional[CompressDecision] = None


def _policy_probe(storage, event_loop, label: str) -> Optional[float]:
    """One-shot write ceiling measurement through the take's own plugin
    stack (the probe traffic sees the same chaos/retry/journal layers
    the take's blobs do, by design). Returns GB/s or None; the sample
    is cached in the ceiling registry either way a sample lands."""
    import os

    from .io_types import PROBE_DIR, WriteIO

    try:
        block = os.urandom(1 << 20)
        reps = _POLICY_PROBE_STREAM_BYTES // len(block)
        buf = memoryview(block * reps)
        paths = [
            f"{PROBE_DIR}/policy_{os.getpid()}_{i}.bin"
            for i in range(_POLICY_PROBE_STREAMS)
        ]
        import asyncio

        from .io_types import run_on_loop

        async def _run() -> float:
            t0 = time.monotonic()
            await asyncio.gather(
                *(storage.write(WriteIO(path=p, buf=buf)) for p in paths)
            )
            elapsed = max(time.monotonic() - t0, 1e-9)
            await asyncio.gather(
                *(storage.delete(p) for p in paths), return_exceptions=True
            )
            return len(buf) * len(paths) / elapsed / 1e9

        gbps = run_on_loop(event_loop, _run())
        note_pipe_ceiling(label, gbps)
        from . import telemetry

        telemetry.incr("compress.policy_probes")
        return gbps
    except Exception:
        logger.warning(
            "compression policy probe failed (non-fatal; bypassing)",
            exc_info=True,
        )
        return None


def _eligible_stagers(write_reqs) -> List[object]:
    """The stagers fused compression may apply to: standalone dense
    array blobs (incl. chunk blobs) above the per-blob floor, of a
    dtype the shuffle filter understands. Slab members (batched small
    arrays) and sharded shards (whose restore path reads arbitrary
    overlap sub-ranges — impossible at compressed-tile grain) are
    constructed with ``compressible=False`` and never appear here."""
    from .io_preparers.array import ArrayBufferStager
    from .knobs import get_compress_min_blob_bytes

    floor = get_compress_min_blob_bytes()
    out = []
    for wr in write_reqs:
        st = wr.buffer_stager
        if not isinstance(st, ArrayBufferStager):
            continue
        if not getattr(st, "compressible", True):
            continue
        entry = st.entry
        if entry is None or entry.byte_range is not None:
            continue
        if codec_for_dtype(entry.dtype) is None:
            continue
        if st.get_planned_bytes() < floor:
            continue
        out.append(st)
    return out


def apply_take_policy(write_reqs, storage, event_loop, rec=None):
    """Resolve this take's compress-or-bypass decision and arm the
    eligible stagers. Called once per take, after batching and before
    scheduling; never raises (a policy failure must not fail a take)."""
    global LAST_DECISION
    try:
        decision = _apply_take_policy_impl(write_reqs, storage, event_loop)
    except Exception:
        logger.warning("compression policy failed (bypassing)", exc_info=True)
        decision = CompressDecision(
            mode="auto", compress=False, reason="policy_error"
        )
    LAST_DECISION = decision
    try:
        if rec is not None:
            rec.meta["compress"] = decision.to_meta()
        if decision.compress or decision.reason not in (
            "mode_off",
            "no_eligible_blobs",
            "below_auto_floor",
        ):
            from . import flight

            flight.record(
                "compress_policy",
                op=decision.reason,
                decision="compress" if decision.compress else "bypass",
                codec_gbps=decision.codec_gbps,
                pipe_gbps=decision.pipe_gbps,
            )
    except Exception:
        logger.debug("compress decision recording failed", exc_info=True)
    return decision


def _apply_take_policy_impl(write_reqs, storage, event_loop):
    from . import _native
    from .knobs import get_compress_mode, is_checksum_disabled

    mode = get_compress_mode()
    if mode == "off":
        return CompressDecision(mode=mode, compress=False, reason="mode_off")
    if is_checksum_disabled():
        # Compressed restores verify the stored bytes by checksum; with
        # checksums off there is no integrity evidence to record.
        return CompressDecision(
            mode=mode, compress=False, reason="checksums_disabled"
        )
    if not _native.compression_available():
        return CompressDecision(
            mode=mode, compress=False, reason="native_unavailable"
        )
    eligible = _eligible_stagers(write_reqs)
    if not eligible:
        return CompressDecision(
            mode=mode, compress=False, reason="no_eligible_blobs"
        )
    eligible_bytes = sum(st.get_planned_bytes() for st in eligible)
    codec_gbps = codec_throughput_gbps()
    pipe = None
    if mode == "auto":
        if eligible_bytes < AUTO_MIN_TAKE_BYTES:
            return CompressDecision(
                mode=mode,
                compress=False,
                reason="below_auto_floor",
                codec_gbps=codec_gbps,
                eligible_bytes=eligible_bytes,
            )
        label = pipe_ceiling_key(storage)
        pipe = pipe_ceiling(label)
        if pipe is None:
            pipe = _policy_probe(storage, event_loop, label)
        if pipe is None:
            return CompressDecision(
                mode=mode,
                compress=False,
                reason="no_pipe_ceiling",
                codec_gbps=codec_gbps,
                eligible_bytes=eligible_bytes,
            )
        if codec_gbps < pipe * COMPRESS_MARGIN:
            return CompressDecision(
                mode=mode,
                compress=False,
                reason="pipe_outruns_codec",
                codec_gbps=codec_gbps,
                pipe_gbps=pipe,
                eligible_bytes=eligible_bytes,
            )
        reason = "codec_outruns_pipe"
    else:
        reason = "mode_forced"
    for st in eligible:
        st.compress_codec = codec_for_dtype(st.entry.dtype)
    return CompressDecision(
        mode=mode,
        compress=True,
        reason=reason,
        codec_gbps=codec_gbps,
        pipe_gbps=pipe,
        eligible_bytes=eligible_bytes,
    )


# ------------------------------------------------------- restore helpers


def check_tile_coverage(
    location: str, n_sizes: int, raw_nbytes: int, tile_raw: int
) -> None:
    """Refuse a codec entry whose comp_tile_sizes does not COVER the
    payload: per-group/whole-blob checksums of a truncated list (buggy
    external rewriter) would all verify while the destination tail is
    never written — silent garbage. Shared by the standalone and
    chunked read paths so both decoders enforce one contract."""
    if not raw_nbytes or not tile_raw:
        return
    expected_tiles = -(-raw_nbytes // tile_raw)
    if n_sizes != expected_tiles:
        raise IOError(
            f"compressed entry {location!r} records {n_sizes} tile(s) "
            f"but its {raw_nbytes}-byte payload spans {expected_tiles} "
            f"at {tile_raw} raw bytes/tile — the snapshot metadata is "
            "inconsistent"
        )


def comp_tile_offsets(comp_sizes: List[int]) -> List[int]:
    """Start offset of each compressed tile within the stored blob."""
    out = []
    off = 0
    for s in comp_sizes:
        out.append(off)
        off += int(s)
    return out


def combined_comp_checksum(entry, t0: int, t1: int) -> Optional[str]:
    """Expected checksum of compressed tiles [t0, t1) of a codec entry,
    derived from the recorded per-tile values by CRC combine over the
    COMPRESSED tile lengths — the compressed-blob counterpart of
    ``combined_tile_checksum``. None when the range is unverifiable
    (no tiles, algorithm mismatch)."""
    from . import _native

    sizes = entry.comp_tile_sizes or []
    if not entry.tile_checksums:
        if t0 == 0 and t1 == len(sizes) == 1:
            return entry.checksum
        return None
    algo = _native.checksum_algorithm()
    crcs: List[int] = []
    lengths: List[int] = []
    for i in range(t0, t1):
        tile = entry.tile_checksums[i]
        tile_algo, _, value = tile.partition(":")
        if tile_algo != algo:
            return None
        try:
            crcs.append(int(value, 16))
        except ValueError:
            return None
        lengths.append(int(sizes[i]))
    if not crcs:
        return None
    from .io_preparers.array import _fold_crcs

    return f"{algo}:{_fold_crcs(crcs, lengths):08x}"
