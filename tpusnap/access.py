"""Read-side access attribution: who reads what inside a snapshot.

Write-side observability matured over PRs 2→19 (traces, history, probes,
fleet); the READ side stopped at process-grain counters
(``storage.bytes_read``). This module is the measurement substrate for
serving-shaped restore (ROADMAP item 1): a bounded, telemetry-gated
**access ledger** recording, per physical read, the logical manifest
leaf it served, the byte range within the stored blob, the byte count,
and the source tier (``local`` / ``remote`` / ``cas`` /
``evicted-read-through``).

Design constraints, in order (the history.jsonl stance):

- **Never fail a read.** Recording and flushing are best-effort and
  exception-free at the call sites; a broken ledger costs attribution,
  never a restore.
- **Bounded.** Reads are aggregated IN MEMORY per scope, keyed by
  (leaf, location, range, source) — a restore that reads a tile 10'000
  times produces one ledger record with ``n: 10000``, not 10'000 lines.
  One JSONL line per aggregation bucket is appended at scope exit; the
  per-reader file is size-bounded by ``TPUSNAP_ACCESS_LEDGER_MAX_BYTES``
  with single-generation rotation (``<file>.1``, the JSONL metrics-sink
  scheme — rotation keeps recent reads visible to ``heatmap`` while
  bounding disk).
- **Crash-tolerant.** Appends go through
  :func:`history.append_jsonl_line` — one O_APPEND write per line, so
  tens of concurrent reader processes interleave whole lines and a
  torn final line is isolated and skipped on load.
- **Sidecar, not KV.** Ledgers live under the LOCAL
  ``TPUSNAP_TELEMETRY_DIR/access/<digest>/<job_id>.jsonl`` — the
  snapshot itself is immutable once committed (same reasoning as
  restore traces), and a KV store would add a dependency to the one
  path that must work during disaster recovery. Readers that share a
  telemetry dir (a serving fleet on one host, or fleetsim's reader
  cohort) are merged by ``tpusnap heatmap``; readers on different
  hosts merge at the fleet layer via their published reader records.

The ambient-scope pattern mirrors :mod:`tpusnap.telemetry`: a
thread-local current ledger installed by ``Snapshot._restore_locked`` /
``read_object`` and consulted once per read inside the scheduler's
``_ReadPipeline`` (the single seam every read path — budget-tiled
restores, tile-grain compressed random access, ``read_object``, CAS
ref-translated reads — already converges on).

Monotonic-only invariant: the one wall-clock timestamp (``ts``) goes
through the injectable ``_wall`` seam (TPS002).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .history import append_jsonl_line
from .knobs import (
    get_access_ledger_max_bytes,
    get_job_id,
    get_telemetry_dir,
    is_access_ledger_enabled,
)

logger = logging.getLogger(__name__)

ACCESS_DIRNAME = "access"

# Wall-clock seam: timestamps only, never duration math (tests inject).
_wall = time.time

# Source tiers a read can be attributed to. Plugins stamp the exotic
# ones on ReadIO.source; the scope's default covers the rest.
KNOWN_SOURCES = ("local", "remote", "cas", "evicted-read-through")


def access_dir(snapshot_path: str) -> str:
    """Local directory holding every reader's ledger for
    ``snapshot_path`` (digest-keyed like restore traces, so every
    spelling of one destination lands in one place)."""
    from .progress import _path_digest

    return os.path.join(
        get_telemetry_dir(), ACCESS_DIRNAME, _path_digest(snapshot_path)
    )


class AccessLedger:
    """Per-reader, per-scope read aggregation. One instance spans one
    read scope (a restore, or one ``read_object`` call); ``flush()``
    appends its buckets to this reader's ledger file. Thread-safe the
    cheap way (one lock around a dict update) because consumer
    callbacks may record from executor threads."""

    def __init__(
        self, snapshot_path: str, default_source: str = "local"
    ) -> None:
        self.snapshot_path = snapshot_path
        self.job_id = get_job_id()
        self.default_source = default_source
        self.path = os.path.join(
            access_dir(snapshot_path), f"{self.job_id}.jsonl"
        )
        # (logical_path, location, start, end, source) -> [reads, bytes]
        self._buckets: Dict[
            Tuple[str, str, int, int, str], List[int]
        ] = {}
        # Scope-lifetime totals (survive flushes — the fleet reader
        # record and the restore summary read them after the ledger
        # drained to disk). ``_ranges`` dedups distinct byte ranges per
        # location for the working-set computation.
        self._cum_reads = 0
        self._cum_bytes = 0
        self._ranges: Dict[str, set] = {}
        self._lock = threading.Lock()

    def record(
        self,
        logical_path: str,
        location: str,
        start: int,
        end: int,
        nbytes: int,
        source: Optional[str] = None,
    ) -> None:
        """Attribute one physical read (or one member of a merged
        spanning read) of ``location[start:end]`` to manifest leaf
        ``logical_path``."""
        if not logical_path:
            return
        key = (
            logical_path,
            location,
            int(start),
            int(end),
            source or self.default_source,
        )
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                self._buckets[key] = [1, int(nbytes)]
            else:
                bucket[0] += 1
                bucket[1] += int(nbytes)
            self._cum_reads += 1
            self._cum_bytes += int(nbytes)
            self._ranges.setdefault(location, set()).add(
                (int(start), int(end))
            )

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._cum_bytes

    @property
    def total_reads(self) -> int:
        with self._lock:
            return self._cum_reads

    def working_set_bytes(self) -> int:
        """Distinct stored bytes this scope touched (union of read
        ranges per location) — the hot-tile working set ``tune`` sizes
        the restore budget against."""
        with self._lock:
            ranges = {
                loc: list(rs) for loc, rs in self._ranges.items()
            }
        return sum(_union_length(rs) for rs in ranges.values())

    def flush(self) -> None:
        """Append this scope's buckets to the reader's ledger file —
        one whole line per bucket, rotated when past the size bound.
        Best-effort: failures log at DEBUG and drop the records."""
        with self._lock:
            buckets = dict(self._buckets)
            self._buckets.clear()
        if not buckets:
            return
        ts = round(_wall(), 3)
        try:
            self._rotate_if_needed()
            for (lp, loc, start, end, source), (n, nbytes) in sorted(
                buckets.items()
            ):
                line = json.dumps(
                    {
                        "v": 1,
                        "ts": ts,
                        "job_id": self.job_id,
                        "lp": lp,
                        "loc": loc,
                        "range": [start, end],
                        "n": n,
                        "bytes": nbytes,
                        "src": source,
                    },
                    separators=(",", ":"),
                )
                append_jsonl_line(self.path, line)
        except Exception:
            logger.debug("access ledger flush failed", exc_info=True)

    def _rotate_if_needed(self) -> None:
        max_bytes = get_access_ledger_max_bytes()
        try:
            if os.path.getsize(self.path) > max_bytes:
                os.replace(self.path, self.path + ".1")
        except OSError:
            return


# ----------------------------------------------------- ambient scope

_tls = threading.local()


def current() -> Optional[AccessLedger]:
    """The ledger installed on this thread, or None (recording off)."""
    return getattr(_tls, "current", None)


@contextmanager
def use(ledger: Optional[AccessLedger]):
    """Install ``ledger`` as this thread's ambient recorder for the
    duration (the telemetry.use pattern). Works across the scheduler's
    event loop because ``run_on_loop`` drives it on the calling
    thread."""
    prior = getattr(_tls, "current", None)
    _tls.current = ledger
    try:
        yield ledger
    finally:
        _tls.current = prior


def open_ledger(
    snapshot_path: str, default_source: str = "local"
) -> Optional[AccessLedger]:
    """``read_scope``'s knob gate without the context manager: a live
    ledger (or None when recording is off) whose flush timing the
    caller controls. The restore path pairs this with :func:`use` and
    flushes only after its telemetry wall has closed, so attribution
    I/O never shows up as unspanned restore time."""
    if not is_access_ledger_enabled():
        return None
    return AccessLedger(snapshot_path, default_source=default_source)


@contextmanager
def read_scope(snapshot_path: str, default_source: str = "local"):
    """The one call sites use: open a ledger for one read scope when
    the knob allows, record through it ambiently, flush at exit.
    Yields the ledger (or None when recording is off) so the caller
    can stamp scope totals into its own telemetry."""
    ledger = open_ledger(snapshot_path, default_source=default_source)
    if ledger is None:
        yield None
        return
    try:
        with use(ledger):
            yield ledger
    finally:
        try:
            ledger.flush()
        except Exception:
            logger.debug("access ledger flush failed", exc_info=True)


def default_source_for_plugin(label: str) -> str:
    """Map a storage-plugin label (``storage_plugin_label``) to the
    ambient source tier of its plain reads. Conservative: anything not
    recognizably local counts as remote."""
    lab = (label or "").lower()
    if lab.startswith(("fs", "chaos+fs", "tier", "cas+fs")):
        return "local"
    return "remote"


# --------------------------------------------------------------- loading


def load_ledger_records(
    snapshot_path: str, access_root: Optional[str] = None
) -> List[Dict[str, Any]]:
    """Every parseable record from every reader's ledger (rotated
    generation first so ordering is roughly chronological). Torn or
    corrupt lines are skipped, never raised. ``access_root`` overrides
    the digest-derived directory (tests, copied telemetry dirs)."""
    root = access_root or access_dir(snapshot_path)
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return out
    paths: List[str] = []
    for name in names:
        if name.endswith(".jsonl.1"):
            paths.append(os.path.join(root, name))
    for name in names:
        if name.endswith(".jsonl"):
            paths.append(os.path.join(root, name))
    for p in paths:
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for ln in data.split(b"\n"):
            if not ln.strip():
                continue
            try:
                rec = json.loads(ln)
            except Exception:
                continue
            if isinstance(rec, dict) and rec.get("lp"):
                out.append(rec)
    return out


# --------------------------------------------------------------- heatmap


def _leaf_stored_nbytes(entry) -> int:
    """Stored (on-disk) payload bytes of one manifest leaf — the
    coverage denominator. Differs from the logical ``entry_nbytes``
    exactly when the entry is compressed (reads happen in stored-blob
    coordinates, so coverage must too)."""
    from .inspect import entry_nbytes
    from .manifest import (
        ChunkedTensorEntry,
        ShardedEntry,
        TensorEntry,
    )

    if isinstance(entry, TensorEntry):
        if entry.codec and entry.comp_tile_sizes:
            return sum(int(s) for s in entry.comp_tile_sizes)
        return entry_nbytes(entry)
    if isinstance(entry, ChunkedTensorEntry):
        return sum(_leaf_stored_nbytes(c.tensor) for c in entry.chunks)
    if isinstance(entry, ShardedEntry):
        return sum(_leaf_stored_nbytes(s.tensor) for s in entry.shards)
    return entry_nbytes(entry)


def _union_length(intervals: List[Tuple[int, int]]) -> int:
    """Total length covered by a set of [start, end) intervals."""
    if not intervals:
        return 0
    intervals.sort()
    covered = 0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            covered += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    covered += cur_e - cur_s
    return covered


def snapshot_stored_nbytes(metadata) -> int:
    """Total stored payload bytes of a snapshot — the denominator of
    whole-snapshot coverage and amplification (compressed entries count
    their stored, not logical, size)."""
    from .manifest import PrimitiveEntry, is_container_entry

    total = 0
    for _, entry in metadata.manifest.items():
        if is_container_entry(entry) or isinstance(entry, PrimitiveEntry):
            continue
        total += _leaf_stored_nbytes(entry)
    return total


def compute_heatmap(
    records: List[Dict[str, Any]], metadata
) -> Dict[str, Any]:
    """Merge reader ledger ``records`` against a snapshot's manifest
    into the per-leaf heatmap: read counts, bytes, distinct readers,
    per-leaf and whole-snapshot **coverage** (bytes ever read ÷ stored
    bytes) and **read amplification** (aggregate bytes read ÷ stored
    bytes)."""
    from .manifest import PrimitiveEntry, is_container_entry

    # Leaves are keyed by the rank-STRIPPED logical path — the form
    # readers see (per-rank manifest views strip the prefix, and that is
    # what the ledger records). A path present on several ranks (private
    # per-rank state, per-rank shard subsets of one sharded entry) merges
    # into one leaf whose stored size is the sum; replicated entries were
    # consolidated onto rank 0 at take time and count once.
    leaves: Dict[str, Dict[str, Any]] = {}
    stored_total = 0
    for key, entry in metadata.manifest.items():
        if is_container_entry(entry) or isinstance(entry, PrimitiveEntry):
            continue
        _, _, lp = key.partition("/")
        stored = _leaf_stored_nbytes(entry)
        stored_total += stored
        leaf = leaves.get(lp)
        if leaf is None:
            leaves[lp] = {
                "path": lp,
                "stored_bytes": stored,
                "bytes_read": 0,
                "reads": 0,
                "readers": set(),
                "sources": {},
                "_intervals": {},  # location -> [(start, end)]
            }
        else:
            leaf["stored_bytes"] += stored

    readers: Dict[str, Dict[str, int]] = {}
    unknown_bytes = 0
    range_counts: Dict[Tuple[str, str, int, int], Dict[str, int]] = {}
    for rec in records:
        lp = str(rec.get("lp", ""))
        n = int(rec.get("n", 1) or 1)
        nbytes = int(rec.get("bytes", 0) or 0)
        job = str(rec.get("job_id", "?"))
        src = str(rec.get("src", "local"))
        r = readers.setdefault(job, {"reads": 0, "bytes_read": 0})
        r["reads"] += n
        r["bytes_read"] += nbytes
        leaf = leaves.get(lp)
        if leaf is None:
            unknown_bytes += nbytes
            continue
        leaf["bytes_read"] += nbytes
        leaf["reads"] += n
        leaf["readers"].add(job)
        leaf["sources"][src] = leaf["sources"].get(src, 0) + nbytes
        rng = rec.get("range")
        if (
            isinstance(rng, (list, tuple))
            and len(rng) == 2
            and rng[1] > rng[0]
        ):
            loc = str(rec.get("loc", ""))
            leaf["_intervals"].setdefault(loc, []).append(
                (int(rng[0]), int(rng[1]))
            )
            rkey = (lp, loc, int(rng[0]), int(rng[1]))
            agg = range_counts.setdefault(rkey, {"n": 0, "bytes": 0})
            agg["n"] += n
            agg["bytes"] += nbytes

    read_total = sum(r["bytes_read"] for r in readers.values())
    covered_total = 0
    leaf_rows: List[Dict[str, Any]] = []
    for lp, leaf in leaves.items():
        union = sum(
            _union_length(iv) for iv in leaf["_intervals"].values()
        )
        covered = min(union, leaf["stored_bytes"])
        covered_total += covered
        stored = leaf["stored_bytes"]
        leaf_rows.append(
            {
                "path": lp,
                "stored_bytes": stored,
                "bytes_read": leaf["bytes_read"],
                "reads": leaf["reads"],
                "readers": len(leaf["readers"]),
                "coverage": (covered / stored) if stored else 0.0,
                "amplification": (leaf["bytes_read"] / stored)
                if stored
                else 0.0,
                "sources": dict(leaf["sources"]),
            }
        )
    leaf_rows.sort(key=lambda row: (-row["bytes_read"], row["path"]))

    hot_ranges = [
        {
            "path": lp,
            "location": loc,
            "range": [start, end],
            "reads": agg["n"],
            "bytes": agg["bytes"],
        }
        for (lp, loc, start, end), agg in range_counts.items()
    ]
    hot_ranges.sort(
        key=lambda h: (-h["reads"], -h["bytes"], h["path"], h["range"])
    )

    coverage = (covered_total / stored_total) if stored_total else 0.0
    amplification = (read_total / stored_total) if stored_total else 0.0
    return {
        "v": 1,
        "snapshot_bytes": stored_total,
        "bytes_read": read_total,
        "unattributed_bytes": unknown_bytes,
        "coverage": round(coverage, 6),
        "amplification": round(amplification, 6),
        "readers": {
            job: dict(stats) for job, stats in sorted(readers.items())
        },
        "n_readers": len(readers),
        "leaves": leaf_rows,
        "hot_ranges": hot_ranges,
    }


def location_read_counts(
    records: List[Dict[str, Any]]
) -> Dict[str, int]:
    """Aggregate read counts per storage location — the popularity
    signal ``gc --evict-local`` uses to evict cold blobs first."""
    out: Dict[str, int] = {}
    for rec in records:
        loc = str(rec.get("loc", "") or "")
        if not loc:
            continue
        out[loc] = out.get(loc, 0) + int(rec.get("n", 1) or 1)
    return out


def iter_access_roots(telemetry_dir: Optional[str] = None) -> Iterator[str]:
    """Every per-digest access directory under a telemetry dir (for
    tooling that scans without knowing the snapshot path)."""
    root = os.path.join(
        telemetry_dir or get_telemetry_dir(), ACCESS_DIRNAME
    )
    try:
        names = sorted(os.listdir(root))
    except OSError:
        return
    for name in names:
        p = os.path.join(root, name)
        if os.path.isdir(p):
            yield p
