"""RSS profiler — validate that the memory-budget-gated pipeline holds.

Counterpart of /root/reference/torchsnapshot/rss_profiler.py:32-56: a
background thread samples the process RSS delta on an interval inside a
context manager; benchmarks assert the peak delta stays within the
configured memory budget. :class:`RSSSampler` is the start/stop form
the telemetry subsystem embeds so every take's summary carries its
peak-RSS figure.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Generator, List, Optional

import psutil

_DEFAULT_INTERVAL_SEC = 0.1


class RSSSampler:
    """Background-thread RSS-delta sampler with explicit start/stop.

    Samples ``process RSS - baseline`` into ``deltas`` every
    ``interval_sec`` between :meth:`start` and :meth:`stop`; ``stop``
    always appends one final sample, so even a context shorter than the
    interval records a delta. ``stop`` is idempotent and joins the
    thread (no samples land after it returns)."""

    def __init__(
        self,
        deltas: Optional[List[int]] = None,
        interval_sec: float = _DEFAULT_INTERVAL_SEC,
    ) -> None:
        self.deltas: List[int] = deltas if deltas is not None else []
        self.interval_sec = interval_sec
        self._process = psutil.Process()
        self._baseline = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RSSSampler":
        if self._thread is not None:
            raise RuntimeError("RSSSampler already started")
        self._baseline = self._process.memory_info().rss
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._sample_loop, name="tpusnap-rss", daemon=True
        )
        self._thread.start()
        return self

    def _sample_loop(self) -> None:
        # Event.wait doubles as the interval sleep AND the prompt-stop
        # signal: a stop() mid-interval returns immediately instead of
        # holding the caller for a full sleep.
        while not self._stop.wait(self.interval_sec):
            self.deltas.append(self._process.memory_info().rss - self._baseline)

    def stop(self) -> List[int]:
        if self._thread is not None:
            self._stop.set()
            self._thread.join()
            self._thread = None
            # Final delta: a sub-interval context still records one.
            self.deltas.append(self._process.memory_info().rss - self._baseline)
        return self.deltas

    @property
    def peak_delta(self) -> int:
        return max(self.deltas, default=0)


@contextmanager
def measure_rss_deltas(
    rss_deltas: List[int], interval_sec: float = _DEFAULT_INTERVAL_SEC
) -> Generator[None, None, None]:
    """Append RSS deltas (bytes, relative to entry) to ``rss_deltas`` every
    ``interval_sec`` until the context exits (reference rss_profiler.py:33-56).
    """
    sampler = RSSSampler(deltas=rss_deltas, interval_sec=interval_sec)
    sampler.start()
    try:
        yield
    finally:
        sampler.stop()
