"""RSS profiler — validate that the memory-budget-gated pipeline holds.

Counterpart of /root/reference/torchsnapshot/rss_profiler.py:32-56: a
background thread samples the process RSS delta on an interval inside a
context manager; benchmarks assert the peak delta stays within the
configured memory budget.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Generator, List

import psutil

_DEFAULT_INTERVAL_SEC = 0.1


@contextmanager
def measure_rss_deltas(
    rss_deltas: List[int], interval_sec: float = _DEFAULT_INTERVAL_SEC
) -> Generator[None, None, None]:
    """Append RSS deltas (bytes, relative to entry) to ``rss_deltas`` every
    ``interval_sec`` until the context exits (reference rss_profiler.py:33-56).
    """
    process = psutil.Process()
    baseline = process.memory_info().rss
    stop = threading.Event()

    def sample() -> None:
        while not stop.is_set():
            rss_deltas.append(process.memory_info().rss - baseline)
            time.sleep(interval_sec)

    thread = threading.Thread(target=sample, name="tpusnap-rss", daemon=True)
    thread.start()
    try:
        yield
    finally:
        stop.set()
        thread.join()
        rss_deltas.append(process.memory_info().rss - baseline)
