"""RNG state capture with take/restore invariance.

Counterpart of /root/reference/torchsnapshot/rng_state.py:13. JAX's own
RNG is explicit (PRNG keys live in user state and are checkpointed as
ordinary arrays), so the global RNGs worth capturing on the host are
python's ``random`` and numpy's legacy global generator. The invariant
enforced by Snapshot (reference snapshot.py:338-374) is preserved: taking
a snapshot leaves RNG state exactly as it was, and restoring reproduces
the state at save time.
"""

import pickle
import random
from typing import Any, Dict

import numpy as np


class RNGState:
    def state_dict(self) -> Dict[str, Any]:
        return {
            "python_random": pickle.dumps(random.getstate()),
            "numpy_random": pickle.dumps(np.random.get_state()),
        }

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        random.setstate(pickle.loads(state_dict["python_random"]))
        np.random.set_state(pickle.loads(state_dict["numpy_random"]))
