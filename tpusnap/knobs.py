"""Tunable knobs, each an env var with a context-manager override for tests.

TPU-native counterpart of the reference's knob system
(/root/reference/torchsnapshot/knobs.py:21-96). Defaults match the
reference: 512MB max chunk, 512MB max shard, 128MB slab threshold.
"""

import contextlib
import logging
import os
import threading
from typing import Dict, Generator, Optional

logger = logging.getLogger(__name__)

_MAX_CHUNK_SIZE_ENV_VAR = "TPUSNAP_MAX_CHUNK_SIZE_BYTES"
_MAX_SHARD_SIZE_ENV_VAR = "TPUSNAP_MAX_SHARD_SIZE_BYTES"
_SLAB_SIZE_THRESHOLD_ENV_VAR = "TPUSNAP_SLAB_SIZE_THRESHOLD_BYTES"
_DISABLE_BATCHING_ENV_VAR = "TPUSNAP_DISABLE_BATCHING"
_DISABLE_DEVICE_BATCHING_ENV_VAR = "TPUSNAP_DISABLE_DEVICE_BATCHING"
_DISABLE_PARTITIONER_ENV_VAR = "TPUSNAP_DISABLE_PARTITIONER"
_MEMORY_BUDGET_ENV_VAR = "TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES"
_DISABLE_NATIVE_ENV_VAR = "TPUSNAP_DISABLE_NATIVE"
_DISABLE_DIRECT_IO_ENV_VAR = "TPUSNAP_DISABLE_DIRECT_IO"
_DISABLE_DONTCACHE_ENV_VAR = "TPUSNAP_DISABLE_DONTCACHE"
_DISABLE_CHECKSUM_ENV_VAR = "TPUSNAP_DISABLE_CHECKSUM"
_DIRECT_IO_QD_ENV_VAR = "TPUSNAP_DIRECT_IO_QD"
_DIRECT_IO_CHUNK_ENV_VAR = "TPUSNAP_DIRECT_IO_CHUNK_BYTES"
_TILE_CHECKSUM_ENV_VAR = "TPUSNAP_TILE_CHECKSUM_BYTES"
_SCRUB_CONCURRENCY_ENV_VAR = "TPUSNAP_SCRUB_CONCURRENCY"
_RECORD_DEDUP_HASHES_ENV_VAR = "TPUSNAP_RECORD_DEDUP_HASHES"
_DURABLE_COMMIT_ENV_VAR = "TPUSNAP_DURABLE_COMMIT"
_TELEMETRY_ENV_VAR = "TPUSNAP_TELEMETRY"
_DISABLE_JOURNAL_ENV_VAR = "TPUSNAP_DISABLE_JOURNAL"
_STALL_DEADLINE_ENV_VAR = "TPUSNAP_STALL_DEADLINE_S"
_HEARTBEAT_INTERVAL_ENV_VAR = "TPUSNAP_HEARTBEAT_INTERVAL_S"
_TELEMETRY_DIR_ENV_VAR = "TPUSNAP_TELEMETRY_DIR"
_METRICS_EXPORT_ENV_VAR = "TPUSNAP_METRICS_EXPORT"
_METRICS_DIR_ENV_VAR = "TPUSNAP_METRICS_DIR"
_HISTORY_ENV_VAR = "TPUSNAP_HISTORY"
_HISTORY_MAX_BYTES_ENV_VAR = "TPUSNAP_HISTORY_MAX_BYTES"
_STAGE_THREADS_ENV_VAR = "TPUSNAP_STAGE_THREADS"
_ASYNC_STAGE_WINDOW_ENV_VAR = "TPUSNAP_ASYNC_STAGE_WINDOW_BYTES"
_ASYNC_COW_ENV_VAR = "TPUSNAP_ASYNC_COW"
_PROBE_ENV_VAR = "TPUSNAP_PROBE"
_PROBE_INTERVAL_ENV_VAR = "TPUSNAP_PROBE_INTERVAL_BYTES"
_PROBE_BYTES_ENV_VAR = "TPUSNAP_PROBE_BYTES"
_AUTOTUNE_ENV_VAR = "TPUSNAP_AUTOTUNE"
_STAGING_POOL_ENV_VAR = "TPUSNAP_STAGING_POOL_BYTES"
_LOCKCHECK_ENV_VAR = "TPUSNAP_LOCKCHECK"
_FLIGHT_ENV_VAR = "TPUSNAP_FLIGHT"
_FLIGHT_RING_ENV_VAR = "TPUSNAP_FLIGHT_RING"
_FLIGHT_FLUSH_ENV_VAR = "TPUSNAP_FLIGHT_FLUSH_S"
_SLO_RPO_ENV_VAR = "TPUSNAP_SLO_RPO_S"
_SLO_RTO_ENV_VAR = "TPUSNAP_SLO_RTO_S"
_SLO_STREAM_CADENCE_X_ENV_VAR = "TPUSNAP_SLO_STREAM_CADENCE_X"
_DELTA_CADENCE_ENV_VAR = "TPUSNAP_DELTA_CADENCE_S"
_DELTA_MAX_CHAIN_ENV_VAR = "TPUSNAP_DELTA_MAX_CHAIN"
_TIER_DRAIN_ENV_VAR = "TPUSNAP_TIER_DRAIN"
_TIER_OP_DEADLINE_ENV_VAR = "TPUSNAP_TIER_OP_DEADLINE_S"
_TIER_OUTAGE_THRESHOLD_ENV_VAR = "TPUSNAP_TIER_OUTAGE_THRESHOLD"
_TIER_BACKOFF_CAP_ENV_VAR = "TPUSNAP_TIER_BACKOFF_CAP_S"
_TIER_LOCAL_RETENTION_ENV_VAR = "TPUSNAP_TIER_LOCAL_RETENTION_S"
_COMPRESS_ENV_VAR = "TPUSNAP_COMPRESS"
_COMPRESS_MIN_BLOB_ENV_VAR = "TPUSNAP_COMPRESS_MIN_BLOB_BYTES"
_BARRIER_TIMEOUT_ENV_VAR = "TPUSNAP_BARRIER_TIMEOUT_S"
_LIVENESS_TTL_ENV_VAR = "TPUSNAP_LIVENESS_TTL_S"
_RANK_FAILURE_ENV_VAR = "TPUSNAP_RANK_FAILURE"
_JOB_ID_ENV_VAR = "TPUSNAP_JOB_ID"
_FLEET_DIR_ENV_VAR = "TPUSNAP_FLEET_DIR"
_CAS_DIR_ENV_VAR = "TPUSNAP_CAS_DIR"
_CAS_GRACE_ENV_VAR = "TPUSNAP_CAS_GRACE_S"
_CAS_LEASE_TTL_ENV_VAR = "TPUSNAP_CAS_LEASE_TTL_S"
_CAS_REMOTE_ENV_VAR = "TPUSNAP_CAS_REMOTE"
_ACCESS_LEDGER_ENV_VAR = "TPUSNAP_ACCESS_LEDGER"
_ACCESS_LEDGER_MAX_BYTES_ENV_VAR = "TPUSNAP_ACCESS_LEDGER_MAX_BYTES"

_DEFAULT_MAX_CHUNK_SIZE_BYTES = 512 * 1024 * 1024
_DEFAULT_MAX_SHARD_SIZE_BYTES = 512 * 1024 * 1024
_DEFAULT_SLAB_SIZE_THRESHOLD_BYTES = 128 * 1024 * 1024
# Per-file O_DIRECT write queue depth / chunk size (measured on virtio:
# QD 2 x 32 MiB out-runs single-in-flight 8 MiB by ~30% aggregate).
_DEFAULT_DIRECT_IO_QD = 2
_DEFAULT_DIRECT_IO_CHUNK_BYTES = 32 * 1024 * 1024
# Row-tile granularity for tile-grain checksums on large dense blobs
# (the verifiable unit of memory-budgeted partial reads).
_DEFAULT_TILE_CHECKSUM_BYTES = 16 * 1024 * 1024
# Staging window of a pipelined async take: the blocked window stages at
# most this much staging COST before control returns to training, and it
# is the effective in-flight staging budget of the background drain —
# so blocked time and clone RSS are both O(window), not O(state). Two
# max-size chunks (2 x 512 MB, cost 2x while the clone is held) fit, so
# the drain overlaps clone(N+1) with write(N) instead of serializing.
_DEFAULT_ASYNC_STAGE_WINDOW_BYTES = 2 * 1024 * 1024 * 1024
# In-take roofline probes: one probe segment per this many payload
# bytes written, each probe writing (and reading back) this many raw
# bytes through the take's own plugin stack. At the defaults the probe
# overhead is bounded by PROBE_BYTES / PROBE_INTERVAL ≈ 3% of the
# take's I/O, and a 20 GB take self-measures its ceiling ~10 times.
_DEFAULT_PROBE_INTERVAL_BYTES = 2 * 1024 * 1024 * 1024
_DEFAULT_PROBE_BYTES = 64 * 1024 * 1024
_DEFAULT_STAGING_POOL_BYTES = 4 * 1024 * 1024 * 1024


# ------------------------------------------------- tuned-plan overlay
#
# `tpusnap tune` reconcile seam (TPUSNAP_AUTOTUNE=1): an applied plan's
# knob values live HERE, one layer below the environment, and every
# knob lookup consults the env first — so an explicitly-set env var
# always beats the tuner, per lookup, with no copying of tuner values
# into os.environ (which a later explicit `export` could not then
# override, and which child processes would inherit as if the operator
# had set them).
_tuned_lock = threading.Lock()
_tuned_overlay: Dict[str, str] = {}
_tuned_plan_id: Optional[str] = None


def apply_tuned_plan(plan_id: str, knobs: Dict[str, str]) -> Dict[str, str]:
    """Install a tuner plan's knob values as the fallback layer. Knobs
    the environment already sets explicitly are SKIPPED (explicit env
    always wins). Returns the subset actually applied — what the
    take/restore stamps into its history event as ``tuned.knobs``."""
    applied: Dict[str, str] = {}
    with _tuned_lock:
        global _tuned_plan_id
        _tuned_overlay.clear()
        for name, value in knobs.items():
            if name in os.environ:
                continue
            _tuned_overlay[name] = str(value)
            applied[name] = str(value)
        _tuned_plan_id = plan_id if applied else None
    return applied


def clear_tuned_plan() -> None:
    with _tuned_lock:
        global _tuned_plan_id
        _tuned_overlay.clear()
        _tuned_plan_id = None


def tuned_plan() -> Optional[Dict[str, object]]:
    """The currently-applied plan (``{plan_id, knobs}``) or None."""
    with _tuned_lock:
        if _tuned_plan_id is None or not _tuned_overlay:
            return None
        return {"plan_id": _tuned_plan_id, "knobs": dict(_tuned_overlay)}


def _env_get(name: str, default: Optional[str] = None) -> Optional[str]:
    """Knob lookup: explicit environment first, then the applied tuner
    plan, then the default."""
    val = os.environ.get(name)
    if val is not None:
        return val
    with _tuned_lock:
        val = _tuned_overlay.get(name)
    return val if val is not None else default


def _get_float_env(name: str, default: float) -> float:
    val = _env_get(name)
    if val is None:
        return default
    try:
        return float(val)
    except ValueError:
        logger.warning("Ignoring non-numeric %s=%r", name, val)
        return default


def _get_int_env(name: str, default: int) -> int:
    val = _env_get(name)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError:
        logger.warning("Ignoring non-integer %s=%r", name, val)
        return default


def get_max_chunk_size_bytes() -> int:
    return _get_int_env(_MAX_CHUNK_SIZE_ENV_VAR, _DEFAULT_MAX_CHUNK_SIZE_BYTES)


def get_max_shard_size_bytes() -> int:
    return _get_int_env(_MAX_SHARD_SIZE_ENV_VAR, _DEFAULT_MAX_SHARD_SIZE_BYTES)


def get_slab_size_threshold_bytes() -> int:
    return _get_int_env(
        _SLAB_SIZE_THRESHOLD_ENV_VAR, _DEFAULT_SLAB_SIZE_THRESHOLD_BYTES
    )


def is_batching_disabled() -> bool:
    return os.environ.get(_DISABLE_BATCHING_ENV_VAR, "0") == "1"


def is_device_batching_disabled() -> bool:
    return os.environ.get(_DISABLE_DEVICE_BATCHING_ENV_VAR, "0") == "1"


def is_partitioner_disabled() -> bool:
    return os.environ.get(_DISABLE_PARTITIONER_ENV_VAR, "0") == "1"


def is_native_disabled() -> bool:
    return os.environ.get(_DISABLE_NATIVE_ENV_VAR, "0") == "1"


def is_direct_io_disabled() -> bool:
    """O_DIRECT file writes (fs plugin): on by default; the native layer
    falls back to buffered writes automatically on filesystems without
    O_DIRECT support, so this knob exists for debugging/bench A-Bs."""
    return os.environ.get(_DISABLE_DIRECT_IO_ENV_VAR, "0") == "1"


def is_checksum_disabled() -> bool:
    """Per-blob CRC32C integrity checksums: recorded at stage time and
    verified on read, both on by default. Disable for A/B benchmarking or
    when reading snapshots from untrusted-layout sources only."""
    return os.environ.get(_DISABLE_CHECKSUM_ENV_VAR, "0") == "1"


def is_dontcache_disabled() -> bool:
    """Uncached buffered writes (RWF_DONTCACHE, Linux 6.14+) for
    unaligned sources: on by default; the native layer falls back to the
    O_DIRECT bounce pipeline automatically where unsupported."""
    return os.environ.get(_DISABLE_DONTCACHE_ENV_VAR, "0") == "1"


def get_direct_io_qd() -> int:
    """In-flight chunk writes per file on the O_DIRECT path."""
    return _get_int_env(_DIRECT_IO_QD_ENV_VAR, _DEFAULT_DIRECT_IO_QD)


def get_direct_io_chunk_bytes() -> int:
    return _get_int_env(
        _DIRECT_IO_CHUNK_ENV_VAR, _DEFAULT_DIRECT_IO_CHUNK_BYTES
    )


def get_tile_checksum_bytes() -> int:
    return _get_int_env(_TILE_CHECKSUM_ENV_VAR, _DEFAULT_TILE_CHECKSUM_BYTES)


def get_scrub_concurrency() -> int:
    """Blob ranges the integrity scrub keeps in flight (peak memory is
    this many scratch buffers). Raise for high-latency storage (cloud
    scrubs), lower for tight-memory hosts."""
    return max(1, _get_int_env(_SCRUB_CONCURRENCY_ENV_VAR, 4))


def is_durable_commit_enabled() -> bool:
    """Make a returned take survive power loss: every blob file is
    fsync'd after its write, and the metadata commit fsyncs its temp
    file, renames, then fsyncs every directory the snapshot created —
    data, dirents and the commit record all on stable storage, in that
    order. Off by default: the fsyncs after a multi-GB take force the
    device to flush everything just written (~2 s measured on the dev
    host's virtio disk), a cost the baselines tpusnap is benchmarked
    against (torch.save, the reference) never pay. Without it the
    commit is still crash-SAFE (temp+rename: never torn, at worst
    invisible/incomplete-and-invisible); metadata REWRITES of committed
    snapshots (materialize, retention) fsync their own commit
    unconditionally — there the flush is cheap and the downside is
    destroying good state."""
    return os.environ.get(_DURABLE_COMMIT_ENV_VAR, "0") == "1"


def is_dedup_hash_recording_forced() -> bool:
    """Record 64-bit per-tile dedup hashes on EVERY take, not just
    incremental ones — set on the FULL base take of a planned
    incremental chain so the first increment can already make
    tile-grain skip decisions against it (otherwise the chain reaches
    tile grain from the second increment on). Costs one extra fused
    hash lane (~2x the hash pass) on large tiled blobs."""
    return os.environ.get(_RECORD_DEDUP_HASHES_ENV_VAR, "0") == "1"


def is_journal_disabled() -> bool:
    """Crash-safe take journal (:mod:`tpusnap.lifecycle`): on by default
    — rank 0 marks the take before any blob write (so fsck can classify
    a SIGKILLed take) and every rank records per-blob completion hashes
    (the salvage-resume evidence; one fused CRC32C+XXH64 pass per
    non-slab blob on the write path, overlapped with storage I/O on a
    worker thread). ``TPUSNAP_DISABLE_JOURNAL=1`` turns the whole layer
    off for maximum-throughput A/B benchmarking: crashed takes then
    classify as foreign and retakes restart from byte zero."""
    return os.environ.get(_DISABLE_JOURNAL_ENV_VAR, "0") == "1"


def is_telemetry_enabled() -> bool:
    """Per-take SPAN capture + persisted Chrome traces
    (:mod:`tpusnap.telemetry`): on by default — the disabled path of a
    span is a dict lookup, and the tier-1 overhead guard bounds the
    enabled cost at <10% on a small take. ``TPUSNAP_TELEMETRY=0``
    disables span capture and trace persistence; COUNTERS (retries,
    faults, pool hits, bytes written) stay on either way."""
    return os.environ.get(_TELEMETRY_ENV_VAR, "1") != "0"


def get_stall_deadline_s() -> float:
    """No-forward-progress window after which a take's stall watchdog
    (:mod:`tpusnap.progress`) emits its structured WARNING naming the
    blocked op and — when attribution is available — the ranks that have
    not arrived at the barrier it is stuck in. Well under the 600 s
    barrier timeout by design: the point is an actionable log in
    seconds, not another timeout."""
    return max(0.1, _get_float_env(_STALL_DEADLINE_ENV_VAR, 30.0))


def get_heartbeat_interval_s() -> float:
    """Cadence of the per-rank heartbeat pump: progress records are
    published at most once per interval (and only when something
    changed, with a periodic keep-alive) — O(world) KV keys per
    interval, never per op."""
    return max(0.02, _get_float_env(_HEARTBEAT_INTERVAL_ENV_VAR, 0.5))


def get_telemetry_dir() -> str:
    """Local directory for telemetry that cannot live inside the
    snapshot — restore traces (the snapshot is immutable once
    committed). Defaults to a stable per-user tmp path (uid-suffixed:
    a shared-host /tmp dir owned by the first user would EACCES every
    other user's trace writes); override with
    ``TPUSNAP_TELEMETRY_DIR``."""
    import tempfile

    uid = os.getuid() if hasattr(os, "getuid") else "u"
    return os.environ.get(_TELEMETRY_DIR_ENV_VAR) or os.path.join(
        tempfile.gettempdir(), f"tpusnap-telemetry-{uid}"
    )


_KNOWN_METRICS_FORMATS = ("prom", "jsonl")
# Unknown-format tokens already warned about: get_metrics_export runs at
# every take/restore begin, and one typo must not spam a WARNING per
# checkpoint for the job's whole life.
_warned_metrics_formats: set = set()


def get_metrics_export() -> tuple:
    """Fleet metrics export formats (:mod:`tpusnap.metrics_export`),
    comma-separated: ``prom`` (Prometheus textfile, atomic ``.prom``
    rewrite per take/restore summary for node-exporter textfile
    collection) and/or ``jsonl`` (structured per-summary event lines,
    rotation-bounded). Empty (the default) exports nothing; unknown
    names warn once per process and are skipped rather than failing a
    take."""
    raw = os.environ.get(_METRICS_EXPORT_ENV_VAR, "")
    out = []
    for tok in raw.replace(";", ",").split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        if tok not in _KNOWN_METRICS_FORMATS:
            if tok not in _warned_metrics_formats:
                _warned_metrics_formats.add(tok)
                logger.warning(
                    "Ignoring unknown %s format %r (known: %s)",
                    _METRICS_EXPORT_ENV_VAR,
                    tok,
                    ", ".join(_KNOWN_METRICS_FORMATS),
                )
            continue
        if tok not in out:
            out.append(tok)
    return tuple(out)


def get_metrics_dir() -> str:
    """Directory the export sinks write into (``.prom`` textfiles, the
    JSONL event log). Defaults to the telemetry dir so a node's whole
    observability surface lives under one path; point
    ``TPUSNAP_METRICS_DIR`` at the node-exporter textfile collector's
    directory in production."""
    return os.environ.get(_METRICS_DIR_ENV_VAR) or get_telemetry_dir()


def is_history_enabled() -> bool:
    """Cross-run history recording (:mod:`tpusnap.history`): every
    COMPLETED take/restore appends one summary line to the per-host
    ``TPUSNAP_TELEMETRY_DIR/history.jsonl`` (size-bounded, crash-
    tolerant), queryable by ``python -m tpusnap history`` and its
    ``--check`` regression gate. ``TPUSNAP_HISTORY=0`` disables the
    append (the file is never written)."""
    return os.environ.get(_HISTORY_ENV_VAR, "1") != "0"


def get_history_max_bytes() -> int:
    """Size bound on history.jsonl: when an append pushes the file past
    this, the oldest lines are compacted away (newest kept, atomic
    rewrite). Floor of 64 KiB so a misconfigured bound cannot thrash
    every append."""
    return max(
        64 * 1024, _get_int_env(_HISTORY_MAX_BYTES_ENV_VAR, 4 * 1024 * 1024)
    )


def is_access_ledger_enabled() -> bool:
    """Read-side access attribution (:mod:`tpusnap.access`): every
    restore / ``read_object`` records which manifest leaves and byte
    ranges it actually read, aggregated in memory and appended as one
    JSONL summary line per read scope to the per-reader ledger sidecar
    (``TPUSNAP_TELEMETRY_DIR/access/<digest>/<job_id>.jsonl``) that
    ``tpusnap heatmap`` and the fleet fold merge across readers. On by
    default — the per-read cost is one dict update on an already-
    telemetry-instrumented path, bounded by the tier-1 ≤10% overhead
    guard. ``TPUSNAP_ACCESS_LEDGER=0`` disables recording (no ledger
    file is ever written); also off whenever telemetry as a whole is
    disabled."""
    return (
        os.environ.get(_ACCESS_LEDGER_ENV_VAR, "1") != "0"
        and is_telemetry_enabled()
    )


def get_access_ledger_max_bytes() -> int:
    """Size bound on one reader's access ledger file: when an append
    pushes it past this, the file rotates to ``<name>.1`` (previous
    rotation overwritten) — same single-generation scheme as the JSONL
    metrics sink. Floor of 64 KiB so a misconfigured bound cannot
    rotate on every flush."""
    return max(
        64 * 1024,
        _get_int_env(_ACCESS_LEDGER_MAX_BYTES_ENV_VAR, 8 * 1024 * 1024),
    )


def get_stage_threads() -> int:
    """Worker threads of the write scheduler's staging executor (the
    clone / DtoH / serialize pass). Default 1. The historical anomaly
    (~1 GB/s aggregate for 4 threads vs ~4 GB/s for one on the dev
    host) was NESTED parallelism: each executor thread already runs a
    4-way-internal native copy pass, so 4 executor threads
    oversubscribed the memory system 16 ways — see
    :func:`get_native_copy_threads`, which now divides the internal
    fan-out by this knob so the total copy-thread budget stays
    constant. Raising this is therefore safe everywhere and shifts
    parallelism grain (useful when per-request Python overhead, not
    bandwidth, is the bound); clamped to [1, 16]."""
    return max(1, min(16, _get_int_env(_STAGE_THREADS_ENV_VAR, 1)))


def get_async_stage_window_bytes() -> Optional[int]:
    """Staging window of a pipelined async take (see
    :mod:`tpusnap.scheduler`): ``async_take`` returns control once the
    first window of write requests is staged, and the background drain
    stages subsequent windows interleaved with storage I/O under this
    in-flight bound — blocked time and clone RSS are O(window) instead
    of O(state). ``0`` disables pipelining: ``async_take`` then stages
    the WHOLE state before returning (the pre-pipeline strict
    semantics, for callers that mutate host-aliasing state in place
    immediately after control returns instead of using
    ``PendingSnapshot.wait_staged()``)."""
    val = _get_int_env(
        _ASYNC_STAGE_WINDOW_ENV_VAR, _DEFAULT_ASYNC_STAGE_WINDOW_BYTES
    )
    return val if val > 0 else None


def is_async_cow_enabled() -> bool:
    """Copy-on-write async staging for host-aliasing arrays (numpy /
    pinned_host / CPU-backend device arrays), ON BY DEFAULT since
    round 14 (ROADMAP 5: the 20 GB take spent 13.5 of 14.5 s in the
    clone pass — frozen layers should clone nothing): instead of the
    defensive clone, the blocked window records the fused
    CRC32C(+XXH64) hash of the live bytes and the write path re-hashes
    after the storage write — a mismatch (the caller mutated the array
    mid-take) fails the take loudly instead of committing torn data.
    ``PendingSnapshot.staged()/wait_staged()`` are COW-aware (they
    report THIS RANK's write drain), so ``staged() ⟹ safe to mutate``
    holds exactly as before. ``TPUSNAP_ASYNC_COW=0`` is the escape
    hatch back to defensive cloning, which strengthens the guarantee
    from "mutation is detected and fails the take" to "mutation cannot
    corrupt" at the cost of a full clone pass per take."""
    return os.environ.get(_ASYNC_COW_ENV_VAR, "1") != "0"


def is_probe_enabled() -> bool:
    """In-take roofline probes (``TPUSNAP_PROBE=1``, off by default):
    the write scheduler interleaves tiny raw write/read probe segments
    between I/O windows — through the SAME storage plugin stack the
    take's blobs use — so every take self-measures its achievable
    storage ceiling and carries a drift-immune ``roofline_fraction`` in
    its summary, rollup and history event. Opt-in because the probes
    cost real I/O (bounded by PROBE_BYTES/PROBE_INTERVAL, ~3% at the
    defaults) and only run when telemetry is enabled. The restore
    scheduler runs the same probes between its read windows, feeding
    ``restore_roofline_fraction`` from the read leg."""
    return _env_get(_PROBE_ENV_VAR, "0") == "1"


def is_autotune_enabled() -> bool:
    """``TPUSNAP_AUTOTUNE=1`` (off by default): at take/restore begin,
    compute the `tpusnap tune` plan for this backend/kind/world-size
    cell from the local history and apply it through the tuned-plan
    overlay. Explicit env vars always win over the plan; the knobs a
    run actually applied are stamped into its history event as
    ``tuned: {plan_id, knobs}`` so `history --check` can attribute (and
    gate) any regression the tuner causes."""
    return _env_get(_AUTOTUNE_ENV_VAR, "0") == "1"


def get_probe_interval_bytes() -> int:
    """Payload bytes written between in-take roofline probe segments.
    Floor of 16 MiB so a misconfigured cadence cannot turn the take
    into a probe benchmark."""
    return max(
        16 * 1024 * 1024,
        _get_int_env(_PROBE_INTERVAL_ENV_VAR, _DEFAULT_PROBE_INTERVAL_BYTES),
    )


def get_probe_bytes() -> int:
    """Raw bytes one probe segment writes (then reads back) through the
    take's plugin stack, split across a few concurrent streams to
    measure the AGGREGATE ceiling the take's own parallel writes see.
    Floor of 1 MiB: smaller probes measure syscall latency, not
    bandwidth."""
    return max(
        1024 * 1024, _get_int_env(_PROBE_BYTES_ENV_VAR, _DEFAULT_PROBE_BYTES)
    )


def get_staging_pool_bytes() -> int:
    """Cap on the reusable aligned staging-buffer pool
    (:mod:`tpusnap._staging_pool`): released async-clone buffers up to
    this many bytes are parked and handed back warm (no first-touch
    page faults) to later takes and later pipelined-staging windows.
    ``0`` disables the pool entirely (every clone allocates fresh)."""
    return max(0, _get_int_env(_STAGING_POOL_ENV_VAR, _DEFAULT_STAGING_POOL_BYTES))


def is_flight_enabled() -> bool:
    """Black-box flight recorder (:mod:`tpusnap.flight`): on by default
    — a bounded, lock-light ring buffer of structured events (spans,
    phases, journal writes, retries, faults, barriers, stalls, probes)
    flushed to crash-surviving sidecars at the heartbeat cadence, so a
    SIGKILLed/wedged take leaves a forensic timeline
    (``python -m tpusnap timeline``) instead of just a journal marker.
    ``TPUSNAP_FLIGHT=0`` disables recording AND flushing entirely (the
    disabled record path is one attribute check)."""
    return os.environ.get(_FLIGHT_ENV_VAR, "1") != "0"


def get_flight_ring_size() -> int:
    """Flight-recorder ring capacity in EVENTS: the black box keeps the
    newest this-many events (older ones are evicted and counted as
    dropped in the flushed header). Bounded by design — the recorder's
    memory and flush cost are O(ring), never O(take). Floor of 256 so a
    misconfigured ring cannot reduce the black box to noise."""
    return max(256, _get_int_env(_FLIGHT_RING_ENV_VAR, 4096))


def get_flight_flush_interval_s() -> float:
    """Cadence of the flight recorder's crash-surviving flush
    (piggybacked on the heartbeat pump): the sidecar is rewritten
    atomically at most once per interval, so after a SIGKILL — which no
    handler can catch — AT MOST this many seconds of events are lost.
    This knob IS the documented loss bound. Defaults to the heartbeat
    interval (``TPUSNAP_HEARTBEAT_INTERVAL_S``)."""
    val = _get_float_env(_FLIGHT_FLUSH_ENV_VAR, -1.0)
    if val <= 0:
        return get_heartbeat_interval_s()
    return max(0.02, val)


def get_slo_rpo_threshold_s() -> float:
    """Recovery-point objective threshold (:mod:`tpusnap.slo`): when
    the seconds since the last committed take exceed this, the tracker
    emits one edge-triggered ``slo_breach`` flight event + counter per
    episode, the breach flag rides the exported gauges/sidecar, and
    ``python -m tpusnap slo --check`` exits 2. ``0`` (the default)
    means no RPO objective is set — the gauges still publish."""
    return max(0.0, _get_float_env(_SLO_RPO_ENV_VAR, 0.0))


def get_slo_rto_threshold_s() -> float:
    """Recovery-time objective threshold (:mod:`tpusnap.slo`): breach
    when the history-derived estimated restore time of the last
    committed snapshot exceeds this many seconds. ``0`` (the default)
    = unset. The estimate needs ≥3 comparable restore events in
    ``history.jsonl``; with a threshold set and no estimate available,
    ``slo --check`` exits 3 (no verdict), never a silent pass."""
    return max(0.0, _get_float_env(_SLO_RTO_ENV_VAR, 0.0))


def get_slo_stream_cadence_x() -> float:
    """Stream-cadence gate multiplier of ``slo --check``
    (:mod:`tpusnap.slo`): while a delta stream is LIVE (its SLO record
    advertises a ``stream_cadence_s`` and is not a final record), the
    observed time since the last commit must stay under this many
    multiples of the declared cadence — beyond it the verdict is a
    breach (exit 2): the stream has silently stalled and exposure is
    growing past what the operator declared. ``0`` disables the gate;
    values are floored at 1 (below 1x a healthy stream could never
    pass). Default 3x."""
    val = _get_float_env(_SLO_STREAM_CADENCE_X_ENV_VAR, 3.0)
    if val <= 0:
        return 0.0
    return max(1.0, val)


def get_delta_cadence_s() -> float:
    """Default micro-commit cadence of a delta stream
    (:meth:`tpusnap.Snapshot.stream` / :class:`tpusnap.delta.DeltaStream`)
    when the call doesn't pass ``cadence_s``: the stream commits one
    journaled incremental micro-snapshot per interval, so this bounds
    the stream's recovery-point objective — a crash replays base +
    committed delta chain and loses at most ~one interval of work.
    Floor 0.1 s (a micro-commit is a real two-phase-committed take;
    sub-100ms cadences would spend the whole interval committing)."""
    return max(0.1, _get_float_env(_DELTA_CADENCE_ENV_VAR, 5.0))


def get_delta_max_chain() -> int:
    """Chain-compaction threshold of a delta stream: once the chain
    from the base to the head exceeds this many members, the stream
    materializes the head (the existing ``materialize`` path — copying
    referenced blobs in, checksum-verified, committed atomically) so it
    becomes the new self-contained base, and retires the superseded
    members. Bounds both restore fan-in (how many sibling directories a
    head's blob references span) and the storage a long-running stream
    pins. Clamped to [2, 1024]."""
    return max(2, min(1024, _get_int_env(_DELTA_MAX_CHAIN_ENV_VAR, 8)))


def is_tier_drain_enabled() -> bool:
    """Background cloud drain of the write-back tier
    (:mod:`tpusnap.tiering`): on by default — a take to a
    ``tier+local=...+remote=...`` URL commits to the local tier at disk
    speed and the uploader thread drains blobs to the remote tier in the
    background, converging to ``remote-durable``. ``0`` disables the
    automatic drain: takes stay ``local-committed`` until
    ``python -m tpusnap drain`` is run (useful for tests and for
    operators who schedule drains out of band)."""
    return os.environ.get(_TIER_DRAIN_ENV_VAR, "1") != "0"


def get_tier_op_deadline_s() -> float:
    """Per-op retry deadline (``retry_deadline_sec``) of the write-back
    uploader's REMOTE plugin: short by design — once a single upload has
    made no progress for this long, the retry middleware gives up
    (``retry.exhausted``) and the uploader's own sustained-outage mode
    (circuit breaker + capped exponential backoff, takes keep committing
    locally) takes over. The default 600 s payload deadline would park
    the drain inside one op for 10 minutes before the outage machinery
    ever saw a failure."""
    return max(0.05, _get_float_env(_TIER_OP_DEADLINE_ENV_VAR, 60.0))


def get_tier_outage_threshold() -> int:
    """Consecutive failed remote uploads before the uploader's circuit
    opens: the drain enters DEGRADED mode (edge-triggered
    ``tier_degraded`` flight event, `tpusnap_tier_degraded` gauge,
    capped-backoff probing) instead of hammering a down endpoint."""
    return max(1, _get_int_env(_TIER_OUTAGE_THRESHOLD_ENV_VAR, 3))


def get_tier_backoff_cap_s() -> float:
    """Cap on the uploader's degraded-mode exponential backoff between
    remote probes during a sustained outage."""
    return max(0.05, _get_float_env(_TIER_BACKOFF_CAP_ENV_VAR, 30.0))


def get_tier_local_retention_s() -> float:
    """Hot-local-cache retention policy for ``gc --evict-local``: local
    payload blobs of a ``remote-durable`` snapshot may be reclaimed only
    once the remote-durable marker is at least this old. ``0`` (the
    default) lets an explicit eviction reclaim immediately; a fleet that
    wants the last N minutes of checkpoints restorable at local-disk
    speed sets this to that window."""
    return max(0.0, _get_float_env(_TIER_LOCAL_RETENTION_ENV_VAR, 0.0))


_KNOWN_COMPRESS_MODES = ("auto", "on", "off", "lz4")
_warned_compress_modes: set = set()


def get_compress_mode() -> str:
    """Per-take fused tile compression (:mod:`tpusnap.compress`):

    - ``auto`` (default) — a MEASURED per-take decision: compress when
      the storage pipe's probe-reported ceiling is clearly slower than
      the codec's measured throughput (cloud, virtio, the write-back
      tier's remote drain), bypass when local disk outruns it. Takes
      whose eligible payload is below the auto floor always bypass
      (small takes are not worth the codec bookkeeping or a probe).
    - ``on`` — compress every eligible blob regardless of the pipe.
    - ``off`` — bypass entirely.
    - ``lz4`` — force the named codec family (same as ``on`` today;
      the name exists so a future codec can be pinned explicitly).

    Unknown values warn once per process and fall back to ``auto``."""
    raw = (_env_get(_COMPRESS_ENV_VAR) or "auto").strip().lower()
    if raw not in _KNOWN_COMPRESS_MODES:
        if raw not in _warned_compress_modes:
            _warned_compress_modes.add(raw)
            logger.warning(
                "Ignoring unknown %s=%r (known: %s); using auto",
                _COMPRESS_ENV_VAR,
                raw,
                ", ".join(_KNOWN_COMPRESS_MODES),
            )
        return "auto"
    return raw


def get_compress_min_blob_bytes() -> int:
    """Per-blob eligibility floor for fused tile compression: blobs
    smaller than this bypass the codec (slab members and tiny arrays
    cost more in bookkeeping than the pipe saves). Floor of 64 KiB."""
    return max(
        64 * 1024, _get_int_env(_COMPRESS_MIN_BLOB_ENV_VAR, 1024 * 1024)
    )


def get_barrier_timeout_s() -> float:
    """Hard deadline of every blocking collective/KV wait (the
    coordination-service barriers in :mod:`tpusnap.comm`, the
    ``LinearBarrier``/``KVStore.get`` polls in :mod:`tpusnap.dist_store`).
    Historically three separate literals (600 s in comm/dist_store,
    1800 s on the async commit barrier — see
    :func:`get_commit_barrier_timeout_s`); one knob now routes them all.
    This is the LAST-RESORT bound: with liveness leases on
    (``TPUSNAP_LIVENESS_TTL_S``) a dead peer fails the wait within
    ~2x the lease TTL, so the full timeout is only burned when the
    coordination service itself is unreachable. Floor of 1 s."""
    return max(1.0, _get_float_env(_BARRIER_TIMEOUT_ENV_VAR, 600.0))


def get_commit_barrier_timeout_s() -> float:
    """Deadline of the async commit's LinearBarrier waits — 3x the
    collective timeout, preserving the historical 600 s/1800 s ratio
    (the commit barrier waits on every rank's full residual I/O drain,
    not just a collective round-trip)."""
    return 3.0 * get_barrier_timeout_s()


def get_liveness_ttl_s() -> float:
    """Rank-liveness lease TTL (:mod:`tpusnap.liveness`): each rank's
    lease record (published over the coordination KV by the heartbeat
    pump — no extra thread) must advance within this window or peers
    blocked in a collective/commit wait declare the rank dead and raise
    :class:`~tpusnap.liveness.RankFailedError` naming it, within ~2x
    this TTL instead of parking until the barrier timeout. ``0``
    disables the liveness layer (waits fall back to the bare
    ``TPUSNAP_BARRIER_TIMEOUT_S``). Requires telemetry (the lease rides
    the heartbeat pump); keep the value well above the heartbeat
    interval — the floor is 4x ``TPUSNAP_HEARTBEAT_INTERVAL_S``."""
    ttl = _get_float_env(_LIVENESS_TTL_ENV_VAR, 15.0)
    if ttl <= 0:
        return 0.0
    return max(4.0 * get_heartbeat_interval_s(), ttl)


_KNOWN_RANK_FAILURE_POLICIES = ("abort", "degrade")
_warned_rank_failure_policies: set = set()


def get_rank_failure_policy() -> str:
    """What a multi-process take does when liveness declares a peer
    dead mid-take:

    - ``abort`` (default) — the detecting rank raises
      :class:`~tpusnap.liveness.RankFailedError`, publishes it through
      the take-abort monitor so every survivor aborts within seconds,
      and the path is left torn (fsck/`timeline` name the dead rank; a
      retake salvages the survivors' completed blobs via the dual-hash
      evidence rule).
    - ``degrade`` — a take whose dead rank held only REPLICATED
      partitions is completed by the survivors: the dead rank's
      replicated write assignments are adopted by live ranks
      (re-planned deterministically), the commit barrier shrinks to the
      live set, and ``metadata.extras["degraded"]`` records the
      adoption. A dead rank holding sharded/unique partitions (or an
      incremental take) still aborts — its bytes are unrecoverable.

    Must be set identically on every rank. Unknown values warn once per
    process and fall back to ``abort``."""
    raw = os.environ.get(_RANK_FAILURE_ENV_VAR, "abort").strip().lower()
    if raw not in _KNOWN_RANK_FAILURE_POLICIES:
        if raw not in _warned_rank_failure_policies:
            _warned_rank_failure_policies.add(raw)
            logger.warning(
                "Ignoring unknown %s=%r (known: %s); using abort",
                _RANK_FAILURE_ENV_VAR,
                raw,
                ", ".join(_KNOWN_RANK_FAILURE_POLICIES),
            )
        return "abort"
    return raw


def get_native_copy_threads() -> int:
    """Internal threads of ONE native copy/hash pass (``_native.memcpy``
    and the fused clone+CRC(+XXH64) tile passes), derived so the TOTAL
    copy-thread budget stays ~constant: ``stage_threads × this ≈ 4``.
    The ROADMAP 5 staging anomaly (``TPUSNAP_STAGE_THREADS=4`` measured
    ~1 GB/s aggregate vs ~4 GB/s for 1 on the dev host) was NESTED
    parallelism, not NUMA: each staging executor thread already fans
    out to 4 native memcpy threads, so 4 executor threads ran 16 copy
    threads on a memory system that saturates around 4 — past
    saturation, extra copy threads are pure cache-line ping-pong and
    context switching. Measured on a 24-core host: equal-total-budget
    splits are equivalent (1×4 ≈ 2×2 ≈ 4×1 ≈ 28 GB/s), confirming the
    total is what matters. With this divisor, raising
    ``TPUSNAP_STAGE_THREADS`` only shifts the parallelism grain (and
    overlaps per-request Python overhead) — it can no longer
    oversubscribe the memory system, which is why the auto-default of
    1 executor thread stays safe everywhere."""
    return max(1, 4 // get_stage_threads())


def is_lockcheck_enabled() -> bool:
    """Runtime lock-order watchdog (:mod:`tpusnap.devtools.lockwatch`),
    OPT-IN via ``TPUSNAP_LOCKCHECK=1``: every ``threading.Lock``/
    ``RLock`` created after import is wrapped to record the per-thread
    held-lock stack and a global lock-order graph; AB/BA cycles
    (potential deadlocks) and locks held across storage I/O are
    reported at process exit and via the lockwatch API. Off by default:
    the instrumentation adds a pure-Python hop to every lock
    acquisition. The tier-1 test run enables it so the whole suite
    doubles as a deadlock detector."""
    return os.environ.get(_LOCKCHECK_ENV_VAR, "0") == "1"


def get_memory_budget_override_bytes() -> Optional[int]:
    if _env_get(_MEMORY_BUDGET_ENV_VAR) is None:
        return None
    val = _get_int_env(_MEMORY_BUDGET_ENV_VAR, -1)
    return val if val > 0 else None


_NODE_NAME_ENV_VAR = "TPUSNAP_NODE_NAME"


def get_node_name() -> str:
    """The identity used to decide which ranks SHARE A HOST (the
    per-host memory-budget divisor gathers these). Defaults to the OS
    hostname; ``TPUSNAP_NODE_NAME`` overrides it for containerized
    jobs where every pod reports a unique hostname despite sharing a
    node (kubernetes), and for multi-host simulation in tests."""
    import socket

    return os.environ.get(_NODE_NAME_ENV_VAR) or socket.gethostname()


def get_job_id() -> str:
    """The identity of THIS training job on every observability
    artifact — telemetry summaries, history events, heartbeat records,
    flight headers, SLO sidecars, Prometheus filenames/labels, and the
    fleet status records under ``TPUSNAP_FLEET_DIR``. Defaults to
    ``<node>-<pid>`` so two jobs sharing a telemetry/metrics/fleet
    directory never collide even when nobody set the knob; a
    MULTI-PROCESS job must set ``TPUSNAP_JOB_ID`` identically on every
    rank (the host-pid default would split one job into per-rank
    identities). Sanitized to filename/label-safe characters: the id
    lands in file names and Prometheus label values."""
    explicit = get_explicit_job_id()
    if explicit is not None:
        return explicit
    raw = f"{get_node_name()}-{os.getpid()}"
    clean = "".join(c if (c.isalnum() or c in "._-") else "-" for c in raw)
    return clean or "job"


def get_explicit_job_id() -> Optional[str]:
    """``TPUSNAP_JOB_ID`` exactly as configured (sanitized), or None
    when unset — the comparability key history's regression baseline
    filters on. :func:`get_job_id`'s host-pid DEFAULT is deliberately
    absent here: it changes every process, and stamping it into history
    events would make every cross-run baseline structurally empty
    (one-take-per-process fleets would never accumulate a gradeable
    window)."""
    raw = os.environ.get(_JOB_ID_ENV_VAR)
    if not raw:
        return None
    clean = "".join(c if (c.isalnum() or c in "._-") else "-" for c in raw)
    return clean or None


def get_fleet_dir() -> Optional[str]:
    """Shared cross-job status directory (``TPUSNAP_FLEET_DIR``): when
    set, rank 0 of every instrumented job mirrors its heartbeat/SLO/
    tier state into ``<dir>/<job_id>.json`` (atomic rewrite, riding the
    heartbeat pump — :mod:`tpusnap.fleet`), and ``python -m tpusnap
    fleet`` folds all jobs' records into fleet rollups. Unset/empty =
    the fleet layer is off (zero per-take cost)."""
    val = os.environ.get(_FLEET_DIR_ENV_VAR)
    return val or None


def get_cas_dir() -> Optional[str]:
    """Shared content-addressed blob store (``TPUSNAP_CAS_DIR``,
    :mod:`tpusnap.cas`): a directory — or a storage URL, e.g.
    ``chaos+fs:///store`` so chaos plans can target store I/O — that
    every CAS-composed take publishes payload blobs into, keyed by
    their (CRC32C, XXH64) dual hash. When set, a plain ``fs`` take URL
    is auto-composed with the CAS layer (equivalent to the explicit
    ``cas+fs://`` scheme); snapshots then hold ref records instead of
    private payload copies. Unset/empty = the layer is off."""
    val = os.environ.get(_CAS_DIR_ENV_VAR)
    return val or None


def get_cas_grace_s() -> float:
    """Grace window of the store's mark-and-sweep gc
    (:func:`tpusnap.cas.gc_store`): an UNMARKED blob, a stale publish
    intent, a ``.tmp.*`` torn-publish leftover or a stale root record
    is swept only once it is at least this old — young debris may be a
    concurrent publisher mid-adoption whose ref record simply hasn't
    landed yet. Lowering it below the duration of a take invites the
    publish-vs-gc race the intent records exist to close."""
    return max(0.0, _get_float_env(_CAS_GRACE_ENV_VAR, 900.0))


def get_cas_lease_ttl_s() -> float:
    """TTL of the per-store gc lock lease (``gc.lock``): a second
    ``gc --store`` against the same store is refused while a live lease
    exists, and a lease abandoned by a SIGKILLed sweeper is stealable
    once this old (the PR 15 lease shape applied to stores)."""
    return max(0.5, _get_float_env(_CAS_LEASE_TTL_ENV_VAR, 60.0))


def get_cas_remote() -> Optional[str]:
    """Remote mirror URL of the content-addressed store: when set (or
    recorded in the store's ``config.json``), the tiering drain uploads
    each unique store blob ONCE store-wide to ``<remote>/blobs/<key>``
    — recording dual-hash evidence in the store-level upload journal —
    and store reads fall back to the mirror for locally-evicted blobs.
    Unset = the store is local-only (``gc --evict-local`` then refuses
    to evict CAS-referenced payloads)."""
    val = os.environ.get(_CAS_REMOTE_ENV_VAR)
    return val or None


@contextlib.contextmanager
def _override_env(name: str, value: Optional[str]) -> Generator[None, None, None]:
    prev = os.environ.get(name)
    try:
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value
        yield
    finally:
        if prev is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = prev


@contextlib.contextmanager
def override_max_chunk_size_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_MAX_CHUNK_SIZE_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_max_shard_size_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_MAX_SHARD_SIZE_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_slab_size_threshold_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_SLAB_SIZE_THRESHOLD_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_batching_disabled(disabled: bool) -> Generator[None, None, None]:
    with _override_env(_DISABLE_BATCHING_ENV_VAR, "1" if disabled else "0"):
        yield


@contextlib.contextmanager
def override_device_batching_disabled(disabled: bool) -> Generator[None, None, None]:
    with _override_env(_DISABLE_DEVICE_BATCHING_ENV_VAR, "1" if disabled else "0"):
        yield


@contextlib.contextmanager
def override_memory_budget_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_MEMORY_BUDGET_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_direct_io_disabled(disabled: bool) -> Generator[None, None, None]:
    with _override_env(_DISABLE_DIRECT_IO_ENV_VAR, "1" if disabled else "0"):
        yield


@contextlib.contextmanager
def override_checksum_disabled(disabled: bool) -> Generator[None, None, None]:
    with _override_env(_DISABLE_CHECKSUM_ENV_VAR, "1" if disabled else "0"):
        yield


@contextlib.contextmanager
def override_tile_checksum_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_TILE_CHECKSUM_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_record_dedup_hashes(enabled: bool) -> Generator[None, None, None]:
    with _override_env(_RECORD_DEDUP_HASHES_ENV_VAR, "1" if enabled else "0"):
        yield


@contextlib.contextmanager
def override_telemetry_enabled(enabled: bool) -> Generator[None, None, None]:
    with _override_env(_TELEMETRY_ENV_VAR, "1" if enabled else "0"):
        yield


@contextlib.contextmanager
def override_journal_disabled(disabled: bool) -> Generator[None, None, None]:
    with _override_env(_DISABLE_JOURNAL_ENV_VAR, "1" if disabled else "0"):
        yield


@contextlib.contextmanager
def override_stall_deadline_s(seconds: float) -> Generator[None, None, None]:
    with _override_env(_STALL_DEADLINE_ENV_VAR, str(seconds)):
        yield


@contextlib.contextmanager
def override_heartbeat_interval_s(seconds: float) -> Generator[None, None, None]:
    with _override_env(_HEARTBEAT_INTERVAL_ENV_VAR, str(seconds)):
        yield


@contextlib.contextmanager
def override_telemetry_dir(path: str) -> Generator[None, None, None]:
    with _override_env(_TELEMETRY_DIR_ENV_VAR, path):
        yield


@contextlib.contextmanager
def override_metrics_export(formats: Optional[str]) -> Generator[None, None, None]:
    with _override_env(_METRICS_EXPORT_ENV_VAR, formats):
        yield


@contextlib.contextmanager
def override_metrics_dir(path: Optional[str]) -> Generator[None, None, None]:
    with _override_env(_METRICS_DIR_ENV_VAR, path):
        yield


@contextlib.contextmanager
def override_history_enabled(enabled: bool) -> Generator[None, None, None]:
    with _override_env(_HISTORY_ENV_VAR, "1" if enabled else "0"):
        yield


@contextlib.contextmanager
def override_history_max_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_HISTORY_MAX_BYTES_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_access_ledger(enabled: bool) -> Generator[None, None, None]:
    with _override_env(_ACCESS_LEDGER_ENV_VAR, "1" if enabled else "0"):
        yield


@contextlib.contextmanager
def override_access_ledger_max_bytes(nbytes: int) -> Generator[None, None, None]:
    with _override_env(_ACCESS_LEDGER_MAX_BYTES_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_stage_threads(n: int) -> Generator[None, None, None]:
    with _override_env(_STAGE_THREADS_ENV_VAR, str(n)):
        yield


@contextlib.contextmanager
def override_async_stage_window_bytes(nbytes: int) -> Generator[None, None, None]:
    """0 disables pipelined async staging (strict stage-all semantics)."""
    with _override_env(_ASYNC_STAGE_WINDOW_ENV_VAR, str(nbytes)):
        yield


@contextlib.contextmanager
def override_async_cow(enabled: bool) -> Generator[None, None, None]:
    with _override_env(_ASYNC_COW_ENV_VAR, "1" if enabled else "0"):
        yield


@contextlib.contextmanager
def override_flight_enabled(enabled: bool) -> Generator[None, None, None]:
    with _override_env(_FLIGHT_ENV_VAR, "1" if enabled else "0"):
        yield


@contextlib.contextmanager
def override_flight_ring_size(n: int) -> Generator[None, None, None]:
    with _override_env(_FLIGHT_RING_ENV_VAR, str(n)):
        yield


@contextlib.contextmanager
def override_flight_flush_interval_s(seconds: float) -> Generator[None, None, None]:
    with _override_env(_FLIGHT_FLUSH_ENV_VAR, str(seconds)):
        yield


@contextlib.contextmanager
def override_slo_thresholds(
    rpo_s: Optional[float] = None, rto_s: Optional[float] = None
) -> Generator[None, None, None]:
    """Override the SLO breach thresholds in one scope (None leaves the
    corresponding env var untouched)."""
    with contextlib.ExitStack() as stack:
        if rpo_s is not None:
            stack.enter_context(_override_env(_SLO_RPO_ENV_VAR, str(rpo_s)))
        if rto_s is not None:
            stack.enter_context(_override_env(_SLO_RTO_ENV_VAR, str(rto_s)))
        yield


@contextlib.contextmanager
def override_slo_stream_cadence_x(factor: float) -> Generator[None, None, None]:
    with _override_env(_SLO_STREAM_CADENCE_X_ENV_VAR, str(factor)):
        yield


@contextlib.contextmanager
def override_delta_cadence_s(seconds: float) -> Generator[None, None, None]:
    with _override_env(_DELTA_CADENCE_ENV_VAR, str(seconds)):
        yield


@contextlib.contextmanager
def override_delta_max_chain(n: int) -> Generator[None, None, None]:
    with _override_env(_DELTA_MAX_CHAIN_ENV_VAR, str(n)):
        yield


@contextlib.contextmanager
def override_tier_drain(enabled: bool) -> Generator[None, None, None]:
    with _override_env(_TIER_DRAIN_ENV_VAR, "1" if enabled else "0"):
        yield


@contextlib.contextmanager
def override_tier_outage(
    threshold: Optional[int] = None,
    backoff_cap_s: Optional[float] = None,
    op_deadline_s: Optional[float] = None,
    local_retention_s: Optional[float] = None,
) -> Generator[None, None, None]:
    """Override the write-back tier's outage/retention knobs in one
    scope (None leaves the corresponding env var untouched)."""
    with contextlib.ExitStack() as stack:
        if threshold is not None:
            stack.enter_context(
                _override_env(_TIER_OUTAGE_THRESHOLD_ENV_VAR, str(threshold))
            )
        if backoff_cap_s is not None:
            stack.enter_context(
                _override_env(_TIER_BACKOFF_CAP_ENV_VAR, str(backoff_cap_s))
            )
        if op_deadline_s is not None:
            stack.enter_context(
                _override_env(_TIER_OP_DEADLINE_ENV_VAR, str(op_deadline_s))
            )
        if local_retention_s is not None:
            stack.enter_context(
                _override_env(
                    _TIER_LOCAL_RETENTION_ENV_VAR, str(local_retention_s)
                )
            )
        yield


@contextlib.contextmanager
def override_compress(
    mode: Optional[str] = None,
    min_blob_bytes: Optional[int] = None,
) -> Generator[None, None, None]:
    """Override the fused-compression policy knobs in one scope (None
    leaves the corresponding env var untouched)."""
    with contextlib.ExitStack() as stack:
        if mode is not None:
            stack.enter_context(_override_env(_COMPRESS_ENV_VAR, mode))
        if min_blob_bytes is not None:
            stack.enter_context(
                _override_env(_COMPRESS_MIN_BLOB_ENV_VAR, str(min_blob_bytes))
            )
        yield


@contextlib.contextmanager
def override_barrier_timeout_s(seconds: float) -> Generator[None, None, None]:
    with _override_env(_BARRIER_TIMEOUT_ENV_VAR, str(seconds)):
        yield


@contextlib.contextmanager
def override_liveness(
    ttl_s: Optional[float] = None,
    policy: Optional[str] = None,
) -> Generator[None, None, None]:
    """Override the rank-liveness knobs in one scope (None leaves the
    corresponding env var untouched)."""
    with contextlib.ExitStack() as stack:
        if ttl_s is not None:
            stack.enter_context(
                _override_env(_LIVENESS_TTL_ENV_VAR, str(ttl_s))
            )
        if policy is not None:
            stack.enter_context(_override_env(_RANK_FAILURE_ENV_VAR, policy))
        yield


@contextlib.contextmanager
def override_job_id(job_id: Optional[str]) -> Generator[None, None, None]:
    """Pin (or with ``None``, restore the host-pid default of) the job
    identity in one scope."""
    with _override_env(_JOB_ID_ENV_VAR, job_id):
        yield


@contextlib.contextmanager
def override_fleet_dir(path: Optional[str]) -> Generator[None, None, None]:
    """Point the fleet status mirror at ``path`` (``None`` disables)."""
    with _override_env(_FLEET_DIR_ENV_VAR, path):
        yield


@contextlib.contextmanager
def override_cas(
    store_dir: Optional[str],
    grace_s: Optional[float] = None,
    lease_ttl_s: Optional[float] = None,
    remote: Optional[str] = None,
) -> Generator[None, None, None]:
    """Point the content-addressed store at ``store_dir`` (``None``
    disables) with optional gc grace / lease-TTL / remote overrides."""
    with contextlib.ExitStack() as stack:
        stack.enter_context(_override_env(_CAS_DIR_ENV_VAR, store_dir))
        if grace_s is not None:
            stack.enter_context(
                _override_env(_CAS_GRACE_ENV_VAR, str(grace_s))
            )
        if lease_ttl_s is not None:
            stack.enter_context(
                _override_env(_CAS_LEASE_TTL_ENV_VAR, str(lease_ttl_s))
            )
        if remote is not None:
            stack.enter_context(_override_env(_CAS_REMOTE_ENV_VAR, remote))
        yield


@contextlib.contextmanager
def override_probe(
    enabled: bool,
    interval_bytes: Optional[int] = None,
    probe_bytes: Optional[int] = None,
) -> Generator[None, None, None]:
    """Enable/disable in-take roofline probes, optionally overriding
    the cadence and probe size in the same scope (None leaves the
    corresponding env var untouched)."""
    with contextlib.ExitStack() as stack:
        stack.enter_context(
            _override_env(_PROBE_ENV_VAR, "1" if enabled else "0")
        )
        if interval_bytes is not None:
            stack.enter_context(
                _override_env(_PROBE_INTERVAL_ENV_VAR, str(interval_bytes))
            )
        if probe_bytes is not None:
            stack.enter_context(
                _override_env(_PROBE_BYTES_ENV_VAR, str(probe_bytes))
            )
        yield


@contextlib.contextmanager
def override_autotune(enabled: bool) -> Generator[None, None, None]:
    """Enable/disable the take/restore-begin auto-tuner reconcile."""
    with _override_env(_AUTOTUNE_ENV_VAR, "1" if enabled else "0"):
        yield
