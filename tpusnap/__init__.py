"""tpusnap — a TPU-native checkpointing framework for JAX/XLA workloads.

Built from scratch with the capability set of torchsnapshot (see SURVEY.md):
memory-efficient, pipelined, distributed snapshots of app-state pytrees with
automatic resharding across mesh/world-size changes.
"""

from .version import __version__  # noqa: F401

# Opt-in runtime lock-order watchdog. Installed BEFORE the submodule
# imports below so every lock the package creates at import time is
# tracked; off (the default) this costs one env read.
from . import knobs as _knobs

if _knobs.is_lockcheck_enabled():
    from .devtools import lockwatch as _lockwatch

    _lockwatch.install()

# Populated as layers land; the full export set mirrors the reference's
# torchsnapshot/__init__.py:35-41.
__all__ = ["__version__"]

try:  # pragma: no cover - import surface grows as modules land
    from .stateful import AppState, Stateful  # noqa: F401
    from .state_dict import StateDict  # noqa: F401
    from .rng_state import RNGState  # noqa: F401
    from .pytree_state import PytreeState  # noqa: F401
    from .snapshot import (  # noqa: F401
        PendingRestore,
        PendingSnapshot,
        Snapshot,
        load_snapshot,
    )
    from .liveness import RankFailedError  # noqa: F401
    from .delta import (  # noqa: F401
        DeltaChainReport,
        DeltaStream,
        resolve_chain,
    )
    from .host_offload import (  # noqa: F401
        is_host_resident,
        supports_host_offload,
        to_device,
        to_host_offload,
    )
    from .rss_profiler import measure_rss_deltas  # noqa: F401
    from .inspect import ScrubReport, verify_snapshot  # noqa: F401
    from .lifecycle import (  # noqa: F401
        FsckReport,
        GCReport,
        fsck_snapshot,
        gc_snapshot,
    )
    from .manifest import MetadataError  # noqa: F401
    from .dist_store import TakeAbortedError  # noqa: F401
    from .retry import RetryPolicy  # noqa: F401
    from .faults import FaultPlan, InjectedFaultError  # noqa: F401
    from .telemetry import (  # noqa: F401
        IOStats,
        LogHistogram,
        MetricsSink,
        metrics_sink,
        register_metrics_sink,
        unregister_metrics_sink,
    )
    from .analyze import (  # noqa: F401
        Attribution,
        attribute_spans,
    )
    from .metrics_export import (  # noqa: F401
        JsonlEventSink,
        PrometheusTextfileSink,
    )
    from .history import (  # noqa: F401
        RegressionReport,
        check_regression,
        load_history,
        record_event,
    )
    from .flight import (  # noqa: F401
        FlightRecorder,
        estimate_skew,
        load_flight_logs,
        merge_timeline,
        postmortem_verdict,
    )
    from .slo import (  # noqa: F401
        RTOEstimate,
        SLOTracker,
        estimate_rto,
        read_slo_records,
    )
    from .slo import record_step as record_slo_step  # noqa: F401

    __all__ += [
        "RTOEstimate",
        "SLOTracker",
        "estimate_rto",
        "read_slo_records",
        "record_slo_step",
        "FlightRecorder",
        "estimate_skew",
        "load_flight_logs",
        "merge_timeline",
        "postmortem_verdict",
        "IOStats",
        "LogHistogram",
        "Attribution",
        "attribute_spans",
        "MetricsSink",
        "metrics_sink",
        "register_metrics_sink",
        "unregister_metrics_sink",
        "JsonlEventSink",
        "PrometheusTextfileSink",
        "RegressionReport",
        "check_regression",
        "load_history",
        "record_event",
        "ScrubReport",
        "verify_snapshot",
        "FsckReport",
        "GCReport",
        "fsck_snapshot",
        "gc_snapshot",
        "MetadataError",
        "TakeAbortedError",
        "RetryPolicy",
        "FaultPlan",
        "InjectedFaultError",
        "Snapshot",
        "PendingSnapshot",
        "PendingRestore",
        "load_snapshot",
        "DeltaStream",
        "DeltaChainReport",
        "resolve_chain",
        "Stateful",
        "AppState",
        "StateDict",
        "RNGState",
        "PytreeState",
        "to_host_offload",
        "to_device",
        "is_host_resident",
        "supports_host_offload",
        "measure_rss_deltas",
    ]
except ModuleNotFoundError as e:  # modules not created yet during bootstrap
    # Only swallow "tpusnap.X does not exist yet"; a failure inside an
    # existing submodule (or a missing third-party dep) must propagate.
    if not (e.name == "tpusnap" or (e.name or "").startswith("tpusnap.")):
        raise
