"""The Stateful protocol: anything with state_dict()/load_state_dict().

Counterpart of /root/reference/torchsnapshot/stateful.py:13-23. In JAX
there are no nn.Modules carrying state — app state is explicit pytrees —
so the protocol is the same but the canonical implementations are
``StateDict`` (plain dict) and ``PytreeState`` (arbitrary pytree with
structure-preserving load).
"""

from typing import Any, Dict, Protocol, runtime_checkable


@runtime_checkable
class Stateful(Protocol):
    """Optional class attribute ``load_requires_collectives: bool``
    (default False when absent): set True when ``load_state_dict`` runs
    device collectives (e.g. an all-gather to re-materialize a sharded
    optimizer). Such statefuls need ``restore(per_key_barrier=True)``
    for cross-rank ordering, and ``async_restore`` REJECTS them —
    collectives on the background restore thread run unordered against
    other ranks and deadlock or corrupt (the same discipline as the
    reference's no-collectives-off-thread rule, snapshot.py:902)."""

    def state_dict(self) -> Dict[str, Any]: ...

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None: ...


AppState = Dict[str, Stateful]
