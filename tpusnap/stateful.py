"""The Stateful protocol: anything with state_dict()/load_state_dict().

Counterpart of /root/reference/torchsnapshot/stateful.py:13-23. In JAX
there are no nn.Modules carrying state — app state is explicit pytrees —
so the protocol is the same but the canonical implementations are
``StateDict`` (plain dict) and ``PytreeState`` (arbitrary pytree with
structure-preserving load).
"""

from typing import Any, Dict, Protocol, runtime_checkable


@runtime_checkable
class Stateful(Protocol):
    def state_dict(self) -> Dict[str, Any]: ...

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None: ...


AppState = Dict[str, Stateful]
