"""Cross-run content-addressed blob store: crash-safe shared dedup.

``TPUSNAP_CAS_DIR`` (or an explicit ``cas+<base>://`` URL) composes a
CAS layer around a snapshot's storage plugin: every payload blob is
keyed by its (CRC32C, XXH64) dual hash — the same fused-pass evidence
rule the take journal, salvage-resume and the tiering upload journal
already run on — and published to a SHARED store directory; the
snapshot itself holds per-rank **ref records**
(``.tpusnap/cas_refs/rank_<k>.json``) instead of private copies. N
hyperparameter branches of one base model then pay ~1x storage, and a
retake after a process restart skips every blob the store already
holds, cross-process and cross-lifetime, at hash speed.

Store layout (all paths relative to the store root; the root may be a
storage URL — ``chaos+fs:///store`` — so chaos plans can SIGKILL
around store I/O)::

    blobs/<crc8hex>-<xxh16hex>   content, immutable once published
    blobs/<key>.tmp.<pid>        torn publish (fsck names it; gc sweeps)
    intents/<key>__<owner>       short-lived publish intent records
    roots/<digest>               {dir, ts}: a snapshot dir holding refs
    refcounts.json               ADVISORY ref-count cache (gc rewrites
                                 it from marks; divergence is an fsck
                                 verdict, never load-bearing)
    upload_journal               store-level dual-hash upload evidence
                                 (each unique blob drains ONCE
                                 store-wide, journal keyed by hash)
    config.json                  {"remote": <url>} optional mirror
    gc.lock                      per-store gc lease (PR 15 shape)

Crash-safety protocol (every window SIGKILL-safe and fsck-nameable):

1. the publisher writes an **intent** record for the key;
2. the blob lands via ``write_atomic`` (tmp+rename keyed by hash — two
   jobs racing the same content converge on one file, the loser's tmp
   is orphan-visible "torn publish" debris);
3. the snapshot's **root record** and per-rank **ref record** are
   flushed — refs are the gc liveness roots, written strictly BEFORE
   the metadata commit (the CAS layer force-flushes them when the
   metadata write passes through);
4. the publisher re-verifies the blob exists AFTER its ref landed and
   republishes from the bytes it still holds if a concurrent sweep won
   the race — the airtight closure of the adopt-then-ref window (the
   intent record makes the race rare; the re-verify makes blob loss
   impossible);
5. the intent is cleared (a stale intent is swept after the grace
   window).

GC (:func:`gc_store`) is mark-and-sweep over the ref records: blobs
referenced by any live root's refs — or named by an intent younger
than ``TPUSNAP_CAS_GRACE_S`` — survive; everything else older than the
grace window is swept under a per-store lock lease. Refs-as-files
rather than a refcount integer: a crashed publisher leaves either a
complete ref record or the previous one, never a half-decremented
counter — see docs/design.md "Cross-run content-addressed store".
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import logging
import os
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import flight, telemetry
from .io_types import (
    CAS_REFS_DIR,
    SIDECAR_PREFIX,
    ReadIO,
    StoragePlugin,
    WriteIO,
    run_on_loop,
)

logger = logging.getLogger(__name__)

# Wall-clock seam (timestamps in intents/roots/leases; injectable for
# the fake-clock unit matrix). Durations run on the monotonic clock.
_wall = time.time

_CAS_PREFIX = "cas+"

BLOBS_DIR = "blobs"
INTENTS_DIR = "intents"
ROOTS_DIR = "roots"
REFCOUNTS_PATH = "refcounts.json"
STORE_JOURNAL_PATH = "upload_journal"
CONFIG_PATH = "config.json"
GC_LOCK_PATH = "gc.lock"

#: Store sub-paths whose existence identifies a directory as a store.
_STORE_SHAPE = (BLOBS_DIR, INTENTS_DIR, ROOTS_DIR, REFCOUNTS_PATH,
                STORE_JOURNAL_PATH, GC_LOCK_PATH)


# ---------------------------------------------------------------- keys


def blob_key(triple: Tuple[int, str, str]) -> str:
    """``(nbytes, "crc32c:<8hex>", "xxh64:<16hex>") -> "<8hex>-<16hex>"``
    — the store filename of the content, derived from the SAME dual-hash
    evidence the take journal and upload journal record (PR 14's
    ``uncompressed_dedup_hash`` keeps the pre-compression identity in
    the manifest; the store keys the bytes actually written)."""
    _, crc, xxh = triple
    return f"{crc.split(':', 1)[1]}-{xxh.split(':', 1)[1]}"


def blob_path(key: str) -> str:
    return f"{BLOBS_DIR}/{key}"


def _root_digest(dir_id: str) -> str:
    return hashlib.sha1(dir_id.encode("utf-8")).hexdigest()[:16]


def parse_cas_url(url_path: str) -> Optional[str]:
    """``cas+<base>://<path>`` -> ``<base>://<path>``, or None when
    ``url_path`` is not a CAS URL."""
    if "://" not in url_path:
        return None
    scheme, path = url_path.split("://", 1)
    if not scheme.lower().startswith(_CAS_PREFIX):
        return None
    base = scheme[len(_CAS_PREFIX):] or "fs"
    return f"{base}://{path}"


def store_local_root(store_url: Optional[str]) -> Optional[str]:
    """The local filesystem root of a store URL (bare path, ``fs://``,
    ``file://``, or chaos-wrapped fs), or None for non-fs stores. Store
    gc/fsck need it for mtimes (the grace window runs on file age);
    deletes still go through the composed plugin so chaos plans apply."""
    if not store_url:
        return None
    if "://" not in store_url:
        return os.path.abspath(store_url)
    scheme, path = store_url.split("://", 1)
    s = scheme.lower()
    if s.startswith("chaos+"):
        s = s[len("chaos+"):] or "fs"
    if s in ("fs", "file"):
        return os.path.abspath(path)
    return None


def resolve_store_url(
    explicit: Optional[str] = None,
    storage_options: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    from .knobs import get_cas_dir

    return (
        explicit
        or (storage_options or {}).get("cas_dir")
        or get_cas_dir()
    )


def _store_options(
    storage_options: Optional[Dict[str, Any]],
) -> Dict[str, Any]:
    """Options for the STORE's own plugin build: never recursively
    CAS-composed, and never drawing the snapshot plugin's explicit
    fault plan object (a chaos store URL draws its own plan from
    TPUSNAP_FAULT_SPEC / its own options)."""
    opts = dict(storage_options or {})
    opts["cas"] = False
    opts.pop("fault_plan", None)
    return opts


# ----------------------------------------------------------- ref records


def refs_from_json(data: bytes) -> Optional[Dict[str, Any]]:
    """Parse one per-rank ref record file; None when unparseable. Like
    the take/upload journals the refs are sanitized at the parse
    boundary — a malformed entry reads as absent, never crashes a
    reader."""
    try:
        d = json.loads(data.decode("utf-8"))
    except Exception:
        return None
    if not isinstance(d, dict) or not isinstance(d.get("refs", {}), dict):
        return None
    d.setdefault("version", 1)
    refs = {}
    for k, v in (d.get("refs") or {}).items():
        if (
            isinstance(v, (list, tuple))
            and len(v) >= 3
            and isinstance(v[0], int)
        ):
            refs[str(k)] = [int(v[0]), str(v[1]), str(v[2])]
    d["refs"] = refs
    return d


def cas_rank_path(rank: int) -> str:
    return f"{CAS_REFS_DIR}/rank_{rank}.json"


def read_refs(
    storage: StoragePlugin, event_loop: asyncio.AbstractEventLoop
) -> Tuple[Dict[str, List[Any]], Optional[str]]:
    """Merge every rank's ref records at this plugin's root: location →
    [nbytes, crc, xxh], plus the recorded store URL (from any rank's
    header). Empty on listing-incapable backends or when no refs
    exist."""
    files = storage.sync_list_with_sizes(event_loop) or {}
    refs: Dict[str, List[Any]] = {}
    store: Optional[str] = None
    for p in sorted(files):
        if not p.startswith(CAS_REFS_DIR + "/") or ".tmp." in p:
            continue
        read_io = ReadIO(path=p)
        try:
            storage.sync_read(read_io, event_loop)
        except Exception:
            continue
        doc = refs_from_json(read_io.buf.getvalue())
        if doc is None:
            logger.warning("Unparseable CAS ref record at %r; ignoring", p)
            continue
        refs.update(doc["refs"])
        store = store or doc.get("store")
    return refs, store


def read_refs_dir(local_dir: str) -> Tuple[Dict[str, List[Any]], Optional[str]]:
    """Direct-file variant of :func:`read_refs` for a LOCAL snapshot
    directory (store gc marks from roots without building per-root
    plugins)."""
    refs: Dict[str, List[Any]] = {}
    store: Optional[str] = None
    d = os.path.join(local_dir, CAS_REFS_DIR)
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return refs, store
    for name in names:
        if ".tmp." in name:
            continue
        try:
            with open(os.path.join(d, name), "rb") as f:
                doc = refs_from_json(f.read())
        except OSError:
            continue
        if doc is None:
            continue
        refs.update(doc["refs"])
        store = store or doc.get("store")
    return refs, store


def blob_exists_in_store(store_url: Optional[str], key: str) -> bool:
    """Deep existence probe against a store — snapshot fsck's
    dangling-ref check (a ref whose blob a sweep raced away is the one
    restore-breaking CAS state). Local-root stores probe the filesystem
    directly; others pay a plugin read probe."""
    if not store_url:
        return False
    root = store_local_root(store_url)
    if root is not None:
        return os.path.exists(os.path.join(root, BLOBS_DIR, key))
    store = CASStore(store_url, None)
    event_loop = asyncio.new_event_loop()
    try:
        return run_on_loop(event_loop, store.blob_exists(key))
    finally:
        try:
            run_on_loop(event_loop, store.close())
        finally:
            event_loop.close()


def prune_refs(
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
    keep: Set[str],
) -> int:
    """Drop ref-record entries whose location is outside ``keep`` —
    snapshot gc prunes refs a superseded retake stranded, so they stop
    pinning store blobs nothing references. Returns entries dropped."""
    files = storage.sync_list_with_sizes(event_loop) or {}
    pruned = 0
    for p in sorted(files):
        if not p.startswith(CAS_REFS_DIR + "/") or ".tmp." in p:
            continue
        read_io = ReadIO(path=p)
        try:
            storage.sync_read(read_io, event_loop)
        except Exception:
            logger.debug("CAS ref prune: unreadable %r", p, exc_info=True)
            continue
        doc = refs_from_json(read_io.buf.getvalue())
        if doc is None:
            continue
        kept = {loc: rec for loc, rec in doc["refs"].items() if loc in keep}
        if len(kept) == len(doc["refs"]):
            continue
        pruned += len(doc["refs"]) - len(kept)
        doc["refs"] = kept
        storage.sync_write_atomic(
            WriteIO(path=p, buf=json.dumps(doc).encode("utf-8")), event_loop
        )
    return pruned


# ------------------------------------------------------------- the store


class CASStore:
    """Async access to one store root through its composed plugin.

    One instance per CASStoragePlugin; the store plugin draws its own
    middleware (chaos for a ``chaos+fs://`` store URL, instrumentation,
    retry) from its URL, exactly like any snapshot plugin."""

    def __init__(
        self,
        store_url: str,
        storage_options: Optional[Dict[str, Any]] = None,
    ) -> None:
        from .storage_plugin import url_to_storage_plugin

        self.url = store_url
        self.local_root = store_local_root(store_url)
        self.plugin = url_to_storage_plugin(
            store_url, _store_options(storage_options)
        )
        self._config: Optional[Dict[str, Any]] = None

    async def blob_exists(self, key: str) -> bool:
        probe = ReadIO(path=blob_path(key), byte_range=(0, 1))
        try:
            await self.plugin.read(probe)
            return True
        except FileNotFoundError:
            return False

    async def publish(self, key: str, buf: Any) -> None:
        await self.plugin.write_atomic(WriteIO(path=blob_path(key), buf=buf))

    async def write_intent(self, key: str, job: Optional[str]) -> str:
        owner = f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        path = f"{INTENTS_DIR}/{key}__{owner}"
        payload = json.dumps({"ts": _wall(), "job": job}).encode("utf-8")
        await self.plugin.write_atomic(WriteIO(path=path, buf=payload))
        return path

    async def clear_intent(self, path: str) -> None:
        try:
            await self.plugin.delete(path)
        except Exception:
            # Best-effort: a stranded intent only delays reclamation of
            # its key by one grace window.
            logger.debug("CAS intent clear failed for %r", path, exc_info=True)

    async def write_root(self, dir_id: str) -> None:
        payload = json.dumps({"dir": dir_id, "ts": _wall()}).encode("utf-8")
        await self.plugin.write_atomic(
            WriteIO(path=f"{ROOTS_DIR}/{_root_digest(dir_id)}", buf=payload)
        )

    def config(self) -> Dict[str, Any]:
        if self._config is None:
            cfg: Dict[str, Any] = {}
            if self.local_root is not None:
                try:
                    with open(
                        os.path.join(self.local_root, CONFIG_PATH), "rb"
                    ) as f:
                        loaded = json.loads(f.read().decode("utf-8"))
                    if isinstance(loaded, dict):
                        cfg = loaded
                except (OSError, ValueError):
                    cfg = {}
            self._config = cfg
        return self._config

    def remote_url(self) -> Optional[str]:
        from .knobs import get_cas_remote

        return self.config().get("remote") or get_cas_remote()

    async def read_blob(self, key: str, read_io: ReadIO) -> None:
        """Read a blob into ``read_io`` (byte_range/into/want_crc
        honored), falling back to the store's remote mirror when the
        local copy was evicted AND the store journal holds upload
        evidence for the key."""
        trial = ReadIO(
            path=blob_path(key),
            byte_range=read_io.byte_range,
            into=read_io.into,
            want_crc=read_io.want_crc,
        )
        try:
            await self.plugin.read(trial)
        except FileNotFoundError:
            remote = self.remote_url()
            journal = read_store_journal(self.local_root or "")
            if remote is None or key not in (journal or {}).get("blobs", {}):
                raise
            from .storage_plugin import url_to_storage_plugin

            rp = url_to_storage_plugin(remote, _store_options(None))
            try:
                trial = ReadIO(
                    path=blob_path(key),
                    byte_range=read_io.byte_range,
                    into=read_io.into,
                    want_crc=read_io.want_crc,
                )
                await rp.read(trial)
                telemetry.incr("cas.remote_fallback_reads")
            finally:
                await rp.close()
        read_io.buf = trial.buf
        read_io.in_place = trial.in_place
        read_io.crc32c = trial.crc32c
        read_io.crc_algo = trial.crc_algo

    async def close(self) -> None:
        await self.plugin.close()


def read_store_journal(local_root: str) -> Optional[Dict[str, Any]]:
    """The store-level upload journal (blob key → dual-hash evidence of
    the bytes proven remote), or None. Advisory like every journal:
    malformed entries read as absent evidence."""
    try:
        with open(os.path.join(local_root, STORE_JOURNAL_PATH), "rb") as f:
            d = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        return None
    if not isinstance(d, dict) or not isinstance(d.get("blobs", {}), dict):
        return None
    d.setdefault("version", 1)
    d["blobs"] = {
        str(k): [int(v[0]), str(v[1]), str(v[2])]
        for k, v in (d.get("blobs") or {}).items()
        if isinstance(v, (list, tuple)) and len(v) == 3
        and isinstance(v[0], int)
    }
    return d


# ----------------------------------------------------------- the plugin


class CASStoragePlugin(StoragePlugin):
    """Composes the content-addressed store around a snapshot's (fully
    middleware-composed) storage plugin:

    - payload ``write``s publish to the store (or dedup-skip when the
      key already exists) and land a ref record instead of a private
      file — ``cas.dedup_bytes_saved`` / ``cas.blobs_published`` count
      the split;
    - ``read``/``list_with_sizes``/``delete`` resolve refs
      transparently (a ref'd location lists with its recorded size, so
      salvage-resume's existence/size cross-check keeps working);
    - the metadata commit force-flushes the ref records FIRST — refs
      are gc liveness roots and must be durable strictly before the
      snapshot becomes restorable.

    Sidecars, the metadata file and per-take slab objects (``batched/``,
    uuid-named, never reusable) pass through untouched."""

    handles_own_retries = True  # sub-plugins compose their own middleware

    def __init__(
        self,
        inner: StoragePlugin,
        base_url: str,
        store_url: Optional[str] = None,
        storage_options: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.inner = inner
        self.base_url = base_url
        self.rank = 0  # set by the take after construction
        self._storage_options = storage_options
        self._store_url = store_url
        self._store: Optional[CASStore] = None
        self._refs: Dict[str, List[Any]] = {}
        self._refs_loaded = False
        self._root_written = False
        self._refs_lock: Optional[asyncio.Lock] = None
        self._publishing: Dict[str, asyncio.Task] = {}

    # --- store / refs plumbing -------------------------------------------

    def store(self) -> CASStore:
        if self._store is None:
            if self._store_url is None:
                raise RuntimeError(
                    f"CAS layer for {self.base_url!r} has no store: set "
                    "TPUSNAP_CAS_DIR (or storage_options['cas_dir'])"
                )
            self._store = CASStore(self._store_url, self._storage_options)
        return self._store

    def _lock(self) -> asyncio.Lock:
        if self._refs_lock is None:
            self._refs_lock = asyncio.Lock()
        return self._refs_lock

    def root_id(self) -> str:
        """The identity the store's root record names: the local dir
        when the base resolves to one (store gc then reads the refs
        directly), else the base URL itself."""
        return store_local_root(self.base_url) or self.base_url

    async def _ensure_refs_loaded(self) -> None:
        if self._refs_loaded:
            return
        self._refs_loaded = True
        files = await self.inner.list_with_sizes() or {}
        for p in sorted(files):
            if not p.startswith(CAS_REFS_DIR + "/") or ".tmp." in p:
                continue
            read_io = ReadIO(path=p)
            try:
                await self.inner.read(read_io)
            except Exception:
                continue
            doc = refs_from_json(read_io.buf.getvalue())
            if doc is None:
                continue
            # Merge every rank's records (reads/listings must resolve
            # peers' refs); this rank's flush rewrites only its own
            # file, so the merge never clobbers another rank's entries.
            for loc, rec in doc["refs"].items():
                self._refs.setdefault(loc, rec)
            if self._store_url is None and doc.get("store"):
                self._store_url = doc["store"]

    async def _flush_refs(self) -> None:
        async with self._lock():
            if not self._root_written:
                # Root BEFORE the first ref flush: refs without a root
                # record are invisible to the store's mark phase — the
                # blobs they pin would read as orphans.
                await self.store().write_root(self.root_id())
                self._root_written = True
            mine = {
                loc: rec
                for loc, rec in self._refs.items()
                if rec is not None
            }
            payload = json.dumps(
                {
                    "version": 1,
                    "store": self.store().url,
                    "refs": mine,
                }
            ).encode("utf-8")
            await self.inner.write_atomic(
                WriteIO(path=cas_rank_path(self.rank), buf=payload)
            )

    @staticmethod
    def _is_payload(path: str) -> bool:
        from .snapshot import SNAPSHOT_METADATA_FNAME

        return not (
            path.startswith(SIDECAR_PREFIX)
            or path.startswith("batched/")
            or path == SNAPSHOT_METADATA_FNAME
            or ".tmp." in path.rsplit("/", 1)[-1]
        )

    def _triple_of(self, write_io: WriteIO) -> Tuple[int, str, str]:
        # The journaling layer above stashes its fused-pass dual hash on
        # the WriteIO (one hash pass per blob, not two); compute only
        # when the take runs without journaling.
        triple = getattr(write_io, "dedup_triple", None)
        if triple is not None:
            return tuple(triple)  # type: ignore[return-value]
        from .lifecycle import dual_hash_evidence

        return dual_hash_evidence(write_io.buf)

    async def _publish_once(self, key: str, buf: Any) -> None:
        """Publish ``key`` at most once per plugin instance even under
        concurrent writes of identical content (two coroutines sharing
        one pid would interleave on the same ``.tmp.<pid>`` file)."""
        pending = self._publishing.get(key)
        if pending is None:
            pending = asyncio.ensure_future(self.store().publish(key, buf))
            self._publishing[key] = pending
        try:
            await asyncio.shield(pending)
        finally:
            if self._publishing.get(key) is pending and pending.done():
                del self._publishing[key]

    # --- plugin interface -------------------------------------------------

    async def write(self, write_io: WriteIO) -> None:
        if not self._is_payload(write_io.path):
            await self.inner.write(write_io)
            return
        await self._ensure_refs_loaded()
        triple = self._triple_of(write_io)
        key = blob_key(triple)
        store = self.store()
        from .knobs import get_job_id

        # 1. intent first: the short-lived record that keeps a
        # concurrent gc's mark phase from sweeping this key inside the
        # adopt-then-ref window.
        intent = await store.write_intent(key, get_job_id())
        if await store.blob_exists(key):
            telemetry.incr("cas.ref_hits")
            telemetry.incr("cas.dedup_bytes_saved", triple[0])
            flight.record("cas_ref_hit", op=write_io.path, bytes=triple[0])
        else:
            # 2. tmp+rename keyed by hash: concurrent publishers of the
            # same content converge on one file.
            await self._publish_once(key, write_io.buf)
            telemetry.incr("cas.blobs_published")
            telemetry.incr("cas.bytes_published", triple[0])
            flight.record("cas_publish", op=write_io.path, bytes=triple[0])
        # 3. the ref record — the liveness root — lands before this
        # write completes (the journal layer above records completion
        # evidence only after this returns).
        self._refs[write_io.path] = list(triple)
        await self._flush_refs()
        # 4. adopt-then-ref race closure: re-verify AFTER the ref is
        # durable; if a concurrent sweep won the window we still hold
        # the bytes and republishing converges (the next mark phase
        # sees our ref).
        for _ in range(3):
            if await store.blob_exists(key):
                break
            telemetry.incr("cas.republished_after_race")
            await store.publish(key, write_io.buf)
        else:
            raise RuntimeError(
                f"CAS blob {key} vanished repeatedly after publish — "
                f"store {store.url!r} is losing writes"
            )
        # 5. the intent has served its purpose.
        await store.clear_intent(intent)

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        from .snapshot import SNAPSHOT_METADATA_FNAME

        if write_io.path == SNAPSHOT_METADATA_FNAME:
            # Ref-before-metadata invariant: the commit must never make
            # a snapshot restorable whose liveness roots aren't durable.
            await self._ensure_refs_loaded()
            if self._refs:
                await self._flush_refs()
        await self.inner.write_atomic(write_io, durable=durable)

    async def read(self, read_io: ReadIO) -> None:
        if not read_io.path.startswith(SIDECAR_PREFIX):
            await self._ensure_refs_loaded()
            rec = self._refs.get(read_io.path)
            if rec is not None:
                await self.store().read_blob(blob_key(tuple(rec)), read_io)
                telemetry.incr("cas.store_reads")
                # Access-ledger provenance: a ref-translated store read
                # (the logical location has no private copy).
                read_io.source = "cas"
                return
        await self.inner.read(read_io)

    async def delete(self, path: str) -> None:
        await self._ensure_refs_loaded()
        if self._refs.get(path) is not None:
            # Deleting a ref'd location drops the REF, never the shared
            # blob — reclaiming unreferenced blobs is gc_store's job
            # (another job may still hold a ref to the same key).
            del self._refs[path]
            await self._flush_refs()
            return
        await self.inner.delete(path)

    async def list_with_sizes(self) -> Optional[dict]:
        files = await self.inner.list_with_sizes()
        if files is None:
            return None
        await self._ensure_refs_loaded()
        out = dict(files)
        for loc, rec in self._refs.items():
            # Ref'd locations list with their recorded size: the
            # existence/size cross-check of salvage-resume and the
            # scheduler's dedup path see the store-backed blob exactly
            # like a private copy.
            out.setdefault(loc, int(rec[0]))
        return out

    async def flush_created_dirs(self) -> None:
        await self.inner.flush_created_dirs()

    async def close(self) -> None:
        await self.inner.close()
        if self._store is not None:
            await self._store.close()

    # --- scheduling transparency -----------------------------------------

    @property
    def supports_in_place_reads(self) -> bool:  # type: ignore[override]
        if self._store is not None:
            return (
                self.inner.supports_in_place_reads
                and self._store.plugin.supports_in_place_reads
            )
        return self.inner.supports_in_place_reads

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        return self.inner.in_place_read_overhead_bytes(nbytes)

    def drain_in_flight(self) -> None:
        self.inner.drain_in_flight()
        if self._store is not None:
            self._store.plugin.drain_in_flight()

    def classify_transient(self, exc: BaseException) -> bool:
        from .retry import default_classify_transient

        return getattr(
            self.inner, "classify_transient", default_classify_transient
        )(exc)


def build_cas_plugin(
    url_path: str, storage_options: Optional[Dict[str, Any]] = None
) -> CASStoragePlugin:
    """Resolve an explicit ``cas+<base>://<path>`` URL: the base
    composes its ordinary middleware; the store comes from
    ``storage_options['cas_dir']`` / ``TPUSNAP_CAS_DIR``."""
    from .storage_plugin import url_to_storage_plugin

    base = parse_cas_url(url_path)
    if base is None:
        raise ValueError(f"not a CAS URL: {url_path!r}")
    inner_opts = dict(storage_options or {})
    inner_opts["cas"] = False  # no double composition
    inner = url_to_storage_plugin(base, inner_opts)
    return CASStoragePlugin(
        inner,
        base_url=base,
        store_url=resolve_store_url(None, storage_options),
        storage_options=storage_options,
    )


def find_cas_plugin(plugin: StoragePlugin) -> Optional[CASStoragePlugin]:
    """The CAS layer inside a composed plugin chain, if any (walks
    ``.inner`` and a write-back tier's LOCAL sub-plugin — the tier the
    take writes through)."""
    from .tiering import TieredStoragePlugin

    base: Optional[StoragePlugin] = plugin
    while base is not None:
        if isinstance(base, CASStoragePlugin):
            return base
        if isinstance(base, TieredStoragePlugin):
            base = base.local
            continue
        inner = getattr(base, "inner", None)
        base = inner if isinstance(inner, StoragePlugin) else None
    return None


# --------------------------------------------------------- store fsck/gc


@dataclass
class StoreFsckReport:
    """Read-only classification of one store directory."""

    path: str
    state: str  # "store" | "not-a-store"
    blobs: Dict[str, int] = field(default_factory=dict)  # key -> size
    referenced: Dict[str, int] = field(default_factory=dict)  # key -> refcount
    orphans: Dict[str, int] = field(default_factory=dict)  # key -> size
    dangling: List[Dict[str, Any]] = field(default_factory=list)
    torn_publishes: List[str] = field(default_factory=list)
    intents: int = 0
    stale_intents: int = 0
    roots: int = 0
    stale_roots: List[str] = field(default_factory=list)
    refcount_divergence: List[str] = field(default_factory=list)
    detail: Optional[str] = None

    @property
    def orphan_bytes(self) -> int:
        return sum(self.orphans.values())

    def summary(self) -> str:
        if self.state != "store":
            return f"{self.path}: {self.state} ({self.detail})"
        s = (
            f"{self.path}: store; {len(self.blobs)} blob(s), "
            f"{len(self.referenced)} referenced by {self.roots} root(s), "
            f"{len(self.orphans)} orphan(s) ({self.orphan_bytes} bytes "
            "reclaimable)"
        )
        if self.dangling:
            s += f"; {len(self.dangling)} DANGLING ref(s)"
        if self.torn_publishes:
            s += f"; {len(self.torn_publishes)} torn publish(es)"
        if self.stale_intents:
            s += f"; {self.stale_intents} stale intent(s)"
        if self.refcount_divergence:
            s += (
                f"; refcount cache diverges on "
                f"{len(self.refcount_divergence)} key(s)"
            )
        return s


def _scan_store(
    root: str, grace_s: float
) -> Tuple[
    Dict[str, int],  # blobs key -> size
    List[Tuple[str, float]],  # torn tmp relpaths + age
    Dict[str, int],  # marks key -> refcount
    List[Dict[str, Any]],  # dangling refs
    List[Tuple[str, float, bool]],  # intents (relpath, age, stale)
    List[Tuple[str, float, bool]],  # roots (relpath, age, stale)
    Dict[str, float],  # blob key -> age
]:
    """One shared walk for fsck/gc: blobs, marks from live roots' ref
    records, publish intents and root records with their ages."""
    now = _wall()

    def _age(p: str) -> float:
        try:
            return max(0.0, now - os.stat(p).st_mtime)
        except OSError:
            return 0.0

    blobs: Dict[str, int] = {}
    blob_age: Dict[str, float] = {}
    torn: List[Tuple[str, float]] = []
    bdir = os.path.join(root, BLOBS_DIR)
    try:
        names = sorted(os.listdir(bdir))
    except OSError:
        names = []
    for name in names:
        p = os.path.join(bdir, name)
        if ".tmp." in name:
            torn.append((f"{BLOBS_DIR}/{name}", _age(p)))
            continue
        try:
            blobs[name] = os.stat(p).st_size
        except OSError:
            continue
        blob_age[name] = _age(p)

    marks: Dict[str, int] = {}
    dangling: List[Dict[str, Any]] = []
    roots: List[Tuple[str, float, bool]] = []
    rdir = os.path.join(root, ROOTS_DIR)
    try:
        rnames = sorted(os.listdir(rdir))
    except OSError:
        rnames = []
    for name in rnames:
        p = os.path.join(rdir, name)
        if ".tmp." in name:
            continue
        try:
            with open(p, "rb") as f:
                rec = json.loads(f.read().decode("utf-8"))
            dir_id = str(rec["dir"])
        except (OSError, ValueError, KeyError, TypeError):
            roots.append((f"{ROOTS_DIR}/{name}", _age(p), True))
            continue
        refs, _ = read_refs_dir(dir_id)
        stale = not os.path.isdir(dir_id)
        roots.append((f"{ROOTS_DIR}/{name}", _age(p), stale))
        for loc, rec3 in refs.items():
            key = blob_key(tuple(rec3))
            marks[key] = marks.get(key, 0) + 1
            if key not in blobs:
                dangling.append(
                    {"root": dir_id, "location": loc, "key": key}
                )

    intents: List[Tuple[str, float, bool]] = []
    idir = os.path.join(root, INTENTS_DIR)
    try:
        inames = sorted(os.listdir(idir))
    except OSError:
        inames = []
    for name in inames:
        p = os.path.join(idir, name)
        age = _age(p)
        stale = age > grace_s
        intents.append((f"{INTENTS_DIR}/{name}", age, stale))
        if not stale:
            # A fresh intent marks its key (refcount contribution 0 —
            # protected from the sweep, not yet "referenced"): the
            # publisher is, or very recently was, inside the
            # publish-to-ref window.
            marks.setdefault(name.split("__", 1)[0], 0)
    return blobs, torn, marks, dangling, intents, roots, blob_age


def _is_store_dir(root: str) -> bool:
    return any(
        os.path.exists(os.path.join(root, p)) for p in _STORE_SHAPE
    )


def fsck_store(
    store_url: str, grace_s: Optional[float] = None
) -> StoreFsckReport:
    """Store-wide fsck: read-only; names every CAS failure-mode state
    (dangling ref, orphan, torn publish, stale intent/root, refcount
    cache divergence). Exposed as ``python -m tpusnap fsck --store``.

    Exit contract at the CLI: 0 = clean or merely-reclaimable (orphans
    and torn publishes are NORMAL crash debris, not corruption); 4 =
    dangling refs (a committed snapshot references a blob the store no
    longer holds — restore-breaking); 3 = not a store."""
    from .knobs import get_cas_grace_s

    grace = get_cas_grace_s() if grace_s is None else grace_s
    root = store_local_root(store_url)
    report = StoreFsckReport(path=store_url, state="not-a-store")
    if root is None:
        report.detail = f"store URL {store_url!r} has no local filesystem root"
        return report
    if not os.path.isdir(root) or not _is_store_dir(root):
        report.detail = (
            "no store shape (blobs/, roots/, intents/) at this path"
        )
        return report
    blobs, torn, marks, dangling, intents, roots, _ = _scan_store(root, grace)
    report.state = "store"
    report.blobs = blobs
    report.torn_publishes = [p for p, _ in torn]
    report.dangling = dangling
    report.intents = len(intents)
    report.stale_intents = sum(1 for _, _, stale in intents if stale)
    report.roots = len(roots)
    report.stale_roots = [p for p, _, stale in roots if stale]
    report.referenced = {
        k: n for k, n in marks.items() if k in blobs and n > 0
    }
    report.orphans = {
        k: sz for k, sz in blobs.items() if k not in marks
    }
    cache = None
    try:
        with open(os.path.join(root, REFCOUNTS_PATH), "rb") as f:
            cache = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError):
        cache = None
    if isinstance(cache, dict):
        report.refcount_divergence = sorted(
            k
            for k in set(cache) | set(report.referenced)
            if int(cache.get(k, 0)) != report.referenced.get(k, 0)
        )
    return report


@dataclass
class StoreGCReport:
    path: str
    dry_run: bool
    reclaimed: Dict[str, int] = field(default_factory=dict)
    kept_young: int = 0  # unmarked but inside the grace window
    marked: int = 0
    errors: List[str] = field(default_factory=list)

    @property
    def bytes_reclaimed(self) -> int:
        return sum(self.reclaimed.values())

    def summary(self) -> str:
        verb = "would reclaim" if self.dry_run else "reclaimed"
        s = (
            f"{self.path}: {verb} {len(self.reclaimed)} file(s), "
            f"{self.bytes_reclaimed} bytes ({self.marked} blob(s) "
            f"referenced, {self.kept_young} inside the grace window)"
        )
        if self.errors:
            s += f" ({len(self.errors)} error(s))"
        return s


def _read_lease(root: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(root, GC_LOCK_PATH), "rb") as f:
            d = json.loads(f.read().decode("utf-8"))
        return d if isinstance(d, dict) else None
    except (OSError, ValueError):
        return None


def gc_store(
    store_url: str,
    dry_run: bool = True,
    grace_s: Optional[float] = None,
    lease_ttl_s: Optional[float] = None,
    owner: Optional[str] = None,
    storage_options: Optional[Dict[str, Any]] = None,
) -> StoreGCReport:
    """Mark-and-sweep over the store's ref records.

    Mark: every blob key referenced by any live root's ref records, or
    named by a publish intent younger than the grace window. Sweep
    (oldest-debris-only — everything must out-age ``grace_s``):
    unmarked blobs, ``.tmp.*`` torn publishes, stale intents, and root
    records whose snapshot directory no longer exists. The advisory
    ``refcounts.json`` cache is rewritten from the fresh marks.

    Concurrency: a per-store lock lease (``gc.lock``) refuses a second
    concurrent sweeper; a lease abandoned by a SIGKILLed gc is stolen
    once expired. A SIGKILL anywhere mid-sweep converges on re-run —
    every deletion is independently justified by the same mark state.

    Exposed as ``python -m tpusnap gc --store <dir>`` (dry-run by
    default, ``--force`` to delete)."""
    from .knobs import get_cas_grace_s, get_cas_lease_ttl_s
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    grace = get_cas_grace_s() if grace_s is None else grace_s
    ttl = get_cas_lease_ttl_s() if lease_ttl_s is None else lease_ttl_s
    root = store_local_root(store_url)
    if root is None:
        raise RuntimeError(
            f"gc --store needs a local-filesystem store root; "
            f"{store_url!r} has none (the grace window runs on file age)"
        )
    report = StoreGCReport(path=store_url, dry_run=dry_run)
    if not os.path.isdir(root) or not _is_store_dir(root):
        return report  # nothing store-shaped: trivially converged

    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(
            store_url, event_loop, _store_options(storage_options)
        )
        try:
            me = owner or f"{os.uname().nodename}:{os.getpid()}"
            if not dry_run:
                lease = _read_lease(root)
                now = _wall()
                if (
                    lease is not None
                    and lease.get("owner") != me
                    and isinstance(lease.get("expires_at"), (int, float))
                    and lease["expires_at"] > now
                ):
                    raise RuntimeError(
                        f"store gc already running (lease held by "
                        f"{lease.get('owner')!r} for another "
                        f"{lease['expires_at'] - now:.0f}s) — re-run "
                        "after it expires"
                    )
                storage.sync_write_atomic(
                    WriteIO(
                        path=GC_LOCK_PATH,
                        buf=json.dumps(
                            {"owner": me, "expires_at": now + ttl}
                        ).encode("utf-8"),
                    ),
                    event_loop,
                )
            (
                blobs,
                torn,
                marks,
                _dangling,
                intents,
                roots,
                blob_age,
            ) = _scan_store(root, grace)
            report.marked = sum(1 for k in marks if k in blobs)
            targets: Dict[str, int] = {}
            for key, sz in blobs.items():
                if key in marks:
                    continue
                if blob_age.get(key, 0.0) <= grace:
                    report.kept_young += 1
                    continue
                targets[blob_path(key)] = sz
            for rel, age in torn:
                if age > grace:
                    targets[rel] = 0
            for rel, _age, stale in intents:
                if stale:
                    targets[rel] = 0
            for rel, age, stale in roots:
                if stale and age > grace:
                    targets[rel] = 0
            report.reclaimed = dict(targets)
            if dry_run:
                return report
            done: Dict[str, int] = {}
            for rel in sorted(targets):
                try:
                    storage.sync_delete(rel, event_loop)
                    done[rel] = targets[rel]
                except FileNotFoundError:
                    done[rel] = targets[rel]  # a racing sweeper got it
                except Exception as e:
                    report.errors.append(f"{rel}: {e}")
            report.reclaimed = done
            telemetry.incr("cas.gc_blobs_swept", len(done))
            # Rewrite the advisory refcount cache from the fresh marks
            # (publishers never touch it; divergence = staleness, named
            # by fsck, re-derived here).
            counts = {
                k: n for k, n in marks.items() if n > 0 and k in blobs
            }
            try:
                storage.sync_write_atomic(
                    WriteIO(
                        path=REFCOUNTS_PATH,
                        buf=json.dumps(counts).encode("utf-8"),
                    ),
                    event_loop,
                )
            except Exception as e:
                report.errors.append(f"{REFCOUNTS_PATH}: {e}")
            try:
                storage.sync_delete(GC_LOCK_PATH, event_loop)
            except Exception:
                logger.debug(
                    "store gc lease release failed (expires on its own)",
                    exc_info=True,
                )
            return report
        finally:
            storage.sync_close(event_loop)
    finally:
        event_loop.close()


# ----------------------------------------------------------- store drain


@dataclass
class StoreDrainReport:
    path: str
    state: str  # "durable" | "no-remote" | "partial"
    uploaded: int = 0
    skipped: int = 0
    errors: List[str] = field(default_factory=list)

    def summary(self) -> str:
        return (
            f"{self.path}: {self.state}; {self.uploaded} blob(s) "
            f"uploaded, {self.skipped} skipped via journal evidence"
            + (f" ({len(self.errors)} error(s))" if self.errors else "")
        )


def drain_store(
    store_url: str,
    remote_url: Optional[str] = None,
    keys: Optional[Set[str]] = None,
    storage_options: Optional[Dict[str, Any]] = None,
) -> StoreDrainReport:
    """Upload store blobs to the store's remote mirror ONCE store-wide:
    each blob's dual-hash evidence lands in the store-level upload
    journal after its remote write, so a crashed drain re-hashes and
    SKIPS everything already proven remote — the tiering drain calls
    this for the keys a tiered CAS snapshot references, instead of
    uploading per-snapshot private copies."""
    from .lifecycle import dual_hash_evidence
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    root = store_local_root(store_url)
    report = StoreDrainReport(path=store_url, state="partial")
    if root is None or not os.path.isdir(root):
        report.state = "no-remote"
        report.errors.append(f"no local store at {store_url!r}")
        return report
    store = CASStore(store_url, storage_options)
    remote = remote_url or store.remote_url()
    if not remote:
        report.state = "no-remote"
        report.errors.append(
            "store has no remote mirror (set TPUSNAP_CAS_REMOTE or "
            "config.json {'remote': ...})"
        )
        return report
    journal = read_store_journal(root) or {"version": 1, "blobs": {}}
    journal["remote"] = remote
    bdir = os.path.join(root, BLOBS_DIR)
    try:
        names = sorted(os.listdir(bdir))
    except OSError:
        names = []
    todo = [n for n in names if ".tmp." not in n]
    if keys is not None:
        todo = [n for n in todo if n in keys]
    event_loop = asyncio.new_event_loop()
    try:
        rp = url_to_storage_plugin_in_event_loop(
            remote, event_loop, _store_options(storage_options)
        )
        try:
            for key in todo:
                try:
                    with open(os.path.join(bdir, key), "rb") as f:
                        buf = f.read()
                except OSError as e:
                    report.errors.append(f"{key}: {e}")
                    continue
                triple = dual_hash_evidence(buf)
                prior = journal["blobs"].get(key)
                if prior is not None and tuple(prior) == triple:
                    report.skipped += 1
                    continue
                try:
                    rp.sync_write_atomic(
                        WriteIO(path=blob_path(key), buf=buf), event_loop
                    )
                except Exception as e:
                    report.errors.append(f"{key}: {e}")
                    continue
                journal["blobs"][key] = list(triple)
                report.uploaded += 1
                telemetry.incr("cas.blobs_drained")
                # Journal after EVERY upload (merge-on-write like the
                # tiering journal): a SIGKILL mid-drain loses at most
                # one blob's evidence, never the batch's.
                _flush_store_journal(root, journal)
        finally:
            rp.sync_close(event_loop)
    finally:
        event_loop.close()
    _flush_store_journal(root, journal)
    report.state = "durable" if not report.errors else "partial"
    return report


def _flush_store_journal(root: str, journal: Dict[str, Any]) -> None:
    """Read-modify-write merge + atomic rewrite of the store journal:
    concurrent drains (two jobs' tier drains hitting one store) union
    their evidence instead of clobbering each other."""
    path = os.path.join(root, STORE_JOURNAL_PATH)
    current = read_store_journal(root)
    if current is not None:
        merged = dict(current.get("blobs") or {})
        merged.update(journal.get("blobs") or {})
        journal = dict(journal)
        journal["blobs"] = merged
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(journal, f)
        os.replace(tmp, path)
    except OSError:
        logger.warning(
            "store upload journal flush failed (re-upload on next drain)",
            exc_info=True,
        )


def store_remote_evidence(
    store_url: str, keys: Set[str]
) -> Tuple[Set[str], Optional[str]]:
    """Which of ``keys`` the store journal proves remote, plus the
    journal's remote URL — the gate the tiering drain and
    ``gc --evict-local`` run on before treating a shared blob as
    durable elsewhere."""
    root = store_local_root(store_url)
    if root is None:
        return set(), None
    journal = read_store_journal(root)
    if journal is None:
        return set(), None
    blobs = journal.get("blobs") or {}
    return {k for k in keys if k in blobs}, journal.get("remote")
