"""Per-take telemetry: stage spans, rank counters, persisted traces.

The paper's core claims — overlapped DtoH and storage I/O, memory-budget
driven scheduling, write load spread across ranks — are only verifiable
if a running take can say *where* its wall-clock and budget went, per
rank. This module is that instrument:

- **Spans** — monotonic-clock intervals recorded around every pipeline
  stage (flatten, the G1 plan gather, prepare, staging, checksum
  passes, storage writes, budget waits, barriers/KV waits). Span
  capture is gated by the ``TPUSNAP_TELEMETRY`` knob (on by default;
  the disabled path is a single dict lookup + ``None`` check).
- **Counters** — atomic, ALWAYS-ON (knob-independent): retry attempts
  per classification, injected faults, staging-pool hits/misses, bytes
  written, dedup skips. Cheap enough for the hot path (one lock'd
  ``dict`` add).
- **Gauges** — high-water marks (scheduler budget in use, peak RSS
  delta sampled by :mod:`tpusnap.rss_profiler`).
- **I/O histograms** — always-on log2-bucketed latency × size
  histograms per ``(op, plugin class)`` at the storage-plugin boundary
  (:class:`LogHistogram`/:class:`IOStats`, fed by the registry's
  instrumentation wrapper): p50/p95/p99/max derivable from any
  cross-rank merge, recorded per rank and folded into the rollup —
  whole-op spans hide tail latency; these are where it lives.
- **Roofline probes** — opt-in (``TPUSNAP_PROBE=1``) in-take probe
  segments the write scheduler interleaves between I/O windows; their
  samples land here and the summary derives a drift-immune
  ``roofline_fraction`` (see :mod:`tpusnap.analyze`).
- **TakeTelemetry** — the per-take aggregate. One is installed
  process-globally for the duration of a take (background drain
  threads re-install it thread-locally via :func:`use`); module-level
  :func:`span`/:func:`incr`/:func:`event` record into it from any
  layer without threading a handle through every call.

Persistence: each rank serializes its trace to **Chrome trace-event
JSON** (load it in ``chrome://tracing`` / Perfetto) plus a compact
summary, stored inside the snapshot at
``.tpusnap/telemetry/rank_<k>.json`` — written after the rank's blob
writes drain and BEFORE the metadata commit, so the
metadata-written-last invariant holds (a trace file can be orphaned by
an abort; a committed snapshot missing its trace only means telemetry
was disabled or its best-effort write failed). Rank 0 additionally
folds a cross-rank rollup (per-stage p50/max, bytes written, retries,
budget high-water) into the take's metadata ``extras`` — surfaced by
``python -m tpusnap trace <path>``.

External collectors subscribe through :class:`MetricsSink`
(``register_metrics_sink``): per-span and per-counter callbacks plus
one take-summary callback. Sink exceptions are swallowed — telemetry
must never fail a take.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Generator, List, Optional, Tuple

from .knobs import is_telemetry_enabled

logger = logging.getLogger(__name__)

from .io_types import TELEMETRY_DIR  # canonical sidecar path (io_types)
from . import flight as _flight  # black-box event feed (span open/close)

# Wall-clock seam: timestamps only (started_at); ALL duration math in
# this file is monotonic — direct wall-clock CALLS are lint-forbidden
# here (tests/test_knob_docs.py enforces the invariant); only this bare
# reference is allowed.
_wall = time.time

# Summary of the most recent completed take in this process (set by
# end_take); benchmarks read this to embed the stage breakdown in their
# JSON without re-reading the snapshot.
LAST_TAKE_SUMMARY: Optional[Dict[str, Any]] = None

# Summary of the most recent completed restore in this process (set by
# Snapshot._restore_locked) — the restore-path counterpart benchmarks
# read for their restore stage_breakdown.
LAST_RESTORE_SUMMARY: Optional[Dict[str, Any]] = None


def telemetry_rank_path(rank: int) -> str:
    """Storage-relative path of one rank's persisted trace."""
    return f"{TELEMETRY_DIR}/rank_{rank}.json"


# --------------------------------------------------------------- sinks


class MetricsSink:
    """Subscriber interface for external collectors. Override any
    subset; default implementations are no-ops. Callbacks run inline on
    the recording thread and must be fast and non-raising (raises are
    swallowed, but the time is still yours)."""

    def on_span(self, name: str, duration_s: float, attrs: Dict[str, Any]) -> None:
        pass

    def on_counter(self, name: str, delta: int, value: int) -> None:
        pass

    def on_take_summary(self, summary: Dict[str, Any]) -> None:
        pass

    def on_restore_summary(self, summary: Dict[str, Any]) -> None:
        pass

    def on_slo_update(self, state: Dict[str, Any]) -> None:
        """Checkpoint-SLO state refresh (:mod:`tpusnap.slo`): RPO,
        data-at-risk, estimated RTO, commit interval — pushed at
        heartbeat cadence while a take runs and at every commit."""
        pass

    def on_tier_update(self, state: Dict[str, Any]) -> None:
        """Write-back tier status refresh (:mod:`tpusnap.tiering`):
        uploader state, upload lag bytes/seconds, degraded flag —
        pushed by the background drain on every state transition and
        blob completion."""
        pass


_sinks: Tuple[MetricsSink, ...] = ()
_sinks_lock = threading.Lock()
# (sink class name, callback name) pairs already warned about since the
# last take/restore began — a broken exporter logs ONE rate-limited
# WARNING per sink class per callback per take instead of being
# silently invisible (or spamming once per span).
_sink_warned: set = set()


def _reset_sink_warnings() -> None:
    with _sinks_lock:
        _sink_warned.clear()


def register_metrics_sink(sink: MetricsSink) -> None:
    global _sinks
    with _sinks_lock:
        _sinks = _sinks + (sink,)


def unregister_metrics_sink(sink: MetricsSink) -> None:
    global _sinks
    with _sinks_lock:
        _sinks = tuple(s for s in _sinks if s is not sink)


@contextmanager
def metrics_sink(sink: MetricsSink) -> Generator[MetricsSink, None, None]:
    """Scoped registration: ``with metrics_sink(MySink()) as s: ...``
    unregisters on exit even when the body raises — a failing test (or a
    short-lived collector) can no longer leak its sink into the
    process-global tuple."""
    register_metrics_sink(sink)
    try:
        yield sink
    finally:
        unregister_metrics_sink(sink)


def _notify(method: str, *args) -> None:
    for sink in _sinks:
        try:
            getattr(sink, method)(*args)
        except Exception:
            # Swallowed (telemetry never fails a take) but NOT silent: a
            # broken exporter is diagnosable from one WARNING naming the
            # sink class and callback, rate-limited to once per sink
            # class per callback per take.
            key = (type(sink).__name__, method)
            with _sinks_lock:
                first = key not in _sink_warned
                _sink_warned.add(key)
            if first:
                logger.warning(
                    "MetricsSink %s.%s raised; exception swallowed "
                    "(telemetry never fails a take) — further failures "
                    "from this sink/callback suppressed until the next "
                    "take",
                    key[0],
                    method,
                    exc_info=True,
                )


def notify_slo_update(state: Dict[str, Any]) -> None:
    """Fan one SLO state refresh out to every registered sink (the
    :mod:`tpusnap.slo` publisher's sink leg; same swallow/rate-limit
    contract as every other callback)."""
    _notify("on_slo_update", state)


def notify_tier_update(state: Dict[str, Any]) -> None:
    """Fan one write-back tier status refresh out to every registered
    sink (the :mod:`tpusnap.tiering` uploader's sink leg)."""
    _notify("on_tier_update", state)


# ---------------------------------------------------- global counters

# Process-lifetime counters, knob-independent: retry/fault/pool events
# are recorded here even outside a take, so tests and sinks can observe
# them without a snapshot in flight.
_global_counters: Dict[str, int] = {}
_counters_lock = threading.Lock()


def counter_value(name: str) -> int:
    with _counters_lock:
        return _global_counters.get(name, 0)


def global_counters_snapshot() -> Dict[str, int]:
    """Copy of the process-lifetime counters — the monotonic domain the
    Prometheus textfile sink exports (take-local counters reset per
    take and would break ``rate()``)."""
    with _counters_lock:
        return dict(_global_counters)


def reset_global_counters() -> None:
    """Test aid; production code never resets."""
    with _counters_lock:
        _global_counters.clear()


# ----------------------------------------------------- I/O histograms

# Bucket key for non-positive observations (a zero-latency op, an empty
# write): kept separate so quantile math never takes log2(0).
_ZERO_BUCKET = -1074  # below the smallest positive float64 exponent


class LogHistogram:
    """log2-bucketed histogram: observation ``v`` lands in bucket
    ``floor(log2 v)`` (i.e. the half-open interval ``[2^k, 2^(k+1))``),
    so the whole dynamic range of I/O latencies (microseconds to
    minutes) and sizes (bytes to gigabytes) fits in a few dozen integer
    buckets with bounded relative error. Tracks exact count/sum/min/max
    alongside, so ``quantile(1.0)`` is the true max and single-sample
    histograms are exact. Mergeable across ranks (bucket-count sums) —
    the property the cross-rank rollup and the trend gates rely on;
    p50/p95/p99 are derivable from any merge. NOT thread-safe on its
    own; callers hold their registry lock."""

    __slots__ = ("buckets", "count", "total", "vmin", "vmax")

    def __init__(self) -> None:
        self.buckets: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.vmin = math.inf
        self.vmax = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        if v > 0.0:
            # floor(log2 v) == frexp exponent - 1 (v = m * 2^e, m in
            # [0.5, 1)) — no log call, exact at bucket boundaries.
            k = math.frexp(v)[1] - 1
        else:
            v = 0.0
            k = _ZERO_BUCKET
        self.buckets[k] = self.buckets.get(k, 0) + 1
        self.count += 1
        self.total += v
        if v < self.vmin:
            self.vmin = v
        if v > self.vmax:
            self.vmax = v

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1]: geometrically interpolated
        within the bucket holding the q-th observation (rank position
        maps to an exponent fraction, so the estimate moves CONTINUOUSLY
        as mass shifts across a bucket boundary — a gated p99 must not
        jump 2x when the true latency drifts 10% across a power of
        two), clamped into the exact observed [min, max]. Exact for max
        and for single-sample histograms (a lone sample interpolates to
        its bucket's upper edge, which the clamp pins to the sample)."""
        if self.count == 0:
            return None
        if q >= 1.0:
            return self.vmax
        target = q * self.count
        cum = 0
        for k in sorted(self.buckets):
            n = self.buckets[k]
            cum += n
            if cum >= target:
                if k == _ZERO_BUCKET:
                    return 0.0
                frac = (target - (cum - n)) / n
                est = math.ldexp(1.0, k) * (2.0 ** frac)
                return max(min(est, self.vmax), self.vmin)
        return self.vmax

    def merge(self, other: "LogHistogram") -> None:
        for k, n in other.buckets.items():
            self.buckets[k] = self.buckets.get(k, 0) + n
        self.count += other.count
        self.total += other.total
        self.vmin = min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "total": self.total,
            "min": self.vmin if self.count else None,
            "max": self.vmax,
            "buckets": {str(k): n for k, n in sorted(self.buckets.items())},
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "LogHistogram":
        h = cls()
        for k, n in (d.get("buckets") or {}).items():
            h.buckets[int(k)] = int(n)
        h.count = int(d.get("count", 0))
        h.total = float(d.get("total", 0.0))
        h.vmin = float(d["min"]) if d.get("min") is not None else math.inf
        h.vmax = float(d.get("max", 0.0))
        return h


class IOStats:
    """Latency × size histogram pair for one (op, plugin-class) key at
    the storage-plugin boundary: per-op latency in seconds and payload
    size in bytes, each log2-bucketed, plus the derived quantiles the
    doctor CLI and the regression gates read."""

    __slots__ = ("latency", "size")

    def __init__(self) -> None:
        self.latency = LogHistogram()
        self.size = LogHistogram()

    def observe(self, seconds: float, nbytes: int) -> None:
        self.latency.observe(seconds)
        self.size.observe(nbytes)

    def merge_dict(self, d: Dict[str, Any]) -> None:
        if "latency" in d:
            self.latency.merge(LogHistogram.from_dict(d["latency"]))
        if "size" in d:
            self.size.merge(LogHistogram.from_dict(d["size"]))

    def to_dict(self) -> Dict[str, Any]:
        lat = self.latency
        out: Dict[str, Any] = {
            "count": lat.count,
            "total_s": round(lat.total, 6),
            "bytes_total": int(self.size.total),
            "latency": lat.to_dict(),
            "size": self.size.to_dict(),
        }
        for name, q in (("p50_s", 0.5), ("p95_s", 0.95), ("p99_s", 0.99)):
            v = lat.quantile(q)
            out[name] = round(v, 9) if v is not None else None
        out["max_s"] = round(lat.vmax, 9) if lat.count else None
        return out


# Process-lifetime I/O histograms, knob-independent like the counters:
# one IOStats per "<op>.<PluginClass>" key ("write.FSStoragePlugin").
# The Prometheus sink exports quantiles from THIS registry (stable
# across takes); per-take copies ride TakeTelemetry and the rollup.
_global_io_stats: Dict[str, IOStats] = {}
_io_stats_lock = threading.Lock()


def observe_io(
    op: str,
    plugin: str,
    seconds: float,
    nbytes: int,
    rec: Optional["TakeTelemetry"] = None,
) -> None:
    """Record one storage-plugin op (write/read/delete/list) into the
    process-global histograms AND the in-flight take/restore recorder
    (the ambient one, or an explicit ``rec``). Always-on: the cost is
    two dict updates per multi-MB I/O op."""
    key = f"{op}.{plugin}"
    with _io_stats_lock:
        st = _global_io_stats.get(key)
        if st is None:
            st = _global_io_stats[key] = IOStats()
        st.observe(seconds, nbytes)
    rec = rec if rec is not None else current()
    if rec is not None:
        rec.observe_io(key, seconds, nbytes)


def global_io_histograms_snapshot() -> Dict[str, Dict[str, Any]]:
    """Serialized copy of the process-lifetime I/O histograms (the
    monotonic domain the Prometheus sink exports quantiles from)."""
    with _io_stats_lock:
        return {k: v.to_dict() for k, v in sorted(_global_io_stats.items())}


def reset_global_io_histograms() -> None:
    """Test aid; production code never resets."""
    with _io_stats_lock:
        _global_io_stats.clear()


def probe_aggregate(samples: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold in-take roofline probe samples into the compact aggregate
    the summary/rollup/history carry: sample count, p50 of the per-probe
    write/read ceilings, total probe bytes and elapsed time."""

    def _p50(key: str) -> Optional[float]:
        vals = sorted(s[key] for s in samples if s.get(key))
        return round(vals[len(vals) // 2], 4) if vals else None

    return {
        "probes": len(samples),
        "write_gbps_p50": _p50("write_gbps"),
        "read_gbps_p50": _p50("read_gbps"),
        "bytes": int(sum(s.get("bytes", 0) for s in samples)),
        "elapsed_s": round(sum(s.get("elapsed_s", 0.0) for s in samples), 6),
    }


def merge_io_histograms(
    dicts: List[Dict[str, Dict[str, Any]]],
) -> Dict[str, Dict[str, Any]]:
    """Merge serialized per-rank ``io_histograms`` maps (bucket-count
    sums per key) — the cross-rank rollup's histogram fold. Quantiles
    are recomputed from the merged buckets."""
    merged: Dict[str, IOStats] = {}
    for d in dicts:
        for key, st_dict in (d or {}).items():
            st = merged.get(key)
            if st is None:
                st = merged[key] = IOStats()
            try:
                st.merge_dict(st_dict)
            except Exception:
                continue
    return {k: v.to_dict() for k, v in sorted(merged.items())}


# ------------------------------------------------------- TakeTelemetry


class TakeTelemetry:
    """Thread-safe per-take aggregate of spans, counters and gauges.

    ``enabled`` gates SPAN capture only (the TPUSNAP_TELEMETRY knob,
    sampled once at construction so a take is internally consistent);
    counters and gauges are always recorded. Timestamps are offsets
    from the take's start on the monotonic clock."""

    def __init__(self, rank: int, enabled: Optional[bool] = None) -> None:
        self.rank = rank
        self.enabled = is_telemetry_enabled() if enabled is None else enabled
        self.t0 = time.monotonic()
        self.wall0 = _wall()
        # Identity/outcome context merged into summary(): the take path
        # sets kind/take_id/path/world_size once they're agreed, and
        # completed=True strictly after the commit — the history store
        # and export sinks key off these (an aborted take must not
        # become a throughput trend point).
        self.meta: Dict[str, Any] = {}
        self._lock = threading.Lock()
        # (name, start_s, dur_s, thread_name, is_phase, attrs)
        self._spans: List[Tuple[str, float, float, str, bool, Dict[str, Any]]] = []
        # (name, ts_s, thread_name, attrs) — instant events (faults, retries)
        self._events: List[Tuple[str, float, str, Dict[str, Any]]] = []
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        # Per-take I/O histograms ("<op>.<PluginClass>" → IOStats) and
        # in-take roofline probe samples — always-on like the counters.
        self._io_hist: Dict[str, IOStats] = {}
        self._probe_samples: List[Dict[str, Any]] = []
        self._finalized_wall_s: Optional[float] = None
        # Live state for the heartbeat/watchdog (tpusnap.progress):
        # in-flight named ops keyed by an opaque token (an op may span
        # awaits, so a per-thread stack would mis-pop under the event
        # loop's interleaving), plus the most recently COMPLETED phase.
        self._inflight: Dict[object, Tuple[str, str]] = {}
        self._last_phase: Optional[str] = None
        self._rss_sampler = None
        if self.enabled:
            try:
                from .rss_profiler import RSSSampler

                self._rss_sampler = RSSSampler(interval_sec=0.1)
                self._rss_sampler.start()
            except Exception:
                self._rss_sampler = None

    # --- recording ------------------------------------------------------

    def now(self) -> float:
        return time.monotonic() - self.t0

    def record_span(
        self,
        name: str,
        start_s: float,
        dur_s: float,
        phase: bool = False,
        **attrs: Any,
    ) -> None:
        if not self.enabled:
            return
        thread = threading.current_thread().name
        with self._lock:
            self._spans.append((name, start_s, dur_s, thread, phase, attrs))
        _notify("on_span", name, dur_s, attrs)

    @contextmanager
    def span(
        self, name: str, phase: bool = False, **attrs: Any
    ) -> Generator[None, None, None]:
        if not self.enabled:
            yield
            return
        start = self.now()
        token = self.op_enter(name)
        try:
            yield
        finally:
            self.op_exit(token)
            self.record_span(name, start, self.now() - start, phase=phase, **attrs)

    # --- live state (heartbeat/watchdog feed) ---------------------------

    def op_enter(self, name: str) -> Optional[object]:
        """Mark a named op as in flight; returns the token to pass back
        to :meth:`op_exit`. No-op (None) when span capture is off."""
        if not self.enabled:
            return None
        token = object()
        thread = threading.current_thread().name
        with self._lock:
            self._inflight[token] = (thread, name)
        # Flight-recorder feed (span OPEN): an op that began but never
        # ended is exactly what the post-mortem timeline must show.
        _flight.record("op_begin", op=name)
        return token

    def op_exit(self, token: Optional[object]) -> None:
        if token is None:
            return
        with self._lock:
            entry = self._inflight.pop(token, None)
        if entry is not None:
            _flight.record("op_end", op=entry[1])

    @contextmanager
    def op(self, name: str) -> Generator[None, None, None]:
        """In-flight tracking only (no span record) — for call sites
        that record their span manually but should still be visible to
        the stall watchdog while blocked."""
        token = self.op_enter(name)
        try:
            yield
        finally:
            self.op_exit(token)

    def note_phase(self, name: str) -> None:
        """Record ``name`` as the most recently completed phase (called
        by :class:`PhaseMarker`); read by the heartbeat publisher."""
        self._last_phase = name
        _flight.record("phase", op=name)

    def live_snapshot(self) -> Dict[str, Any]:
        """One consistent snapshot of the recorder's observable state
        for the progress pump: last completed phase, in-flight ops in
        start order (oldest first), counters, and a monotonically
        growing mark count (spans + events) whose advance IS forward
        progress."""
        with self._lock:
            ops = list(self._inflight.values())
            counters = dict(self._counters)
            marks = len(self._spans) + len(self._events)
            probe_gbps = (
                self._probe_samples[-1].get("write_gbps")
                if self._probe_samples
                else None
            )
        out = {
            "phase": self._last_phase,
            "ops": ops,
            "counters": counters,
            "marks": marks,
        }
        if probe_gbps:
            # Latest in-take probe ceiling: lets the heartbeat/watch
            # table express live MB/s as a fraction of the achievable.
            out["probe_write_gbps"] = round(probe_gbps, 3)
        return out

    def event(self, name: str, **attrs: Any) -> None:
        if not self.enabled:
            return
        thread = threading.current_thread().name
        with self._lock:
            self._events.append((name, self.now(), thread, attrs))

    def incr(self, name: str, n: int = 1) -> None:
        # No sink notification here: the module-level incr() notifies
        # with the PROCESS-GLOBAL cumulative value, so sinks see one
        # consistent monotonic domain instead of take-local resets.
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge_max(self, name: str, value: float) -> None:
        with self._lock:
            if value > self._gauges.get(name, float("-inf")):
                self._gauges[name] = value

    def observe_io(self, key: str, seconds: float, nbytes: int) -> None:
        """Take-local leg of :func:`observe_io` (always-on)."""
        with self._lock:
            st = self._io_hist.get(key)
            if st is None:
                st = self._io_hist[key] = IOStats()
            st.observe(seconds, nbytes)

    def add_probe_sample(self, sample: Dict[str, Any]) -> None:
        """Record one in-take roofline probe result (scheduler's probe
        runner): ``write_gbps``/``read_gbps``/``bytes``/``elapsed_s``."""
        with self._lock:
            self._probe_samples.append(dict(sample))

    # --- finalization ---------------------------------------------------

    def finalize(self) -> None:
        """Freeze the take wall-clock and stop the RSS sampler.
        Idempotent; spans recorded after this still reach sinks but are
        not part of the persisted trace's coverage window."""
        if self._finalized_wall_s is not None:
            return
        self._finalized_wall_s = self.now()
        if self._rss_sampler is not None:
            try:
                self._rss_sampler.stop()
                self.gauge_max(
                    "peak_rss_delta_bytes", float(self._rss_sampler.peak_delta)
                )
            except Exception:
                pass
            self._rss_sampler = None

    @property
    def take_wall_s(self) -> float:
        return (
            self._finalized_wall_s
            if self._finalized_wall_s is not None
            else self.now()
        )

    # --- serialization --------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """Compact aggregate: per-span-name {count, total_s, p50_s,
        max_s}, phase list (for wall-clock coverage), counters, gauges."""
        with self._lock:
            spans = list(self._spans)
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            events = list(self._events)
            io_hist = {k: v.to_dict() for k, v in sorted(self._io_hist.items())}
            probes = [dict(s) for s in self._probe_samples]
        by_name: Dict[str, List[float]] = {}
        phase_total: Dict[str, float] = {}
        for name, _start, dur, _thread, phase, _attrs in spans:
            by_name.setdefault(name, []).append(dur)
            if phase:
                phase_total[name] = phase_total.get(name, 0.0) + dur
        stages = {}
        for name, durs in sorted(by_name.items()):
            durs_sorted = sorted(durs)
            stages[name] = {
                "count": len(durs),
                "total_s": round(sum(durs), 6),
                "p50_s": round(durs_sorted[len(durs_sorted) // 2], 6),
                "max_s": round(durs_sorted[-1], 6),
            }
        take_wall = self.take_wall_s
        phase_sum = sum(phase_total.values())
        out = {
            **self.meta,
            "rank": self.rank,
            "enabled": self.enabled,
            "started_at": self.wall0,
            "take_wall_s": round(take_wall, 6),
            "phases": {k: round(v, 6) for k, v in phase_total.items()},
            "phase_coverage": (
                round(min(phase_sum / take_wall, 1.0), 4) if take_wall > 0 else 0.0
            ),
            "stages": stages,
            "counters": counters,
            "gauges": gauges,
            "events": len(events),
        }
        if io_hist:
            out["io_histograms"] = io_hist
        if probes:
            out["probe"] = probe_aggregate(probes)
            # Drift-immune roofline fraction: the operation's payload
            # throughput over its NON-PROBE wall-clock, against the
            # ceiling the interleaved probes measured through the same
            # engine moments apart — no separate roofline session whose
            # disk window the take never shared. Takes judge the write
            # leg; restores judge the read leg.
            adj_wall = max(take_wall - out["probe"].get("elapsed_s", 0.0), 1e-9)
            if self.meta.get("kind") == "restore":
                ceiling = out["probe"].get("read_gbps_p50")
                payload = counters.get("storage.bytes_read", 0)
                if ceiling and payload:
                    out["restore_roofline_fraction"] = round(
                        (payload / adj_wall / 1e9) / ceiling, 4
                    )
            else:
                ceiling = out["probe"].get("write_gbps_p50")
                payload = counters.get("storage.bytes_written", 0)
                if ceiling and payload:
                    out["roofline_fraction"] = round(
                        (payload / adj_wall / 1e9) / ceiling, 4
                    )
        return out

    def chrome_trace_events(self) -> List[Dict[str, Any]]:
        """Chrome trace-event list: complete ("X") events for spans,
        instant ("i") events for faults/retries, ts/dur in microseconds,
        pid = rank, tid = recording thread name."""
        with self._lock:
            spans = list(self._spans)
            events = list(self._events)
        out: List[Dict[str, Any]] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self.rank,
                "tid": 0,
                "args": {"name": f"tpusnap rank {self.rank}"},
            }
        ]
        for name, start, dur, thread, phase, attrs in spans:
            ev: Dict[str, Any] = {
                "name": name,
                "ph": "X",
                "cat": "phase" if phase else "op",
                "ts": round(start * 1e6, 1),
                "dur": round(dur * 1e6, 1),
                "pid": self.rank,
                "tid": thread,
            }
            if attrs:
                ev["args"] = attrs
            out.append(ev)
        for name, ts, thread, attrs in events:
            ev = {
                "name": name,
                "ph": "i",
                "cat": "event",
                "s": "p",
                "ts": round(ts * 1e6, 1),
                "pid": self.rank,
                "tid": thread,
            }
            if attrs:
                ev["args"] = attrs
            out.append(ev)
        return out

    def to_json(self) -> str:
        return json.dumps(
            {
                "rank": self.rank,
                "summary": self.summary(),
                "traceEvents": self.chrome_trace_events(),
            },
            sort_keys=False,
        )


# --------------------------------------------- ambient current recorder

# The take installs its recorder process-globally; background threads
# (async commit drain) overlay it thread-locally via use() so a NEWER
# take's global install cannot steal their spans.
_global_current: Optional[TakeTelemetry] = None
_tls = threading.local()


def current() -> Optional[TakeTelemetry]:
    rec = getattr(_tls, "current", None)
    return rec if rec is not None else _global_current


def _job_id() -> str:
    """The job identity every summary carries (``meta["job_id"]`` —
    concurrent jobs sharing a telemetry/metrics dir stay attributable).
    Best-effort: identity must never fail a take."""
    try:
        from .knobs import get_job_id

        return get_job_id()
    except Exception:
        return "job"


def _begin_common() -> None:
    # Fresh take/restore: re-arm the one-warning-per-sink budget and
    # reconcile env-driven export sinks (TPUSNAP_METRICS_EXPORT may
    # have changed since the last take; best-effort, never fatal).
    _reset_sink_warnings()
    try:
        from .metrics_export import install_env_sinks

        install_env_sinks()
    except Exception:
        logger.warning(
            "Failed to install metrics export sinks (non-fatal)",
            exc_info=True,
        )


def begin_take(rank: int) -> TakeTelemetry:
    """Create a take recorder and install it as the process-global
    current. Pipeline layers then record through the module-level
    span()/incr()/event() without threading a handle."""
    global _global_current
    _begin_common()
    # Fresh black box per take: the flight sidecar is a per-take
    # artifact, and a crashed take's verdict must not count previous
    # takes' stalls/evictions (restores do NOT reset — they overlay).
    try:
        _flight.recorder().mark_take_start()
    except Exception:
        logger.debug("flight ring reset failed", exc_info=True)
    rec = TakeTelemetry(rank)
    rec.meta["kind"] = "take"
    rec.meta["job_id"] = _job_id()
    _global_current = rec
    return rec


def begin_restore(rank: int) -> TakeTelemetry:
    """Create a restore recorder (NOT installed globally — restores
    overlay it thread-locally via :func:`use` so an in-flight take's
    global recorder is never disturbed)."""
    _begin_common()
    rec = TakeTelemetry(rank)
    rec.meta["kind"] = "restore"
    rec.meta["job_id"] = _job_id()
    return rec


def release_global(rec: TakeTelemetry) -> None:
    """Uninstall ``rec`` as the process-global current (no-op when a
    newer take already replaced it). async_take calls this when control
    returns to training — the background drain keeps recording through
    captured references and a thread-local :func:`use` overlay."""
    global _global_current
    if _global_current is rec:
        _global_current = None


def end_take(rec: TakeTelemetry) -> None:
    """Finalize + uninstall (only if still installed) and publish the
    summary: LAST_TAKE_SUMMARY, the sinks' on_take_summary, and — for
    COMPLETED takes only — one cross-run history event."""
    global LAST_TAKE_SUMMARY
    # The auto-tuner's overlay is scoped to the take that applied it
    # (end_take is the chokepoint every take path — sync, async,
    # aborted — funnels through); knob reads afterwards see the plain
    # environment again. The summary below still carries meta["tuned"].
    try:
        from .knobs import clear_tuned_plan

        clear_tuned_plan()
    except Exception:
        pass
    rec.finalize()
    release_global(rec)
    summary = rec.summary()
    LAST_TAKE_SUMMARY = summary
    _notify("on_take_summary", summary)
    try:
        from .history import record_summary

        record_summary("take", summary)
    except Exception:
        logger.debug("history record failed", exc_info=True)


def publish_restore_summary(summary: Dict[str, Any]) -> None:
    """Restore-side counterpart of :func:`end_take`'s publication step:
    LAST_RESTORE_SUMMARY, the sinks' on_restore_summary, and — for
    completed restores — one history event."""
    global LAST_RESTORE_SUMMARY
    LAST_RESTORE_SUMMARY = summary
    _notify("on_restore_summary", summary)
    try:
        from .history import record_summary

        record_summary("restore", summary)
    except Exception:
        logger.debug("history record failed", exc_info=True)


@contextmanager
def use(rec: Optional[TakeTelemetry]) -> Generator[None, None, None]:
    """Thread-local overlay: make ``rec`` the current recorder on THIS
    thread (async commit / background restore threads)."""
    prev = getattr(_tls, "current", None)
    _tls.current = rec
    try:
        yield
    finally:
        _tls.current = prev


@contextmanager
def span(name: str, phase: bool = False, **attrs: Any) -> Generator[None, None, None]:
    """Record a span into the ambient recorder; no-op (one lookup) when
    no take is in flight or span capture is knob-disabled."""
    rec = current()
    if rec is None or not rec.enabled:
        yield
        return
    with rec.span(name, phase=phase, **attrs):
        yield


def event(name: str, **attrs: Any) -> None:
    rec = current()
    if rec is not None:
        rec.event(name, **attrs)


def incr(name: str, n: int = 1, rec: Optional[TakeTelemetry] = None) -> None:
    """Always-on counter: bumps the process-global counter AND the
    in-flight take's (the ambient one, or an explicit ``rec`` captured
    by code that outlives the take's global install). Sinks are
    notified with the process-global cumulative value — one monotonic
    domain regardless of take boundaries."""
    with _counters_lock:
        global_value = _global_counters.get(name, 0) + n
        _global_counters[name] = global_value
    rec = rec if rec is not None else current()
    if rec is not None:
        rec.incr(name, n)
    _notify("on_counter", name, n, global_value)


def gauge_max(name: str, value: float) -> None:
    rec = current()
    if rec is not None:
        rec.gauge_max(name, value)


class PhaseMarker:
    """Sequential PHASE-span recorder for a linear pipeline: each call
    records a phase span from the previous mark (or construction) to
    now, so the recorded phases tile the timeline with no gaps — which
    is what makes the trace CLI's wall-clock coverage meaningful."""

    def __init__(
        self, rec: Optional[TakeTelemetry] = None, from_start: bool = False
    ) -> None:
        self.rec = rec if rec is not None else current()
        # from_start anchors the first phase at the recorder's t0, so
        # recorder-construction overhead (RSS sampler thread spawn)
        # cannot open a coverage hole before the first phase.
        self.last = (
            self.rec.now()
            if self.rec is not None and self.rec.enabled and not from_start
            else 0.0
        )

    def __call__(self, name: str, **attrs: Any) -> None:
        if self.rec is None or not self.rec.enabled:
            return
        now = self.rec.now()
        self.rec.record_span(name, self.last, now - self.last, phase=True, **attrs)
        self.rec.note_phase(name)
        self.last = now

def phase_marker(from_start: bool = False) -> PhaseMarker:
    return PhaseMarker(from_start=from_start)


# -------------------------------------------------------------- rollup


def rollup_summaries(summaries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Cross-rank rollup rank 0 folds into the metadata extras: per
    stage, the p50/max over ranks of each rank's TOTAL time in that
    stage — WITH the straggler's rank id (``max_rank``); summed
    counters; max gauges; slowest-rank wall-clock; and ``phase_skew``,
    the per-phase straggler attribution (slowest rank + max/p50 skew)
    the stall watchdog's post-mortem reads."""
    summaries = [s for s in summaries if s]
    if not summaries:
        return {}
    # (total_s, rank) pairs so the straggler keeps its rank id.
    stage_totals: Dict[str, List[Tuple[float, int]]] = {}
    phase_totals: Dict[str, List[Tuple[float, int]]] = {}
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    for i, s in enumerate(summaries):
        rank = s.get("rank", i)
        for name, agg in (s.get("stages") or {}).items():
            stage_totals.setdefault(name, []).append(
                (agg.get("total_s", 0.0), rank)
            )
        for name, v in (s.get("phases") or {}).items():
            phase_totals.setdefault(name, []).append((v, rank))
        for name, v in (s.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in (s.get("gauges") or {}).items():
            if v > gauges.get(name, float("-inf")):
                gauges[name] = v
    stages = {}
    for name, totals in sorted(stage_totals.items()):
        ts = sorted(totals)
        stages[name] = {
            "ranks": len(ts),
            "p50_s": round(ts[len(ts) // 2][0], 6),
            "max_s": round(ts[-1][0], 6),
            "max_rank": ts[-1][1],
        }
    phase_skew = {}
    for name, totals in sorted(phase_totals.items()):
        ts = sorted(totals)
        p50, mx = ts[len(ts) // 2][0], ts[-1][0]
        phase_skew[name] = {
            "p50_s": round(p50, 6),
            "max_s": round(mx, 6),
            "max_rank": ts[-1][1],
            "skew": round(mx / p50, 3) if p50 > 0 else None,
        }
    out = {
        "phase_skew": phase_skew,
        "ranks": len(summaries),
        "take_wall_s": round(max(s.get("take_wall_s", 0.0) for s in summaries), 6),
        "phase_coverage_min": round(
            min(s.get("phase_coverage", 0.0) for s in summaries), 4
        ),
        "stages": stages,
        "counters": counters,
        "gauges": gauges,
        "bytes_written": counters.get("storage.bytes_written", 0),
        "retry_attempts": counters.get("retry.attempts", 0),
        "budget_high_water_bytes": gauges.get("scheduler.budget_used_bytes"),
        "peak_rss_delta_bytes": gauges.get("peak_rss_delta_bytes"),
    }
    # Cross-rank I/O histogram merge: bucket-count sums per
    # "<op>.<PluginClass>" key, quantiles recomputed from the merge —
    # a rank's p99 outlier survives the fold instead of averaging away.
    io_merged = merge_io_histograms(
        [s.get("io_histograms") or {} for s in summaries]
    )
    if io_merged:
        out["io_histograms"] = io_merged
    # Roofline probes: the p50 fraction across ranks (the fleet
    # headline) plus the worst rank's, with its id (a single rank's slow
    # disk is a straggler story, not a fleet story). Takes fold
    # ``roofline_fraction`` (write lane), restores fold
    # ``restore_roofline_fraction`` (read lane) — same shape.
    any_fracs = False
    for field in ("roofline_fraction", "restore_roofline_fraction"):
        fracs = sorted(
            (s[field], s.get("rank", i))
            for i, s in enumerate(summaries)
            if isinstance(s.get(field), (int, float))
        )
        if not fracs:
            continue
        any_fracs = True
        out[field] = round(fracs[len(fracs) // 2][0], 4)
        out[f"{field}_min"] = round(fracs[0][0], 4)
        out[f"{field}_min_rank"] = fracs[0][1]
    if any_fracs:
        probe_ranks = [s["probe"] for s in summaries if s.get("probe")]
        if probe_ranks:
            out["probe"] = {
                "probes": sum(p.get("probes", 0) for p in probe_ranks)
            }
            for lane in ("write_gbps_p50", "read_gbps_p50"):
                ceilings = sorted(
                    p[lane] for p in probe_ranks if p.get(lane)
                )
                out["probe"][lane] = (
                    round(ceilings[len(ceilings) // 2], 4) if ceilings else None
                )
    return out
