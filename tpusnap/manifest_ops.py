"""Build a rank's view of a snapshot from the global manifest — the
elasticity core.

Counterpart of /root/reference/torchsnapshot/manifest_ops.py:24-216.
Global manifest keys are ``"<rank>/<logical_path>"``. A rank's view:

- its own subtree (keys under ``rank/``), with the prefix stripped
  (reference :87-94);
- replicated entries — stored on rank 0 only after dedup — re-exposed to
  every rank (reference :62-65);
- all ranks' ShardedEntry shards at the same logical path merged into one
  global entry (reference :97-115);
- a NEW rank (rank >= saved world_size) gets rank 0's manifest minus
  non-replicated, non-sharded leaf entries (reference :74-84) — containers
  survive so the tree structure inflates.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .manifest import (
    Entry,
    Manifest,
    ShardedEntry,
    SnapshotMetadata,
    is_container_entry,
    is_replicated,
)


def _split_rank_path(key: str) -> Tuple[int, str]:
    rank_str, _, logical_path = key.partition("/")
    return int(rank_str), logical_path


def get_manifest_for_rank(metadata: SnapshotMetadata, rank: int) -> Manifest:
    """Return ``{logical_path: entry}`` — everything ``rank`` can restore."""
    rank_to_manifest: Dict[int, Manifest] = {}
    for key, entry in metadata.manifest.items():
        r, logical_path = _split_rank_path(key)
        rank_to_manifest.setdefault(r, {})[logical_path] = entry

    # Merge sharded entries across ranks: all shards of a logical path
    # combine into one global ShardedEntry.
    merged_sharded: Dict[str, ShardedEntry] = {}
    for r in sorted(rank_to_manifest):
        for logical_path, entry in rank_to_manifest[r].items():
            if isinstance(entry, ShardedEntry):
                if logical_path not in merged_sharded:
                    merged_sharded[logical_path] = ShardedEntry(
                        shards=list(entry.shards),
                        dtype=entry.dtype,
                        shape=entry.shape,
                    )
                else:
                    merged_sharded[logical_path].shards.extend(entry.shards)

    if rank in rank_to_manifest:
        local = dict(rank_to_manifest[rank])
    else:
        # New rank joining after an upscale: start from rank 0's view,
        # keeping only what is restorable everywhere.
        local = {
            p: e
            for p, e in rank_to_manifest.get(0, {}).items()
            if is_container_entry(e)
            or is_replicated(e)
            or isinstance(e, ShardedEntry)
        }

    # Replicated entries live only in rank 0's tree after consolidation;
    # re-expose them (and their ancestor containers) to every rank.
    for r, manifest in rank_to_manifest.items():
        for logical_path, entry in manifest.items():
            if is_replicated(entry) and logical_path not in local:
                local[logical_path] = entry
                _ensure_ancestors(local, manifest, logical_path)

    for logical_path in list(local):
        if isinstance(local[logical_path], ShardedEntry):
            local[logical_path] = merged_sharded[logical_path]

    return local


def _ensure_ancestors(local: Manifest, source: Manifest, logical_path: str) -> None:
    parts = logical_path.split("/")
    for i in range(1, len(parts)):
        ancestor = "/".join(parts[:i])
        if ancestor not in local and ancestor in source:
            local[ancestor] = source[ancestor]


def get_available_entries(metadata: SnapshotMetadata, rank: int) -> Manifest:
    """Public helper mirroring the reference's Snapshot.get_manifest
    surface: the per-rank restorable view."""
    return get_manifest_for_rank(metadata, rank)


def delta_chain_fields(metadata: SnapshotMetadata):
    """The validated delta-chain fields of a committed snapshot
    (``extras["delta"]``: stream id, ``seq``, ``parent`` member name) —
    None for non-stream snapshots. The one place chain membership is
    decoded, shared by info/fsck/retention and ``tpusnap.delta``."""
    d = (getattr(metadata, "extras", None) or {}).get("delta")
    if isinstance(d, dict) and isinstance(d.get("seq"), int):
        return d
    return None


def external_reference_depth(manifest: Manifest) -> int:
    """The maximum number of parent (``..``) hops any blob location in
    ``manifest`` takes. Incremental writers collapse chained references
    to the member physically holding the bytes, so for a well-formed
    delta-chain member this is ≤ 1 REGARDLESS of chain depth — the
    invariant that keeps head lookups flat (restore/read_object resolve
    every location in one hop, never chasing intermediates). Exposed so
    tests and tooling can assert it instead of assuming it."""
    from .manifest import (
        ChunkedTensorEntry,
        ObjectEntry,
        ShardedEntry,
        TensorEntry,
    )

    def tensors(entry: Entry):
        if isinstance(entry, (TensorEntry, ObjectEntry)):
            yield entry
        elif isinstance(entry, ChunkedTensorEntry):
            for c in entry.chunks:
                yield c.tensor
        elif isinstance(entry, ShardedEntry):
            for s in entry.shards:
                yield s.tensor

    depth = 0
    for entry in manifest.values():
        for t in tensors(entry):
            segs = t.location.split("/")
            i = 0
            while i < len(segs) and segs[i] == "..":
                i += 1
            depth = max(depth, i)
    return depth


def handle_sharded_elasticity(
    local_manifest: Manifest,
    target_flattened: Dict[str, object],
) -> None:
    """Reconcile sharded entries with the restoring rank's targets
    (reference handle_sharded_tensor_elasticity, manifest_ops.py:118-176).

    In JAX the heavy lifting is already done: merged ShardedEntry overlap
    resharding covers any target NamedSharding, including ranks that did
    not participate in saving. What remains is dropping sharded entries
    the restoring rank has no target for (it holds no addressable piece),
    so no read I/O is issued for them.
    """
    for logical_path in list(local_manifest):
        entry = local_manifest[logical_path]
        if isinstance(entry, ShardedEntry) and logical_path not in target_flattened:
            del local_manifest[logical_path]
