"""Sharded embedding-table collection — the torchrec/DMP model family.

The reference validates its checkpoint machinery against torchrec's
DistributedModelParallel embedding tables: row-wise / column-wise /
table-wise sharding, UVM (host-memory-backed) tables, and fused row-wise
Adagrad optimizer state (/root/reference/tests/gpu_tests/test_torchrec.py:181-304,
/root/reference/torchsnapshot/uvm_tensor.py). This module is the TPU-native
analog, designed mesh-first rather than wrapper-first:

- **Every torchrec sharding type is a ``NamedSharding`` layout** over the
  ("data", "fsdp", "tensor") mesh — the model axes ("fsdp", "tensor")
  shard tables, "data" shards the lookup batch:
    * ``row``   — vocab dim sharded: ``P(("fsdp", "tensor"), None)``
                  (torchrec ROW_WISE / the FSDP-ish layout)
    * ``col``   — embedding dim sharded: ``P(None, ("fsdp", "tensor"))``
                  (torchrec COLUMN_WISE)
    * ``table`` — same-shape tables stacked ``[T, V, D]`` and the *table*
                  dim sharded: ``P(("fsdp", "tensor"), None, None)`` —
                  each device holds whole tables (torchrec TABLE_WISE,
                  expert-parallel-style placement)
    * ``replicated`` — ``P(None, None)`` on every device (DP)
- **UVM → host-offload memory kind**: tables flagged ``host_offload``
  live in ``pinned_host`` memory via tpusnap.host_offload — the stager
  then treats them as already-on-host (no DtoH DMA), exactly how the
  reference short-circuits UVM tensors
  (/root/reference/torchsnapshot/io_preparers/tensor.py:257-259).
- **Fused optimizer analog**: row-wise Adagrad keeps one f32 accumulator
  per embedding *row*, sharded identically to the vocab dim of its table,
  so optimizer state reshards with the weights on restore.

Lookups are ``jnp.take`` + masked pooling over fixed-size bags (static
shapes — XLA-friendly; ragged bags are expressed with -1 padding).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]

_SHARDINGS = ("row", "col", "table", "replicated")


@dataclasses.dataclass(frozen=True)
class TableConfig:
    """One embedding table: ``[num_embeddings, embedding_dim]``."""

    name: str
    num_embeddings: int
    embedding_dim: int
    sharding: str = "row"  # row | col | table | replicated
    host_offload: bool = False  # UVM analog: place in pinned_host memory
    pooling: str = "sum"  # sum | mean over each bag

    def __post_init__(self) -> None:
        if self.sharding not in _SHARDINGS:
            raise ValueError(f"unknown sharding {self.sharding!r}")
        if self.pooling not in ("sum", "mean"):
            raise ValueError(f"unknown pooling {self.pooling!r}")


class EmbeddingCollection:
    """Functional collection of sharded embedding tables.

    ``init`` → params pytree; ``apply(params, features)`` → pooled
    embeddings concatenated per-sample ``[batch, sum(dims)]``. Features:
    ``{table_name: int32 [batch, bag_size]}`` with -1 padding for ragged
    bags.

    Tables with ``sharding="table"`` and identical ``(V, D)`` are stacked
    into one ``[T, V, D]`` group leaf (key ``group_{V}x{D}``) whose
    leading dim is sharded — the NamedSharding-native expression of
    "whole tables placed across devices".
    """

    def __init__(self, tables: List[TableConfig]) -> None:
        names = [t.name for t in tables]
        if len(set(names)) != len(names):
            raise ValueError("duplicate table names")
        for n in names:
            if n.startswith("group_"):
                raise ValueError(
                    f"table name {n!r} uses the reserved 'group_' prefix "
                    "(table-wise groups are stored under group_{V}x{D} keys)"
                )
        self.tables = list(tables)
        self._groups: Dict[str, List[TableConfig]] = {}
        for t in tables:
            if t.sharding == "table":
                self._groups.setdefault(self._group_key(t), []).append(t)

    @staticmethod
    def _group_key(t: TableConfig) -> str:
        # no punctuation: the key becomes a snapshot logical-path segment
        return f"group_{t.num_embeddings}x{t.embedding_dim}"

    # ------------------------------------------------------------------ init

    def init(self, key: jax.Array) -> Params:
        params: Params = {"tables": {}, "opt": {}}
        keys = jax.random.split(key, len(self.tables) + len(self._groups))
        ki = iter(range(len(keys)))
        for t in self.tables:
            if t.sharding == "table":
                continue  # materialized with its group below
            w = jax.random.normal(
                keys[next(ki)], (t.num_embeddings, t.embedding_dim), jnp.float32
            ) * (t.embedding_dim**-0.5)
            params["tables"][t.name] = w
            params["opt"][t.name] = jnp.zeros((t.num_embeddings,), jnp.float32)
        for gkey, members in self._groups.items():
            V, D = members[0].num_embeddings, members[0].embedding_dim
            w = jax.random.normal(
                keys[next(ki)], (len(members), V, D), jnp.float32
            ) * (D**-0.5)
            params["tables"][gkey] = w
            params["opt"][gkey] = jnp.zeros((len(members), V), jnp.float32)
        return params

    # ------------------------------------------------------- sharding specs

    def param_specs(self) -> Params:
        """PartitionSpecs over a ("data", "fsdp", "tensor") mesh; optimizer
        accumulators shard with the vocab dim of their table so they
        reshard together on restore."""
        specs: Params = {"tables": {}, "opt": {}}
        model_axes = ("fsdp", "tensor")
        for t in self.tables:
            if t.sharding == "row":
                specs["tables"][t.name] = P(model_axes, None)
                specs["opt"][t.name] = P(model_axes)
            elif t.sharding == "col":
                specs["tables"][t.name] = P(None, model_axes)
                specs["opt"][t.name] = P(None)
            elif t.sharding == "replicated":
                specs["tables"][t.name] = P(None, None)
                specs["opt"][t.name] = P(None)
        for gkey in self._groups:
            specs["tables"][gkey] = P(model_axes, None, None)
            specs["opt"][gkey] = P(model_axes, None)
        return specs

    def shard_params(self, params: Params, mesh: Mesh) -> Params:
        """Place params per ``param_specs``; host-offloaded tables go to
        pinned_host memory with the same sharding (UVM analog)."""
        from ..host_offload import supports_host_offload, to_host_offload

        specs = self.param_specs()
        offloadable = supports_host_offload()
        offload_names = {
            (self._group_key(t) if t.sharding == "table" else t.name)
            for t in self.tables
            if t.host_offload
        }

        def place(path_name: str, x, spec):
            sharded = jax.device_put(x, NamedSharding(mesh, spec))
            if path_name in offload_names and offloadable:
                return to_host_offload(sharded)
            return sharded

        out: Params = {"tables": {}, "opt": {}}
        for section in ("tables", "opt"):
            for name, x in params[section].items():
                out[section][name] = place(name, x, specs[section][name])
        return out

    # --------------------------------------------------------------- forward

    def apply(self, params: Params, features: Dict[str, jax.Array]) -> jax.Array:
        """Pooled lookup per table, concatenated: ``[batch, sum(dims)]``."""
        pooled = []
        for t in self.tables:
            ids = features[t.name]  # [batch, bag] int32, -1 = padding
            table = self._table_weight(params, t)
            mask = (ids >= 0).astype(jnp.float32)[..., None]
            emb = jnp.take(table, jnp.maximum(ids, 0), axis=0) * mask
            agg = emb.sum(axis=1)
            if t.pooling == "mean":
                agg = agg / jnp.maximum(mask.sum(axis=1), 1.0)
            pooled.append(agg)
        return jnp.concatenate(pooled, axis=-1)

    def _table_weight(self, params: Params, t: TableConfig) -> jax.Array:
        if t.sharding != "table":
            return params["tables"][t.name]
        group = self._groups[self._group_key(t)]
        idx = next(i for i, m in enumerate(group) if m.name == t.name)
        return params["tables"][self._group_key(t)][idx]

    # ------------------------------------------------------------------ loss

    def loss(self, params: Params, features, targets) -> jax.Array:
        """Squared error of summed pooled embeddings against targets —
        enough to drive gradients through every table."""
        out = self.apply(params, features)
        return jnp.mean((out.sum(axis=-1) - targets) ** 2)


# ------------------------------------------------------------------ training


def make_embedding_train_step(model: EmbeddingCollection, mesh: Mesh,
                              learning_rate: float = 0.05):
    """Jitted SPMD step with row-wise Adagrad (the fused-optimizer analog):
    accumulator += mean(g²) per row; update = lr·g/√(acc+eps). State and
    params keep their table shardings throughout."""
    specs = model.param_specs()
    eps = 1e-8

    def to_named(tree):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s), tree,
            is_leaf=lambda s: isinstance(s, P),
        )

    def step(params, features, targets):
        loss, grads = jax.value_and_grad(model.loss)(params, features, targets)
        new_tables, new_acc = {}, {}
        for name, w in params["tables"].items():
            g = grads["tables"][name]
            row_ms = jnp.mean(g * g, axis=-1)  # [V] or [T, V]
            acc = params["opt"][name] + row_ms
            scale = jax.lax.rsqrt(acc + eps)[..., None]
            new_tables[name] = w - learning_rate * g * scale
            new_acc[name] = acc
        return {"tables": new_tables, "opt": new_acc}, loss

    feature_sharding = {
        t.name: NamedSharding(mesh, P("data", None)) for t in model.tables
    }
    return jax.jit(
        step,
        in_shardings=(
            to_named(specs),
            feature_sharding,
            NamedSharding(mesh, P("data")),
        ),
        out_shardings=(to_named(specs), NamedSharding(mesh, P())),
    )


def rand_features(
    model: EmbeddingCollection,
    mesh: Optional[Mesh],
    batch: int,
    bag: int,
    seed: int = 0,
) -> Tuple[Dict[str, jax.Array], jax.Array]:
    """Random (features, targets) with ~25% padding, data-sharded if a
    mesh is given."""
    rng = np.random.default_rng(seed)
    feats = {}
    for t in model.tables:
        ids = rng.integers(0, t.num_embeddings, (batch, bag)).astype(np.int32)
        ids[rng.random((batch, bag)) < 0.25] = -1
        arr = jnp.asarray(ids)
        if mesh is not None:
            arr = jax.device_put(arr, NamedSharding(mesh, P("data", None)))
        feats[t.name] = arr
    targets = jnp.asarray(rng.normal(size=(batch,)).astype(np.float32))
    if mesh is not None:
        targets = jax.device_put(targets, NamedSharding(mesh, P("data")))
    return feats, targets
