"""Flagship models: TPU-first reference workloads for tpusnap.

The reference library ships example *training scripts* (DDP / FSDP /
torchrec DLRM, SURVEY.md §2 #23-24) but no model code of its own. tpusnap
ships two model families: a flagship decoder transformer whose parameter
pytree exercises every sharding family the checkpoint preparers must
handle — DP (replicated), FSDP (param-sharded), TP (tensor-parallel),
SP/CP (ring attention over a sequence axis) and EP (expert-sharded MoE
weights) — and a sharded embedding-table collection (the torchrec DMP
analog: row/col/table-wise layouts, host-offloaded tables, row-wise
Adagrad state).
"""

from .embedding import (  # noqa: F401
    EmbeddingCollection,
    TableConfig,
    make_embedding_train_step,
)
from .transformer import (  # noqa: F401
    Transformer,
    TransformerConfig,
    make_mesh,
    make_train_step,
)

__all__ = [
    "EmbeddingCollection",
    "TableConfig",
    "Transformer",
    "TransformerConfig",
    "make_embedding_train_step",
    "make_mesh",
    "make_train_step",
]
