"""Flagship decoder transformer — pure JAX, mesh-sharded, scan-over-layers.

TPU-first design notes:
- **Stacked layers + ``lax.scan``**: every layer's params carry a leading
  ``n_layers`` dim and the forward scans over them — one layer compiles
  once, XLA pipelines the scan, and the stacked layout is the natural
  unit for pipeline-parallel stage splitting.
- **Sharding by ``PartitionSpec``**: ``param_specs()`` maps the parameter
  pytree to specs over a ``("data", "fsdp", "tensor")`` mesh. Matmul
  weights alternate ``("fsdp", "tensor")`` / ``("tensor", "fsdp")`` so
  TP collectives ride ICI and FSDP all-gathers amortize over layers.
  MoE expert weights shard their expert dim over ``"data"`` (expert
  parallelism). With ``use_ring_attention`` the *sequence* is sharded
  over ``"fsdp"`` (context parallelism): attention runs inside a
  ``jax.shard_map`` with every mesh axis manual — batch→data, seq→fsdp,
  heads→tensor — K/V blocks rotating over the fsdp ring
  (ops/ring_attention.py) while the rest of the model stays under XLA
  auto-sharding on the global view. One model therefore exhibits
  dp / fsdp / tp / sp / ep — every sharding family the checkpoint
  preparers (io_preparers/sharded.py) must round-trip and reshard.
- **bf16 compute, f32 params/optimizer**: matmuls hit the MXU in
  bfloat16; Adam moments and softmax statistics stay f32.

This model exists to *exercise the checkpointing framework* end-to-end
(the reference ships training scripts, not models — SURVEY.md §2
#23/#24); it is still a real, trainable transformer.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.flash_attention import flash_attention

# jax.shard_map was promoted to the top-level namespace in newer JAX;
# older versions expose it under jax.experimental.shard_map.
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
else:  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map
from ..ops.ring_attention import ring_attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq_len: int = 1024
    n_experts: int = 0  # 0 → dense FFN; >0 → MoE FFN (EP-sharded weights)
    dtype: Any = jnp.bfloat16  # compute dtype (MXU-friendly)
    param_dtype: Any = jnp.float32
    use_ring_attention: bool = False  # shard the sequence over "fsdp" (CP)
    # Non-ring attention implementation: "auto" → Pallas flash kernel on
    # TPU backends, plain-XLA online softmax elsewhere; "flash" forces
    # the Pallas kernel (interpreter mode off-TPU); "reference" forces
    # the XLA path.
    attention_impl: str = "auto"
    rope_theta: float = 10000.0

    def __post_init__(self) -> None:
        if self.attention_impl not in ("auto", "flash", "reference"):
            raise ValueError(f"unknown attention_impl: {self.attention_impl!r}")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


class Transformer:
    """Functional model: ``init`` → params pytree, ``apply`` → logits."""

    def __init__(self, config: TransformerConfig) -> None:
        if config.d_model % config.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        self.config = config

    # ------------------------------------------------------------------ init

    def init(self, key: jax.Array) -> Params:
        cfg = self.config
        L, D, F, V = cfg.n_layers, cfg.d_model, cfg.d_ff, cfg.vocab_size
        keys = jax.random.split(key, 8)

        def norm(k, *shape, fan_in):
            return jax.random.normal(k, shape, cfg.param_dtype) * fan_in ** -0.5

        params: Params = {
            "embed": norm(keys[0], V, D, fan_in=D),
            "layers": {
                "ln1": jnp.ones((L, D), cfg.param_dtype),
                "ln2": jnp.ones((L, D), cfg.param_dtype),
                "wqkv": norm(keys[1], L, D, 3 * D, fan_in=D),
                "wo": norm(keys[2], L, D, D, fan_in=D),
            },
            "ln_f": jnp.ones((D,), cfg.param_dtype),
            "unembed": norm(keys[3], D, V, fan_in=D),
        }
        if cfg.n_experts:
            E = cfg.n_experts
            params["layers"]["router"] = norm(keys[4], L, D, E, fan_in=D)
            params["layers"]["w1e"] = norm(keys[5], L, E, D, F, fan_in=D)
            params["layers"]["w2e"] = norm(keys[6], L, E, F, D, fan_in=F)
        else:
            params["layers"]["w1"] = norm(keys[5], L, D, F, fan_in=D)
            params["layers"]["w2"] = norm(keys[6], L, F, D, fan_in=F)
        return params

    # ------------------------------------------------------- sharding specs

    def param_specs(self) -> Params:
        """PartitionSpecs over a ("data", "fsdp", "tensor") mesh."""
        cfg = self.config
        specs: Params = {
            "embed": P("fsdp", "tensor"),
            "layers": {
                "ln1": P(None, None),
                "ln2": P(None, None),
                "wqkv": P(None, "fsdp", "tensor"),
                "wo": P(None, "tensor", "fsdp"),
            },
            "ln_f": P(None),
            "unembed": P("tensor", "fsdp"),
        }
        if cfg.n_experts:
            specs["layers"]["router"] = P(None, "fsdp", None)
            # Expert dim over "data" → expert parallelism.
            specs["layers"]["w1e"] = P(None, "data", "fsdp", "tensor")
            specs["layers"]["w2e"] = P(None, "data", "tensor", "fsdp")
        else:
            specs["layers"]["w1"] = P(None, "fsdp", "tensor")
            specs["layers"]["w2"] = P(None, "tensor", "fsdp")
        return specs

    def shard_params(self, params: Params, mesh: Mesh) -> Params:
        specs = self.param_specs()
        return jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
        )

    # --------------------------------------------------------------- forward

    def apply(
        self,
        params: Params,
        tokens: jax.Array,
        mesh: Optional[Mesh] = None,
    ) -> jax.Array:
        """Forward pass → logits [batch, seq, vocab] (f32).

        ``mesh`` is required when ``config.use_ring_attention`` — the
        sequence-parallel attention region is a ``shard_map`` over it.
        Everything outside that region operates on the global logical
        view (RoPE positions, scan over layers, losses) and is sharded
        automatically by XLA.
        """
        cfg = self.config
        if cfg.use_ring_attention and mesh is None:
            raise ValueError("use_ring_attention requires a mesh")
        x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)

        def layer(x, lp):
            x = x + self._attention(lp, _rmsnorm(x, lp["ln1"]), mesh)
            x = x + self._ffn(lp, _rmsnorm(x, lp["ln2"]))
            return x, None

        x, _ = lax.scan(layer, x, params["layers"])
        x = _rmsnorm(x, params["ln_f"])
        return jnp.einsum(
            "bsd,dv->bsv", x, params["unembed"].astype(cfg.dtype),
            preferred_element_type=jnp.float32,
        )

    def _attention(self, lp, x, mesh):
        cfg = self.config
        b, s, _ = x.shape
        qkv = jnp.einsum("bsd,dz->bsz", x, lp["wqkv"].astype(cfg.dtype))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (b, s, cfg.n_heads, cfg.head_dim)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        # RoPE on the global view: positions are plain global indices.
        q = _rope(q, cfg.rope_theta)
        k = _rope(k, cfg.rope_theta)
        if cfg.use_ring_attention:
            # Fully-manual region: batch→data, sequence→fsdp, heads→tensor.
            # Heads are independent (no collective on "tensor"); K/V blocks
            # rotate over the "fsdp" ring.
            spec = P("data", "fsdp", "tensor", None)
            out = _shard_map(
                functools.partial(ring_attention, axis_name="fsdp", causal=True),
                mesh=mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )(q, k, v)
        else:
            impl = cfg.attention_impl
            if impl == "auto":
                impl = "flash" if jax.default_backend() == "tpu" else "reference"
            if impl == "flash":
                out = flash_attention(q, k, v, causal=True)
            else:
                out = ring_attention(q, k, v, axis_name=None, causal=True)
        out = out.reshape(b, s, cfg.d_model)
        return jnp.einsum("bsd,dz->bsz", out, lp["wo"].astype(cfg.dtype))

    def _ffn(self, lp, x):
        cfg = self.config
        if not cfg.n_experts:
            h = jnp.einsum("bsd,df->bsf", x, lp["w1"].astype(cfg.dtype))
            h = jax.nn.gelu(h)
            return jnp.einsum("bsf,fd->bsd", h, lp["w2"].astype(cfg.dtype))
        # MoE with dense soft routing (every token weighted over all
        # experts). The *weights* are EP-sharded; XLA inserts the gathers.
        # Top-k token dispatch (all-to-all) is future work — the
        # checkpoint framework only needs the expert-sharded layout.
        gates = jax.nn.softmax(
            jnp.einsum("bsd,de->bse", x, lp["router"].astype(cfg.dtype)), axis=-1
        )
        h = jnp.einsum("bsd,edf->bsef", x, lp["w1e"].astype(cfg.dtype))
        h = jax.nn.gelu(h)
        out = jnp.einsum("bsef,efd->bsed", h, lp["w2e"].astype(cfg.dtype))
        return jnp.einsum("bsed,bse->bsd", out, gates)

    # ------------------------------------------------------------------ loss

    def loss(
        self, params: Params, tokens: jax.Array, mesh: Optional[Mesh] = None
    ) -> jax.Array:
        """Next-token cross-entropy (last position predicts nothing)."""
        logits = self.apply(params, tokens, mesh=mesh)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
        tgt = tokens[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return nll.mean()


def _rmsnorm(x, scale):
    xf = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-6)
    return (xf * rms).astype(x.dtype) * scale.astype(x.dtype)


def _rope(x, theta):
    """Rotary position embedding over global positions."""
    b, s, h, d = x.shape
    pos = jnp.arange(s)
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    ang = pos[:, None].astype(jnp.float32) * freqs[None, :]  # [s, d/2]
    cos, sin = jnp.cos(ang)[None, :, None, :], jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., 0::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.reshape(b, s, h, d).astype(x.dtype)


# ------------------------------------------------------------------ training


def make_mesh(
    devices=None, mesh_shape: Optional[Tuple[int, int, int]] = None
) -> Mesh:
    """Build a ("data", "fsdp", "tensor") mesh over the given devices."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = _default_mesh_shape(n)
    if int(np.prod(mesh_shape)) != n:
        raise ValueError(f"mesh_shape {mesh_shape} != {n} devices")
    arr = np.asarray(devices).reshape(mesh_shape)
    return Mesh(arr, ("data", "fsdp", "tensor"))


def _default_mesh_shape(n: int) -> Tuple[int, int, int]:
    """Split n devices into (data, fsdp, tensor), preferring fsdp×tensor
    inner axes (ICI-adjacent) of 2×2 when divisible."""
    if n % 4 == 0:
        return (n // 4, 2, 2)
    if n % 2 == 0:
        return (n // 2, 1, 2)
    return (n, 1, 1)


def make_train_step(model: Transformer, mesh: Mesh, learning_rate: float = 1e-3):
    """Jitted SPMD train step ``(state, tokens) -> (state, loss)``.

    ``state = {"params": ..., "opt": {"mu": ..., "nu": ..., "step": ...}}``
    (Adam; f32 moments sharded like their params). Token sharding:
    ``P("data", "fsdp")`` under ring attention — the sequence rides the
    "fsdp" axis as context parallelism — else ``P(("data", "fsdp"), None)``
    (batch sharded over both axes).
    """
    cfg = model.config
    specs = model.param_specs()
    state_specs = train_state_specs(model)
    token_spec = (
        P("data", "fsdp") if cfg.use_ring_attention else P(("data", "fsdp"), None)
    )
    b1, b2, eps = 0.9, 0.999, 1e-8

    def train_step(state, tokens):
        loss, grads = jax.value_and_grad(model.loss)(
            state["params"], tokens, mesh=mesh
        )
        step = state["opt"]["step"] + 1
        bc1 = 1.0 - b1 ** step.astype(jnp.float32)
        bc2 = 1.0 - b2 ** step.astype(jnp.float32)

        def upd(p, g, mu, nu):
            g = g.astype(jnp.float32)
            mu = b1 * mu + (1 - b1) * g
            nu = b2 * nu + (1 - b2) * g * g
            p_new = p.astype(jnp.float32) - learning_rate * (mu / bc1) / (
                jnp.sqrt(nu / bc2) + eps
            )
            return p_new.astype(p.dtype), mu, nu

        out = jax.tree.map(
            upd, state["params"], grads, state["opt"]["mu"], state["opt"]["nu"]
        )
        is_triple = lambda t: isinstance(t, tuple)  # noqa: E731
        params = jax.tree.map(lambda t: t[0], out, is_leaf=is_triple)
        mu = jax.tree.map(lambda t: t[1], out, is_leaf=is_triple)
        nu = jax.tree.map(lambda t: t[2], out, is_leaf=is_triple)
        new_state = {"params": params, "opt": {"mu": mu, "nu": nu, "step": step}}
        return new_state, loss

    def to_named(tree_specs):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            tree_specs,
            is_leaf=lambda s: isinstance(s, P),
        )

    return jax.jit(
        train_step,
        in_shardings=(to_named(state_specs), NamedSharding(mesh, token_spec)),
        out_shardings=(to_named(state_specs), NamedSharding(mesh, P())),
    )


def train_state_specs(model: Transformer) -> Params:
    specs = model.param_specs()
    return {"params": specs, "opt": {"mu": specs, "nu": specs, "step": P()}}


def init_train_state(model: Transformer, mesh: Mesh, key: jax.Array) -> Params:
    """Sharded params + zero-initialized Adam state."""
    specs = model.param_specs()
    params = model.shard_params(model.init(key), mesh)

    def zeros_f32(p, s):
        return jax.device_put(
            jnp.zeros(p.shape, jnp.float32), NamedSharding(mesh, s)
        )

    mu = jax.tree.map(zeros_f32, params, specs)
    nu = jax.tree.map(zeros_f32, params, specs)
    step = jax.device_put(jnp.zeros((), jnp.int32), NamedSharding(mesh, P()))
    return {"params": params, "opt": {"mu": mu, "nu": nu, "step": step}}
