# Version of the tpusnap snapshot format written to SnapshotMetadata.
# Mirrors the role of the reference's torchsnapshot/version.py:17.
__version__ = "0.1.0"
