// Native helpers for tpusnap's hot I/O paths.
//
// The reference gets GIL-released native copies/writes for free through
// torch (TorchScripted tensor copies, torch's file I/O —
// /root/reference/torchsnapshot/io_preparers/tensor.py:351-358). JAX has no
// such runtime, so this tiny C++ library supplies the equivalents:
//
//   ts_write_file    — whole-buffer file write (single open/write loop, no
//                      Python-level chunking, GIL released by the caller)
//   ts_write_file_auto — engine-picking whole-file write: O_DIRECT
//                      zero-copy for aligned sources, RWF_DONTCACHE
//                      uncached buffered I/O for unaligned ones, bounce
//                      pipeline fallback (ts_write_file_direct2); plain
//                      buffered writes hit the dirty-page writeback
//                      throttle well below device speed on large streams
//   ts_read_range    — positional ranged read into a caller buffer
//                      (ts_read_range_direct2: O_DIRECT, preads straight
//                      into aligned destinations)
//   ts_memcpy_par    — multi-threaded memcpy for staging large host buffers
//   ts_crc32c        — CRC32C (Castagnoli, software slice-by-8) for
//                      optional integrity checksums
//
// Built on demand by tpusnap/_native/__init__.py with:
//   g++ -O3 -shared -fPIC -pthread -o libtpusnap_native.so tpusnap_native.cpp

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

#ifdef __linux__
#include <sys/statfs.h>
#include <sys/uio.h>
#endif

// Uncached buffered I/O (Linux 6.14+): write through the page cache —
// so no alignment requirements and a single CPU copy — but kick off
// writeback immediately and drop the pages once it completes. Unlike a
// plain buffered stream, dirty pages never pile up into the writeback
// throttle, and unlike O_DIRECT no bounce buffer is needed for
// unaligned sources. Kernels/filesystems without support fail with
// EOPNOTSUPP/EINVAL and the caller falls back.
#ifndef RWF_DONTCACHE
#define RWF_DONTCACHE 0x00000080
#endif

#ifndef O_DIRECT
#define O_DIRECT 0
#endif

#ifndef TMPFS_MAGIC
#define TMPFS_MAGIC 0x01021994
#endif
#ifndef RAMFS_MAGIC
#define RAMFS_MAGIC 0x858458f6
#endif

// RAM-backed filesystems accept O_DIRECT on recent kernels, but there the
// "device" is a kernel memcpy: the direct path's bounce buffer would just
// add a second CPU copy. A single buffered write is the fastest option.
static bool is_ram_backed(int fd) {
#ifdef __linux__
  struct statfs sfs;
  if (::fstatfs(fd, &sfs) != 0) return false;
  return sfs.f_type == TMPFS_MAGIC || sfs.f_type == RAMFS_MAGIC;
#else
  (void)fd;
  return false;
#endif
}

extern "C" {

int ts_write_file(const char* path, const void* buf, size_t n);
int64_t ts_read_range(const char* path, void* out, int64_t offset, size_t n);
int64_t ts_read_range_direct(const char* path, void* out, int64_t offset,
                             size_t n);
uint32_t ts_crc32c(const void* buf, size_t n, uint32_t seed);

// Returns 0 on success, -errno on failure.
int ts_write_file(const char* path, const void* buf, size_t n) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  const char* p = static_cast<const char*>(buf);
  size_t remaining = n;
  while (remaining > 0) {
    ssize_t written = ::write(fd, p, remaining);
    if (written < 0) {
      if (errno == EINTR) continue;
      int err = errno;
      ::close(fd);
      return -err;
    }
    p += written;
    remaining -= static_cast<size_t>(written);
  }
  if (::close(fd) < 0) return -errno;
  return 0;
}

// O_DIRECT whole-file write with a configurable number of in-flight
// chunk writes (device queue depth) and chunk size. Returns 0 on success
// or -errno. Falls back to the buffered path when O_DIRECT open fails
// (overlayfs, unsupported filesystems), when the target is RAM-backed
// (tmpfs — a bounce copy there only doubles the CPU cost), or for small
// buffers where the setup cost outweighs the page-cache bypass.
//
// Two modes:
// - source 4096-aligned: ZERO-COPY — nthreads workers pwrite directly
//   from the caller's buffer, round-robin over chunks. No bounce memcpy
//   at all (buffers tpusnap allocates itself — slabs, async clones,
//   staged copies — are aligned for exactly this reason).
// - unaligned source (arbitrary user numpy arrays): bounce pipeline with
//   nthreads in-flight chunk writes and nthreads+1 bounce buffers; the
//   caller thread's memcpy into the next free bounce buffer overlaps the
//   in-flight pwrites.
int ts_write_file_direct2(const char* path, const void* buf, size_t n,
                          int nthreads, size_t chunk) {
  static const size_t kAlign = 4096;
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  if (chunk < (1u << 20)) chunk = 1u << 20;
  chunk &= ~(kAlign - 1);
  if (O_DIRECT == 0 || n < (4u << 20)) return ts_write_file(path, buf, n);
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_DIRECT, 0644);
  if (fd < 0) return ts_write_file(path, buf, n);
  if (is_ram_backed(fd)) {
    ::close(fd);
    return ts_write_file(path, buf, n);
  }
#ifdef __linux__
  // Reserve the full extent up front: without this, concurrent direct
  // writers allocate blocks chunk-by-chunk and interleave their extents,
  // which turns later sequential restore reads into seek storms.
  // posix_fallocate returns the error number directly (not via errno).
  // ENOSPC must fail now: letting the write proceed surfaces the failure
  // later and then masks it behind a full buffered rewrite of a possibly
  // multi-GB file. Other errors (EOPNOTSUPP on odd filesystems) are
  // non-fatal — the writes below allocate blocks themselves.
  int fa = ::posix_fallocate(fd, 0, static_cast<off_t>(n));
  if (fa == ENOSPC) {
    ::close(fd);
    ::unlink(path);
    return -ENOSPC;
  }
#endif

  const size_t aligned_n = n & ~(kAlign - 1);
  const char* src = static_cast<const char*>(buf);
  std::atomic<int> werr{0};

  if (reinterpret_cast<uintptr_t>(buf) % kAlign == 0) {
    // Zero-copy: workers write straight from the source buffer.
    std::atomic<size_t> next{0};
    std::vector<std::thread> workers;
    workers.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      workers.emplace_back([&] {
        for (;;) {
          const size_t off = next.fetch_add(chunk);
          if (off >= aligned_n || werr.load()) return;
          const size_t len =
              (aligned_n - off < chunk) ? (aligned_n - off) : chunk;
          size_t pos = 0;
          while (pos < len) {
            ssize_t w = ::pwrite(fd, src + off + pos, len - pos, off + pos);
            if (w < 0) {
              if (errno == EINTR) continue;
              werr.store(errno);
              return;
            }
            pos += static_cast<size_t>(w);
          }
        }
      });
    }
    for (auto& t : workers) t.join();
  } else {
    // Bounce pipeline: nthreads in-flight chunk writes, nthreads+1
    // bounce buffers so the caller's memcpy overlaps all of them. The
    // bounce chunk is capped at 8 MiB regardless of the zero-copy chunk
    // knob: this memory is invisible to the scheduler's staging budget,
    // and at the scheduler's 16-file I/O concurrency larger chunks would
    // pin (16 x (qd+1) x chunk) of untracked RSS.
    if (chunk > (8u << 20)) chunk = 8u << 20;
    const int nbufs = nthreads + 1;
    std::vector<void*> bounce(nbufs, nullptr);
    bool alloc_ok = true;
    for (int i = 0; i < nbufs; ++i) {
      if (::posix_memalign(&bounce[i], kAlign, chunk) != 0) {
        alloc_ok = false;
        break;
      }
    }
    if (!alloc_ok) {
      for (void* b : bounce) std::free(b);
      ::close(fd);
      return ts_write_file(path, buf, n);
    }
    // (thread, buffer index) pairs in flight, oldest first.
    std::deque<std::pair<std::thread, int>> inflight;
    std::deque<int> free_bufs;
    for (int i = 0; i < nbufs; ++i) free_bufs.push_back(i);
    size_t off = 0;
    while (off < aligned_n && !werr.load()) {
      if (free_bufs.empty()) {
        inflight.front().first.join();
        free_bufs.push_back(inflight.front().second);
        inflight.pop_front();
        continue;
      }
      const int bi = free_bufs.front();
      free_bufs.pop_front();
      const size_t len =
          (aligned_n - off < chunk) ? (aligned_n - off) : chunk;
      char* wbuf = static_cast<char*>(bounce[bi]);
      std::memcpy(wbuf, src + off, len);  // overlaps in-flight pwrites
      const size_t woff = off;
      inflight.emplace_back(
          std::thread([fd, wbuf, len, woff, &werr] {
            size_t pos = 0;
            while (pos < len) {
              ssize_t w = ::pwrite(fd, wbuf + pos, len - pos, woff + pos);
              if (w < 0) {
                if (errno == EINTR) continue;
                werr.store(errno);
                return;
              }
              pos += static_cast<size_t>(w);
            }
          }),
          bi);
      off += len;
    }
    while (!inflight.empty()) {
      inflight.front().first.join();
      inflight.pop_front();
    }
    for (void* b : bounce) std::free(b);
  }
  ::close(fd);
  if (werr.load() == ENOSPC) {
    // A full disk won't be cured by a buffered rewrite of the same bytes
    // — fail now instead of doubling the multi-GB I/O on the error path
    // (reachable when posix_fallocate was unsupported, e.g. FUSE).
    ::unlink(path);
    return -ENOSPC;
  }
  if (werr.load()) {
    // Write-phase failure. This covers filesystems/devices that accept
    // O_DIRECT at open() but reject the I/O (logical block size > kAlign,
    // FUSE quirks) and short writes that left the continuation offset
    // unaligned (EINVAL masking the true cause, e.g. a filling disk). A
    // buffered rewrite either succeeds or reports the real errno; when it
    // fails too (disk genuinely full), don't leave a partial blob behind.
    int rc = ts_write_file(path, buf, n);
    if (rc != 0) ::unlink(path);
    return rc;
  }

  // Unaligned tail: a buffered positional write (offset need not be
  // block-aligned once the O_DIRECT fd is closed).
  if (aligned_n < n) {
    // Don't leave a partial blob behind on failure, matching the
    // ENOSPC and buffered-rewrite error paths above.
    int tfd = ::open(path, O_WRONLY);
    if (tfd < 0) {
      int err = errno;
      ::unlink(path);
      return -err;
    }
    const char* p = src + aligned_n;
    size_t remaining = n - aligned_n;
    off_t pos = static_cast<off_t>(aligned_n);
    while (remaining > 0) {
      ssize_t w = ::pwrite(tfd, p, remaining, pos);
      if (w < 0) {
        if (errno == EINTR) continue;
        int err = errno;
        ::close(tfd);
        ::unlink(path);
        return -err;
      }
      p += w;
      pos += w;
      remaining -= static_cast<size_t>(w);
    }
    if (::close(tfd) < 0) {
      int err = errno;
      ::unlink(path);
      return -err;
    }
  }
  return 0;
}

// Whole-file write via uncached buffered I/O (RWF_DONTCACHE). Returns 0
// or -errno; -EOPNOTSUPP/-EINVAL mean the kernel/filesystem lacks
// support and the caller should fall back to the O_DIRECT path.
int ts_write_file_dontcache(const char* path, const void* buf, size_t n) {
#ifndef __linux__
  (void)path;
  (void)buf;
  (void)n;
  return -EOPNOTSUPP;
#else
  static const size_t kChunk = 8u << 20;
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  const char* p = static_cast<const char*>(buf);
  size_t off = 0;
  while (off < n) {
    const size_t len = (n - off < kChunk) ? (n - off) : kChunk;
    struct iovec iov = {const_cast<char*>(p + off), len};
    ssize_t w = ::pwritev2(fd, &iov, 1, static_cast<off_t>(off),
                           RWF_DONTCACHE);
    if (w < 0) {
      if (errno == EINTR) continue;
      int err = errno;
      ::close(fd);
      return -err;
    }
    if (w == 0) {
      ::close(fd);
      return -EIO;
    }
    off += static_cast<size_t>(w);
  }
  if (::close(fd) < 0) return -errno;
  return 0;
#endif
}

// Preferred whole-file write: picks the cheapest correct engine.
// - aligned source on an O_DIRECT-capable fs: O_DIRECT zero-copy (no CPU
//   copy at all, data at the device on return);
// - unaligned source + allow_dontcache: uncached buffered write (one
//   CPU copy, no bounce buffer, writeback already in flight on return);
// - aligned source where O_DIRECT open fails (overlayfs etc.):
//   dontcache — falling straight to the plain buffered path would hit
//   the dirty-page writeback throttle this module exists to avoid;
// - otherwise: O_DIRECT bounce pipeline / buffered fallback.
int ts_write_file_auto(const char* path, const void* buf, size_t n,
                       int nthreads, size_t chunk, int allow_dontcache) {
  if (O_DIRECT == 0 || n < (4u << 20)) return ts_write_file(path, buf, n);
  const bool aligned = reinterpret_cast<uintptr_t>(buf) % 4096 == 0;
  bool try_dontcache = allow_dontcache && !aligned;
  if (aligned && allow_dontcache) {
    int probe = ::open(path, O_WRONLY | O_CREAT | O_DIRECT, 0644);
    if (probe < 0) {
      try_dontcache = true;  // no O_DIRECT on this fs
    } else {
      ::close(probe);
    }
  }
  if (try_dontcache) {
    int rc = ts_write_file_dontcache(path, buf, n);
    if (rc == 0) return 0;
    if (rc != -EOPNOTSUPP && rc != -EINVAL) {
      // Real I/O failure: don't leave a partial multi-GB blob behind
      // (matches the direct engines' cleanup contract).
      ::unlink(path);
      return rc;
    }
    // Unsupported here — fall through to the O_DIRECT engines.
  }
  return ts_write_file_direct2(path, buf, n, nthreads, chunk);
}

// Positional ranged read. Returns bytes read (>=0) or -errno.
int64_t ts_read_range(const char* path, void* out, int64_t offset, size_t n) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
#ifdef POSIX_FADV_SEQUENTIAL
  // Large sequential consumers: widen kernel readahead (the default
  // window caps buffered cold reads well below device speed).
  ::posix_fadvise(fd, offset, n, POSIX_FADV_SEQUENTIAL);
  ::posix_fadvise(fd, offset, n, POSIX_FADV_WILLNEED);
#endif
  char* p = static_cast<char*>(out);
  size_t remaining = n;
  int64_t pos = offset;
  while (remaining > 0) {
    ssize_t got = ::pread(fd, p, remaining, pos);
    if (got < 0) {
      if (errno == EINTR) continue;
      int err = errno;
      ::close(fd);
      return -err;
    }
    if (got == 0) break;  // EOF
    p += got;
    pos += got;
    remaining -= static_cast<size_t>(got);
  }
  ::close(fd);
  return static_cast<int64_t>(n - remaining);
}

// Zero-copy O_DIRECT ranged read: when the destination buffer and file
// offset are 4096-aligned (buffers tpusnap allocates are), workers pread
// straight into the caller's buffer — no bounce memcpy at all. This
// matters most on few-core hosts: a bounce copy per concurrent reader
// starves the deserialize/copy consumers running on the same cores.
// Returns bytes read or -errno; falls back to the bounce-buffer variant
// (ts_read_range_direct) when alignment doesn't hold, and to buffered
// reads on RAM-backed filesystems (the page "cache" IS the storage
// there; O_DIRECT would only forfeit the kernel's fast path).
int64_t ts_read_range_direct2(const char* path, void* out, int64_t offset,
                              size_t n, int nthreads, size_t chunk) {
  static const int64_t kAlign = 4096;
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  if (chunk < (1u << 20)) chunk = 1u << 20;
  chunk &= ~(static_cast<size_t>(kAlign) - 1);
  if (O_DIRECT == 0 || n < (4u << 20))
    return ts_read_range(path, out, offset, n);
  if (reinterpret_cast<uintptr_t>(out) % kAlign != 0 || offset % kAlign != 0)
    return ts_read_range_direct(path, out, offset, n);
  int fd = ::open(path, O_RDONLY | O_DIRECT, 0);
  if (fd < 0) return ts_read_range(path, out, offset, n);
  if (is_ram_backed(fd)) {
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }
  const int64_t file_size = st.st_size;
  const int64_t req_end =
      (offset + static_cast<int64_t>(n) < file_size)
          ? offset + static_cast<int64_t>(n)
          : file_size;
  if (req_end <= offset) {
    ::close(fd);
    return 0;
  }
  // Whole blocks inside the file land direct; the final partial block
  // (when the request reaches into it) goes through a buffered pread.
  const int64_t a_end = req_end & ~(kAlign - 1);
  char* dst = static_cast<char*>(out);
  std::atomic<int> rerr{0};
  std::atomic<bool> rshort{false};
  if (a_end > offset) {
    std::atomic<int64_t> next{offset};
    std::vector<std::thread> workers;
    workers.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) {
      workers.emplace_back([&, fd] {
        for (;;) {
          const int64_t off = next.fetch_add(static_cast<int64_t>(chunk));
          if (off >= a_end || rerr.load() || rshort.load()) return;
          const int64_t len =
              (a_end - off < static_cast<int64_t>(chunk))
                  ? (a_end - off)
                  : static_cast<int64_t>(chunk);
          int64_t pos = 0;
          while (pos < len) {
            ssize_t got =
                ::pread(fd, dst + (off - offset) + pos, len - pos, off + pos);
            if (got < 0) {
              if (errno == EINTR) continue;
              rerr.store(errno);
              return;
            }
            if (got == 0) {  // file shrank under us
              rshort.store(true);
              return;
            }
            pos += got;
          }
        }
      });
    }
    for (auto& t : workers) t.join();
  }
  ::close(fd);
  if (rerr.load() || rshort.load())
    return ts_read_range(path, out, offset, n);
  int64_t total = a_end - offset;
  if (req_end > a_end) {
    int64_t tail = ts_read_range(path, dst + (a_end - offset), a_end,
                                 static_cast<size_t>(req_end - a_end));
    if (tail < 0) return tail;
    total += tail;
  }
  return total;
}

// O_DIRECT double-buffered ranged read: bypasses the page cache, whose
// bounded readahead window caps cold buffered reads far below device
// speed. The requested range is covered by aligned block reads through a
// bounce buffer (memcpy out overlaps the next in-flight pread); any
// misaligned head/tail falls back to a buffered pread. Returns bytes
// read or -errno; falls back to ts_read_range when O_DIRECT open fails.
int64_t ts_read_range_direct(const char* path, void* out, int64_t offset,
                             size_t n) {
  static const int64_t kAlign = 4096;
  static const size_t kChunk = 8u << 20;
  if (O_DIRECT == 0 || n < (4u << 20))
    return ts_read_range(path, out, offset, n);
  int fd = ::open(path, O_RDONLY | O_DIRECT, 0);
  if (fd < 0) return ts_read_range(path, out, offset, n);

  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }
  const int64_t file_size = st.st_size;
  const int64_t req_end =
      (offset + static_cast<int64_t>(n) < file_size)
          ? offset + static_cast<int64_t>(n)
          : file_size;
  if (req_end <= offset) {
    ::close(fd);
    return 0;
  }
  // Aligned window fully covered by whole blocks inside the file. When
  // the request starts inside the file's final partial block the window
  // is empty (a_end < a_start) — nothing direct-readable, use buffered.
  const int64_t a_start = (offset + kAlign - 1) & ~(kAlign - 1);
  const int64_t a_end = req_end & ~(kAlign - 1);
  if (a_end <= a_start) {
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }

  void* bounce[2] = {nullptr, nullptr};
  if (::posix_memalign(&bounce[0], kAlign, kChunk) != 0 ||
      ::posix_memalign(&bounce[1], kAlign, kChunk) != 0) {
    std::free(bounce[0]);
    std::free(bounce[1]);
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }

  char* dst = static_cast<char*>(out);
  // Per-buffer results: chunk i writes slot i&1, so the two in-flight
  // chunks never share a result slot. <0: -errno; >=0: bytes read.
  std::atomic<int64_t> rres[2] = {{0}, {0}};
  std::thread reader;
  int err = 0;
  int64_t pos = a_start;
  int idx = 0;
  int64_t pending_len = 0;  // length of the chunk the reader is filling
  int pending_idx = 0;
  int64_t pending_pos = 0;
  bool short_read = false;
  while (pos < a_end && !short_read) {
    const int64_t len =
        (a_end - pos < static_cast<int64_t>(kChunk)) ? (a_end - pos)
                                                     : static_cast<int64_t>(kChunk);
    char* buf = static_cast<char*>(bounce[idx]);
    std::atomic<int64_t>* slot = &rres[idx];
    // Kick off the pread for this chunk, then (on the main thread) copy
    // the PREVIOUS chunk out while it is in flight.
    std::thread t([fd, buf, len, pos, slot] {
      int64_t done = 0;
      while (done < len) {
        ssize_t got = ::pread(fd, buf + done, len - done, pos + done);
        if (got < 0) {
          if (errno == EINTR) continue;
          slot->store(-static_cast<int64_t>(errno));
          return;
        }
        if (got == 0) break;  // EOF (file shrank under us)
        done += got;
      }
      slot->store(done);
    });
    if (reader.joinable()) {
      reader.join();
      const int64_t got = rres[pending_idx].load();
      if (got < 0) {
        err = static_cast<int>(-got);
        t.join();
        reader = std::thread();
        break;
      }
      std::memcpy(dst + (pending_pos - offset), bounce[pending_idx],
                  static_cast<size_t>(got));
      if (got < pending_len) short_read = true;
    }
    reader = std::move(t);
    pending_len = len;
    pending_idx = idx;
    pending_pos = pos;
    pos += len;
    idx ^= 1;
  }
  if (reader.joinable()) {
    reader.join();
    const int64_t got = rres[pending_idx].load();
    if (got < 0) {
      if (err == 0) err = static_cast<int>(-got);
    } else if (err == 0 && !short_read) {
      std::memcpy(dst + (pending_pos - offset), bounce[pending_idx],
                  static_cast<size_t>(got));
      if (got < pending_len) short_read = true;
    }
  }
  std::free(bounce[0]);
  std::free(bounce[1]);
  ::close(fd);
  if (err != 0) return ts_read_range(path, out, offset, n);

  // Misaligned head ([offset, a_start)) and tail ([a_end, req_end)) via
  // buffered preads; also re-read everything after an unexpected short
  // direct read through the buffered path.
  if (short_read) return ts_read_range(path, out, offset, n);
  int64_t total = a_end - a_start;
  if (a_start > offset) {
    int64_t head = ts_read_range(path, dst, offset, a_start - offset);
    if (head < 0) return head;
    total += head;
  }
  if (req_end > a_end) {
    int64_t tail = ts_read_range(path, dst + (a_end - offset), a_end,
                                 static_cast<size_t>(req_end - a_end));
    if (tail < 0) return tail;
    total += tail;
  }
  return total;
}

// Fused read-into-destination with optional inline CRC32C.
//
// Restores on few-core hosts are CPU-ceiling-bound, not disk-bound: the
// scratch-buffer pipeline costs one DMA + a checksum pass + a memcpy pass
// per byte, all competing for the same cores as the storage interrupts.
// This op reads [offset, offset+n) of `path` straight into the caller's
// (arbitrarily aligned) destination and computes the checksum DURING the
// bounce copy-out — sub-blocks sized to stay in L1, so the CRC pass reads
// cache-hot bytes and RAM traffic is one read + one write per byte total.
// The scheduler's consume stage then verifies a 4-byte value instead of
// re-reading gigabytes.
//
// Engine choice mirrors ts_read_range_direct: O_DIRECT chunked preads
// through bounce buffers (nthreads in flight, processed strictly in file
// order because CRC32C is sequential), buffered fallback for small
// ranges / unsupported filesystems / RAM-backed mounts, misaligned head
// and tail via buffered preads. If the destination and file offset are
// both block-aligned, the zero-copy direct reader is used instead and the
// checksum (when requested) is one pass over the destination.
//
// Returns bytes read (short only at EOF) or -errno. *crc_out is written
// only on success, and only when crc_out != NULL.

static uint32_t ts_crccpy(char* dst, const char* src, size_t n, uint32_t crc,
                          int want_crc) {
  if (!want_crc) {
    std::memcpy(dst, src, n);
    return crc;
  }
  static const size_t kSub = 65536;  // L1/L2-resident sub-block
  size_t off = 0;
  while (off < n) {
    const size_t len = (n - off < kSub) ? (n - off) : kSub;
    // CRC the source sub-block first (brings it into cache), then copy
    // the cache-hot bytes out: one RAM read + one RAM write per byte,
    // and no store-to-load traffic on the just-written destination.
    crc = ts_crc32c(src + off, len, crc);
    std::memcpy(dst + off, src + off, len);
    off += len;
  }
  return crc;
}

static int64_t read_into_buffered_crc(const char* path, void* out,
                                      int64_t offset, size_t n,
                                      uint32_t* crc_out) {
  int64_t got = ts_read_range(path, out, offset, n);
  if (got < 0) return got;
  if (crc_out != nullptr)
    *crc_out = ts_crc32c(out, static_cast<size_t>(got), 0);
  return got;
}

int64_t ts_read_range_into_crc(const char* path, void* out, int64_t offset,
                               size_t n, int nthreads, size_t chunk,
                               uint32_t* crc_out) {
  static const int64_t kAlign = 4096;
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 8) nthreads = 8;
  // Bounce memory here is invisible to the scheduler's budget; cap it.
  if (chunk < (1u << 20)) chunk = 1u << 20;
  if (chunk > (8u << 20)) chunk = 8u << 20;
  chunk &= ~(static_cast<size_t>(kAlign) - 1);
  if (O_DIRECT == 0 || n < (4u << 20))
    return read_into_buffered_crc(path, out, offset, n, crc_out);
  if (reinterpret_cast<uintptr_t>(out) % kAlign == 0 && offset % kAlign == 0) {
    int64_t got = ts_read_range_direct2(path, out, offset, n, nthreads,
                                        chunk * 4);
    if (got < 0) return got;
    if (crc_out != nullptr)
      *crc_out = ts_crc32c(out, static_cast<size_t>(got), 0);
    return got;
  }
  int fd = ::open(path, O_RDONLY | O_DIRECT, 0);
  if (fd < 0) return read_into_buffered_crc(path, out, offset, n, crc_out);
  if (is_ram_backed(fd)) {
    ::close(fd);
    return read_into_buffered_crc(path, out, offset, n, crc_out);
  }
  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return read_into_buffered_crc(path, out, offset, n, crc_out);
  }
  const int64_t file_size = st.st_size;
  const int64_t req_end =
      (offset + static_cast<int64_t>(n) < file_size)
          ? offset + static_cast<int64_t>(n)
          : file_size;
  if (req_end <= offset) {
    ::close(fd);
    if (crc_out != nullptr) *crc_out = ts_crc32c(out, 0, 0);
    return 0;
  }
  const int64_t a_start = (offset + kAlign - 1) & ~(kAlign - 1);
  const int64_t a_end = req_end & ~(kAlign - 1);
  if (a_end <= a_start) {
    ::close(fd);
    return read_into_buffered_crc(path, out, offset, n, crc_out);
  }

  // Don't allocate more bounce memory than the window needs: a small
  // (e.g. budget-tile) read must not pin (nthreads+1) full chunks.
  const int64_t window = a_end - a_start;
  if (static_cast<int64_t>(chunk) > window)
    chunk = static_cast<size_t>(window);  // window is block-aligned
  const int64_t n_chunks =
      (window + static_cast<int64_t>(chunk) - 1) / static_cast<int64_t>(chunk);
  const int nbufs =
      (n_chunks < nthreads + 1) ? static_cast<int>(n_chunks) : nthreads + 1;
  std::vector<void*> bounce(nbufs, nullptr);
  for (int i = 0; i < nbufs; ++i) {
    if (::posix_memalign(&bounce[i], kAlign, chunk) != 0) {
      for (void* b : bounce) std::free(b);
      ::close(fd);
      return read_into_buffered_crc(path, out, offset, n, crc_out);
    }
  }

  char* dst = static_cast<char*>(out);
  const int want_crc = crc_out != nullptr;
  uint32_t crc = 0;
  bool failed = false;
  bool short_read = false;

  // Misaligned head via buffered pread (CRC is sequential, so the head
  // must be hashed before the first aligned chunk).
  if (a_start > offset) {
    int64_t head = ts_read_range(path, dst, offset,
                                 static_cast<size_t>(a_start - offset));
    if (head < 0 || head < a_start - offset) failed = true;
    if (!failed && want_crc)
      crc = ts_crc32c(dst, static_cast<size_t>(a_start - offset), crc);
  }

  if (!failed) {
    // nthreads chunk preads in flight; the main thread drains them in
    // strict file order, fusing the bounce->dst copy with the CRC.
    struct Inflight {
      std::thread thread;
      int buf_idx;
      int64_t pos;
      int64_t len;
    };
    std::vector<std::atomic<int64_t>> results(nbufs);
    std::deque<Inflight> inflight;
    std::deque<int> free_bufs;
    for (int i = 0; i < nbufs; ++i) free_bufs.push_back(i);
    int64_t pos = a_start;
    while ((pos < a_end || !inflight.empty()) && !failed && !short_read) {
      while (pos < a_end && !free_bufs.empty() &&
             static_cast<int>(inflight.size()) < nthreads) {
        const int bi = free_bufs.front();
        free_bufs.pop_front();
        const int64_t len = (a_end - pos < static_cast<int64_t>(chunk))
                                ? (a_end - pos)
                                : static_cast<int64_t>(chunk);
        char* buf = static_cast<char*>(bounce[bi]);
        std::atomic<int64_t>* slot = &results[bi];
        inflight.push_back(Inflight{
            std::thread([fd, buf, len, pos, slot] {
              int64_t done = 0;
              while (done < len) {
                ssize_t got =
                    ::pread(fd, buf + done, len - done, pos + done);
                if (got < 0) {
                  if (errno == EINTR) continue;
                  slot->store(-static_cast<int64_t>(errno));
                  return;
                }
                if (got == 0) break;  // file shrank under us
                done += got;
              }
              slot->store(done);
            }),
            bi, pos, len});
        pos += len;
      }
      Inflight f = std::move(inflight.front());
      inflight.pop_front();
      f.thread.join();
      const int64_t got = results[f.buf_idx].load();
      if (got < 0) {
        failed = true;
      } else {
        crc = ts_crccpy(dst + (f.pos - offset),
                        static_cast<char*>(bounce[f.buf_idx]),
                        static_cast<size_t>(got), crc, want_crc);
        if (got < f.len) short_read = true;
      }
      free_bufs.push_back(f.buf_idx);
    }
    for (auto& rem : inflight) rem.thread.join();
  }

  for (void* b : bounce) std::free(b);
  ::close(fd);
  // A short direct read means the file changed size mid-read; re-read the
  // whole range through the simple buffered path for a consistent result.
  if (failed || short_read)
    return read_into_buffered_crc(path, out, offset, n, crc_out);

  // Tail ([a_end, req_end)) via buffered pread.
  int64_t total = a_end - offset;
  if (req_end > a_end) {
    int64_t tail = ts_read_range(path, dst + (a_end - offset), a_end,
                                 static_cast<size_t>(req_end - a_end));
    if (tail < 0) return tail;
    if (want_crc)
      crc = ts_crc32c(dst + (a_end - offset), static_cast<size_t>(tail), crc);
    total += tail;
  }
  if (crc_out != nullptr) *crc_out = crc;
  return total;
}

// Fused clone + per-tile CRC32C: copies [src, src+n) to dst while
// computing an independent (seed-0) CRC per ``tile`` bytes into
// crcs[0..ceil(n/tile)). One memory pass instead of a hash pass plus a
// copy pass — this is the async-snapshot staging hot path, where the
// defensive clone and the integrity checksum would otherwise each read
// every byte. Tiles are independent, so they parallelize across
// nthreads; the caller derives the whole-blob CRC with
// ts_crc32c_combine. n == 0 writes nothing (caller handles empties).
void ts_memcpy_crc_tiles(void* dst, const void* src, size_t n, size_t tile,
                         uint32_t* crcs, int nthreads) {
  if (n == 0) return;
  if (tile == 0 || tile > n) tile = n;
  const size_t n_tiles = (n + tile - 1) / tile;
  std::atomic<size_t> next{0};
  auto work = [&] {
    for (;;) {
      const size_t i = next.fetch_add(1);
      if (i >= n_tiles) return;
      const size_t off = i * tile;
      const size_t len = (n - off < tile) ? (n - off) : tile;
      crcs[i] = ts_crccpy(static_cast<char*>(dst) + off,
                          static_cast<const char*>(src) + off, len, 0, 1);
    }
  };
  if (nthreads <= 1 || n_tiles == 1 || n < (8u << 20)) {
    work();
    return;
  }
  const int nt = (static_cast<size_t>(nthreads) < n_tiles)
                     ? nthreads
                     : static_cast<int>(n_tiles);
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(work);
  for (auto& t : threads) t.join();
}

// Multi-threaded memcpy; nthreads <= 1 degrades to plain memcpy.
void ts_memcpy_par(void* dst, const void* src, size_t n, int nthreads) {
  if (nthreads <= 1 || n < (8u << 20)) {
    std::memcpy(dst, src, n);
    return;
  }
  size_t chunk = (n + nthreads - 1) / nthreads;
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int i = 0; i < nthreads; ++i) {
    size_t off = static_cast<size_t>(i) * chunk;
    if (off >= n) break;
    size_t len = (off + chunk <= n) ? chunk : (n - off);
    threads.emplace_back([=] {
      std::memcpy(static_cast<char*>(dst) + off,
                  static_cast<const char*>(src) + off, len);
    });
  }
  for (auto& t : threads) t.join();
}

static uint32_t kCrcTable[8][256];
static bool kCrcInit = [] {
  const uint32_t poly = 0x82f63b78u;  // CRC32C (Castagnoli), reflected
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kCrcTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int s = 1; s < 8; ++s)
      kCrcTable[s][i] =
          (kCrcTable[s - 1][i] >> 8) ^ kCrcTable[0][kCrcTable[s - 1][i] & 0xff];
  return true;
}();

uint32_t ts_crc32c_combine(uint32_t crc1, uint32_t crc2, uint64_t len2);

#ifdef __SSE4_2__
// One-lane hardware CRC over [p, p+n) given a RAW (non-inverted) state.
static uint64_t crc32c_hw_raw(const uint8_t* p, size_t n, uint64_t state) {
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    state = __builtin_ia32_crc32di(state, v);
    p += 8;
    n -= 8;
  }
  uint32_t s32 = static_cast<uint32_t>(state);
  while (n--) s32 = __builtin_ia32_crc32qi(s32, *p++);
  return s32;
}
#endif

uint32_t ts_crc32c(const void* buf, size_t n, uint32_t seed) {
  (void)kCrcInit;
  uint32_t crc = ~seed;
  const uint8_t* p = static_cast<const uint8_t*>(buf);
#ifdef __SSE4_2__
  // Hardware CRC32C (the checksum exists to run at stage time inside the
  // take's hot path). A single crc32 dependency chain is latency-bound
  // (~8B / 3 cycles); for large buffers, THREE independent lanes run in
  // the instruction's throughput shadow and are merged with the GF(2)
  // combine — ~3x single-lane, bit-identical result.
  if (n >= (1u << 14)) {
    const size_t lane = (n / 3) & ~static_cast<size_t>(7);
    const uint8_t* p0 = p;
    const uint8_t* p1 = p + lane;
    const uint8_t* p2 = p + 2 * lane;
    uint64_t s0 = crc, s1 = 0xFFFFFFFFu, s2 = 0xFFFFFFFFu;
    size_t k = lane;
    while (k >= 8) {
      uint64_t v0, v1, v2;
      std::memcpy(&v0, p0, 8);
      std::memcpy(&v1, p1, 8);
      std::memcpy(&v2, p2, 8);
      s0 = __builtin_ia32_crc32di(s0, v0);
      s1 = __builtin_ia32_crc32di(s1, v1);
      s2 = __builtin_ia32_crc32di(s2, v2);
      p0 += 8;
      p1 += 8;
      p2 += 8;
      k -= 8;
    }
    // Lane results as finalized crcs (seeded 0 for lanes 1/2).
    uint32_t c0 = ~static_cast<uint32_t>(s0);
    uint32_t c1 = ~static_cast<uint32_t>(s1);
    uint32_t c2 = ~static_cast<uint32_t>(s2);
    uint32_t merged = ts_crc32c_combine(c0, c1, lane);
    merged = ts_crc32c_combine(merged, c2, lane);
    // Tail: remaining bytes after the three lanes, chained normally.
    const size_t tail_off = 3 * lane;
    return ts_crc32c(p + tail_off, n - tail_off, merged);
  }
  uint32_t out = static_cast<uint32_t>(crc32c_hw_raw(p, n, crc));
  return ~out;
#else
  while (n >= 8) {
    crc ^= static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
    crc = kCrcTable[7][crc & 0xff] ^ kCrcTable[6][(crc >> 8) & 0xff] ^
          kCrcTable[5][(crc >> 16) & 0xff] ^ kCrcTable[4][crc >> 24] ^
          kCrcTable[3][p[4]] ^ kCrcTable[2][p[5]] ^ kCrcTable[1][p[6]] ^
          kCrcTable[0][p[7]];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *p++) & 0xff];
  return ~crc;
#endif
}

// CRC32C combine (zlib crc32_combine adapted to the Castagnoli
// polynomial): crc of a concatenation A||B from crc(A), crc(B), len(B),
// in O(log len2) GF(2) matrix operations. Lets the stager hash a blob
// ONCE at tile granularity and still record the whole-blob checksum, and
// lets tile-aligned partial reads be verified by combining recorded tile
// checksums — no second hash pass anywhere.
static uint32_t gf2_matrix_times(const uint32_t* mat, uint32_t vec) {
  uint32_t sum = 0;
  int i = 0;
  while (vec) {
    if (vec & 1) sum ^= mat[i];
    vec >>= 1;
    ++i;
  }
  return sum;
}

static void gf2_matrix_square(uint32_t* square, const uint32_t* mat) {
  for (int n = 0; n < 32; ++n) square[n] = gf2_matrix_times(mat, mat[n]);
}

// Shift operators for the combine: kShiftMat[k] advances a CRC past 2^k
// zero bytes. Computed once — the zlib-style algorithm re-derives them
// with 2 + 2*log2(len2) matrix squarings on EVERY call (~25 us), which
// put a ~50 us floor under each multi-lane hash and moved the 3-lane
// break-even from ~64 KiB to ~430 KiB.
static uint32_t kShiftMat[64][32];
static bool kShiftInit = [] {
  uint32_t odd[32];
  uint32_t even[32];
  odd[0] = 0x82f63b78u;  // CRC32C (Castagnoli), reflected: shift by 1 bit
  uint32_t row = 1;
  for (int n = 1; n < 32; ++n) {
    odd[n] = row;
    row <<= 1;
  }
  gf2_matrix_square(even, odd);          // 2 bits
  gf2_matrix_square(odd, even);          // 4 bits
  gf2_matrix_square(kShiftMat[0], odd);  // 8 bits = 1 byte
  for (int k = 1; k < 64; ++k)
    gf2_matrix_square(kShiftMat[k], kShiftMat[k - 1]);
  return true;
}();

// ---------------------------------------------------------------------------
// XXH64 — the second, independent hash backing incremental-dedup equality.
//
// A single 32-bit CRC per blob makes "unchanged" decisions with a ~2^-32
// silent-collision channel per blob-take (a changed blob whose CRC
// collides with the base's skips its write and restores stale data, and
// the scrub passes because the manifest records the colliding value).
// Dedup therefore requires BOTH the CRC32C and this 64-bit XXH64 to
// match — independent constructions, ~2^-96 combined. XXH64 (Yann
// Collet, BSD) is used because it runs near RAM speed on one core,
// so fusing it into the existing hash pass keeps staging disk-bound.

static const uint64_t kXxhP1 = 11400714785074694791ULL;
static const uint64_t kXxhP2 = 14029467366897019727ULL;
static const uint64_t kXxhP3 = 1609587929392839161ULL;
static const uint64_t kXxhP4 = 9650029242287828579ULL;
static const uint64_t kXxhP5 = 2870177450012600261ULL;

static inline uint64_t xxh_rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}
static inline uint64_t xxh_read64(const char* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}
static inline uint32_t xxh_read32(const char* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}
static inline uint64_t xxh_round(uint64_t acc, uint64_t lane) {
  acc += lane * kXxhP2;
  acc = xxh_rotl64(acc, 31);
  return acc * kXxhP1;
}
static inline uint64_t xxh_merge(uint64_t h, uint64_t v) {
  h ^= xxh_round(0, v);
  return h * kXxhP1 + kXxhP4;
}

// Streaming state: lets the fused tile pass feed 32-byte-aligned blocks
// while they are still L2-hot from the CRC pass, so RAM is read once.
struct Xxh64State {
  uint64_t v1, v2, v3, v4;
  uint64_t total;
  explicit Xxh64State(uint64_t seed)
      : v1(seed + kXxhP1 + kXxhP2),
        v2(seed + kXxhP2),
        v3(seed),
        v4(seed - kXxhP1),
        total(0) {}
};

// Consume the longest prefix of whole 32-byte stripes; returns bytes
// consumed. Interior blocks must be multiples of 32 so no tail buffering
// is needed between blocks.
static size_t xxh_consume_stripes(Xxh64State& s, const char* p, size_t n) {
  size_t consumed = 0;
  while (n - consumed >= 32) {
    s.v1 = xxh_round(s.v1, xxh_read64(p + consumed));
    s.v2 = xxh_round(s.v2, xxh_read64(p + consumed + 8));
    s.v3 = xxh_round(s.v3, xxh_read64(p + consumed + 16));
    s.v4 = xxh_round(s.v4, xxh_read64(p + consumed + 24));
    consumed += 32;
  }
  s.total += consumed;
  return consumed;
}

static uint64_t xxh_finalize(const Xxh64State& s, uint64_t seed,
                             const char* tail, size_t tail_n) {
  const uint64_t total = s.total + tail_n;
  uint64_t h;
  if (total >= 32) {
    h = xxh_rotl64(s.v1, 1) + xxh_rotl64(s.v2, 7) + xxh_rotl64(s.v3, 12) +
        xxh_rotl64(s.v4, 18);
    h = xxh_merge(h, s.v1);
    h = xxh_merge(h, s.v2);
    h = xxh_merge(h, s.v3);
    h = xxh_merge(h, s.v4);
  } else {
    h = seed + kXxhP5;
  }
  h += total;
  const char* p = tail;
  size_t n = tail_n;
  while (n >= 8) {
    h ^= xxh_round(0, xxh_read64(p));
    h = xxh_rotl64(h, 27) * kXxhP1 + kXxhP4;
    p += 8;
    n -= 8;
  }
  if (n >= 4) {
    h ^= static_cast<uint64_t>(xxh_read32(p)) * kXxhP1;
    h = xxh_rotl64(h, 23) * kXxhP2 + kXxhP3;
    p += 4;
    n -= 4;
  }
  while (n > 0) {
    h ^= static_cast<uint64_t>(static_cast<unsigned char>(*p)) * kXxhP5;
    h = xxh_rotl64(h, 11) * kXxhP1;
    ++p;
    --n;
  }
  h ^= h >> 33;
  h *= kXxhP2;
  h ^= h >> 29;
  h *= kXxhP3;
  h ^= h >> 32;
  return h;
}

uint64_t ts_xxh64(const void* buf, size_t n, uint64_t seed) {
  if (n == 0) {
    // Callers may pass NULL for empty input; `p + consumed` on a null
    // pointer is UB, so finalize the empty stream without touching it.
    Xxh64State s0(seed);
    static const char kEmpty = 0;
    return xxh_finalize(s0, seed, &kEmpty, 0);
  }
  const char* p = static_cast<const char*>(buf);
  Xxh64State s(seed);
  const size_t consumed = xxh_consume_stripes(s, p, n);
  return xxh_finalize(s, seed, p + consumed, n - consumed);
}

// Shared inner loop of the fused tile passes: hash one tile with both
// CRC32C and XXH64, optionally copying it to dst first. Processes
// 256 KiB blocks so the second hash reads each block while it is still
// cache-hot from the copy/first hash — one RAM read per byte total.
static void hash_tile_dual(char* dst, const char* src, size_t len,
                           uint32_t* crc_out, uint64_t* xxh_out) {
  const size_t kBlock = 256u << 10;  // multiple of 32 (stripe size)
  uint32_t crc = 0;
  Xxh64State s(0);
  size_t done = 0;
  while (done < len) {
    const size_t blk = (len - done < kBlock) ? (len - done) : kBlock;
    const char* hp = src + done;
    if (dst != nullptr) {
      std::memcpy(dst + done, src + done, blk);
      hp = dst + done;  // hash the copy while it is cache-hot
    }
    crc = ts_crc32c(hp, blk, crc);
    if (done + blk < len) {
      xxh_consume_stripes(s, hp, blk);  // interior blocks: 32-aligned
    } else {
      const size_t c = xxh_consume_stripes(s, hp, blk);
      *xxh_out = xxh_finalize(s, 0, hp + c, blk - c);
    }
    done += blk;
  }
  if (len == 0) *xxh_out = xxh_finalize(s, 0, src, 0);
  *crc_out = crc;
}

// Per-tile CRC32C + XXH64 of [src, src+n) in one memory pass (dst=NULL),
// or fused with a clone into dst (the async-snapshot staging path, where
// the defensive copy, the integrity CRC and the dedup hash would
// otherwise each read every byte). Tiles parallelize across nthreads.
static void crc_xxh_tiles_impl(void* dst, const void* src, size_t n,
                               size_t tile, uint32_t* crcs, uint64_t* xxhs,
                               int nthreads) {
  if (n == 0) return;
  if (tile == 0 || tile > n) tile = n;
  const size_t n_tiles = (n + tile - 1) / tile;
  std::atomic<size_t> next{0};
  auto work = [&] {
    for (;;) {
      const size_t i = next.fetch_add(1);
      if (i >= n_tiles) return;
      const size_t off = i * tile;
      const size_t len = (n - off < tile) ? (n - off) : tile;
      hash_tile_dual(
          dst == nullptr ? nullptr : static_cast<char*>(dst) + off,
          static_cast<const char*>(src) + off, len, &crcs[i], &xxhs[i]);
    }
  };
  if (nthreads <= 1 || n_tiles == 1 || n < (8u << 20)) {
    work();
    return;
  }
  const int nt = (static_cast<size_t>(nthreads) < n_tiles)
                     ? nthreads
                     : static_cast<int>(n_tiles);
  std::vector<std::thread> threads;
  threads.reserve(nt);
  for (int t = 0; t < nt; ++t) threads.emplace_back(work);
  for (auto& t : threads) t.join();
}

void ts_crc_xxh_tiles(const void* src, size_t n, size_t tile, uint32_t* crcs,
                      uint64_t* xxhs, int nthreads) {
  crc_xxh_tiles_impl(nullptr, src, n, tile, crcs, xxhs, nthreads);
}

void ts_memcpy_crc_xxh_tiles(void* dst, const void* src, size_t n, size_t tile,
                             uint32_t* crcs, uint64_t* xxhs, int nthreads) {
  crc_xxh_tiles_impl(dst, src, n, tile, crcs, xxhs, nthreads);
}

uint32_t ts_crc32c_combine(uint32_t crc1, uint32_t crc2, uint64_t len2) {
  (void)kShiftInit;
  if (len2 == 0) return crc1;
  for (int k = 0; len2; ++k, len2 >>= 1)
    if (len2 & 1) crc1 = gf2_matrix_times(kShiftMat[k], crc1);
  return crc1 ^ crc2;
}

// ---------------------------------------------------------------------------
// Dtype-aware fused tile compression.
//
// The engine's staging hot path already makes one fused memory pass per
// tile (clone + CRC32C + XXH64 above). On network-bound destinations
// (cloud, virtio, the write-back tier's remote drain) the storage pipe —
// not the host — is the ceiling, so a codec stage rides the same pass:
// a byte-shuffle filter keyed on dtype element size (bf16/f32/f64
// exponent bytes group into near-constant planes; fp8/int8 skip the
// filter) followed by LZ4 block compression, per checksum tile, so the
// restore path keeps tile-grain random access. The implementation is
// self-contained (the container ships no lz4/zstd library): a greedy
// hash-chain LZ4 block encoder and a bounds-checked decoder, both
// producing/consuming the standard LZ4 block format. Determinism is
// load-bearing: incremental dedup and salvage-resume compare hashes of
// the COMPRESSED bytes, so equal input must always yield equal output
// (fixed table size, greedy matching, no threads inside one tile).

static const size_t kLz4TableBits = 13;
static const size_t kLz4TableSize = 1u << kLz4TableBits;

static inline uint32_t lz4_read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static inline uint32_t lz4_hash(uint32_t v) {
  return (v * 2654435761u) >> (32 - kLz4TableBits);
}

// Compress src[0..n) into dst[0..cap) (standard LZ4 block format).
// Returns the compressed size, or 0 when the output would reach ``cap``
// (caller stores the tile raw). ``table`` must hold kLz4TableSize
// uint32 slots; it is reset here (one memset per tile, reused across a
// thread's tiles).
static size_t lz4_compress_block(const uint8_t* src, size_t n, uint8_t* dst,
                                 size_t cap, uint32_t* table) {
  if (n == 0 || cap == 0) return 0;
  std::memset(table, 0, kLz4TableSize * sizeof(uint32_t));
  const uint8_t* ip = src;
  const uint8_t* anchor = src;
  const uint8_t* const iend = src + n;
  // Spec: the last match must start >= 12 bytes before the end, and the
  // last 5 bytes are always literals.
  const uint8_t* const mflimit = (n > 12) ? iend - 12 : src;
  const uint8_t* const matchlimit = iend - 5;
  uint8_t* op = dst;
  uint8_t* const oend = dst + cap;

  while (ip < mflimit) {
    const uint32_t v = lz4_read32(ip);
    const uint32_t h = lz4_hash(v);
    const uint8_t* ref = src + table[h];
    table[h] = static_cast<uint32_t>(ip - src);
    if (ref >= ip || static_cast<size_t>(ip - ref) > 65535 ||
        lz4_read32(ref) != v) {
      ++ip;
      continue;
    }
    // Extend the match forward.
    size_t mlen = 4;
    while (ip + mlen < matchlimit && ip[mlen] == ref[mlen]) ++mlen;
    const size_t litlen = static_cast<size_t>(ip - anchor);
    // Worst-case sequence size: token + litlen extras + literals +
    // offset + matchlen extras.
    const size_t need = 1 + litlen / 255 + 1 + litlen + 2 + mlen / 255 + 1;
    if (static_cast<size_t>(oend - op) < need) return 0;
    uint8_t* token = op++;
    if (litlen >= 15) {
      *token = 15 << 4;
      size_t rest = litlen - 15;
      while (rest >= 255) {
        *op++ = 255;
        rest -= 255;
      }
      *op++ = static_cast<uint8_t>(rest);
    } else {
      *token = static_cast<uint8_t>(litlen << 4);
    }
    std::memcpy(op, anchor, litlen);
    op += litlen;
    const size_t offset = static_cast<size_t>(ip - ref);
    *op++ = static_cast<uint8_t>(offset & 0xff);
    *op++ = static_cast<uint8_t>(offset >> 8);
    size_t mcode = mlen - 4;
    if (mcode >= 15) {
      *token |= 15;
      mcode -= 15;
      while (mcode >= 255) {
        *op++ = 255;
        mcode -= 255;
      }
      *op++ = static_cast<uint8_t>(mcode);
    } else {
      *token |= static_cast<uint8_t>(mcode);
    }
    ip += mlen;
    anchor = ip;
    if (ip < mflimit) {
      // Seed the table at the match tail so back-to-back matches chain.
      table[lz4_hash(lz4_read32(ip - 2))] =
          static_cast<uint32_t>(ip - 2 - src);
    }
  }
  // Final literals-only sequence.
  const size_t litlen = static_cast<size_t>(iend - anchor);
  const size_t need = 1 + litlen / 255 + 1 + litlen;
  if (static_cast<size_t>(oend - op) < need) return 0;
  uint8_t* token = op++;
  if (litlen >= 15) {
    *token = 15 << 4;
    size_t rest = litlen - 15;
    while (rest >= 255) {
      *op++ = 255;
      rest -= 255;
    }
    *op++ = static_cast<uint8_t>(rest);
  } else {
    *token = static_cast<uint8_t>(litlen << 4);
  }
  std::memcpy(op, anchor, litlen);
  op += litlen;
  return static_cast<size_t>(op - dst);
}

// Bounds-checked LZ4 block decode. Returns decompressed size or -1 on
// any malformed input (scrub catches bit-rot by CRC first; this guard
// is for defense in depth — corrupt input must never write out of
// bounds or loop forever).
static int64_t lz4_decompress_block(const uint8_t* src, size_t n,
                                    uint8_t* dst, size_t cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + cap;
  while (ip < iend) {
    const uint8_t token = *ip++;
    size_t litlen = token >> 4;
    if (litlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        litlen += b;
      } while (b == 255);
    }
    if (litlen > static_cast<size_t>(iend - ip) ||
        litlen > static_cast<size_t>(oend - op))
      return -1;
    std::memcpy(op, ip, litlen);
    op += litlen;
    ip += litlen;
    if (ip >= iend) break;  // last sequence carries no match
    if (iend - ip < 2) return -1;
    const size_t offset =
        static_cast<size_t>(ip[0]) | (static_cast<size_t>(ip[1]) << 8);
    ip += 2;
    if (offset == 0 || offset > static_cast<size_t>(op - dst)) return -1;
    size_t mlen = token & 15;
    if (mlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    mlen += 4;
    if (mlen > static_cast<size_t>(oend - op)) return -1;
    const uint8_t* match = op - offset;
    if (offset >= mlen) {
      std::memcpy(op, match, mlen);
    } else {
      // Overlapping copy: forward byte order replicates the window
      // (RLE-style matches), exactly per the format.
      for (size_t i = 0; i < mlen; ++i) op[i] = match[i];
    }
    op += mlen;
  }
  return static_cast<int64_t>(op - dst);
}

// Byte-shuffle filter: split ``n`` bytes of ``elem``-sized values into
// ``elem`` byte planes (plane j = bytes j, j+elem, j+2*elem, ...). For
// float dtypes the exponent/sign bytes of nearby values are near
// constant, so their plane becomes long runs LZ4 folds away. A non-
// multiple tail rides raw after the planes.
static void byte_shuffle(const uint8_t* src, uint8_t* dst, size_t n,
                         int elem) {
  if (elem <= 1) {
    std::memcpy(dst, src, n);
    return;
  }
  const size_t ne = n / static_cast<size_t>(elem);
  for (int j = 0; j < elem; ++j) {
    uint8_t* d = dst + static_cast<size_t>(j) * ne;
    const uint8_t* s = src + j;
    for (size_t i = 0; i < ne; ++i) d[i] = s[i * elem];
  }
  const size_t body = ne * static_cast<size_t>(elem);
  std::memcpy(dst + body, src + body, n - body);
}

static void byte_unshuffle(const uint8_t* src, uint8_t* dst, size_t n,
                           int elem) {
  if (elem <= 1) {
    std::memcpy(dst, src, n);
    return;
  }
  const size_t ne = n / static_cast<size_t>(elem);
  for (int j = 0; j < elem; ++j) {
    const uint8_t* s = src + static_cast<size_t>(j) * ne;
    uint8_t* d = dst + j;
    for (size_t i = 0; i < ne; ++i) d[i * elem] = s[i];
  }
  const size_t body = ne * static_cast<size_t>(elem);
  std::memcpy(dst + body, src + body, n - body);
}

// Raw single-buffer entry points (unit tests, the Python policy's codec
// micro-benchmark). ``elem`` <= 1 skips the shuffle filter.
int64_t ts_lz4_compress(const void* src, size_t n, void* dst, size_t cap,
                        int elem) {
  std::vector<uint32_t> table(kLz4TableSize);
  const uint8_t* in = static_cast<const uint8_t*>(src);
  std::vector<uint8_t> shuffled;
  if (elem > 1 && n > 0) {
    shuffled.resize(n);
    byte_shuffle(in, shuffled.data(), n, elem);
    in = shuffled.data();
  }
  const size_t got = lz4_compress_block(in, n, static_cast<uint8_t*>(dst),
                                        cap, table.data());
  return got == 0 ? -1 : static_cast<int64_t>(got);
}

int64_t ts_lz4_decompress(const void* src, size_t n, void* dst, size_t cap,
                          int elem) {
  if (elem > 1 && cap > 0) {
    std::vector<uint8_t> shuffled(cap);
    const int64_t got = lz4_decompress_block(
        static_cast<const uint8_t*>(src), n, shuffled.data(), cap);
    if (got < 0) return got;
    byte_unshuffle(shuffled.data(), static_cast<uint8_t*>(dst),
                   static_cast<size_t>(got), elem);
    return got;
  }
  return lz4_decompress_block(static_cast<const uint8_t*>(src), n,
                              static_cast<uint8_t*>(dst), cap);
}

// Per-tile output slot: worst-case LZ4 expansion plus headroom, rounded
// so slots stay 64-byte aligned. The Python side sizes the destination
// buffer with ts_compress_bound (same formula — one definition each
// side of the FFI, asserted equal by the bindings at load time).
static size_t lz4_slot_stride(size_t tile) {
  const size_t bound = tile + tile / 255 + 64;
  return (bound + 63) & ~static_cast<size_t>(63);
}

int64_t ts_compress_bound(size_t n, size_t tile) {
  if (n == 0) return 0;
  if (tile == 0 || tile > n) tile = n;
  const size_t n_tiles = (n + tile - 1) / tile;
  return static_cast<int64_t>(n_tiles * lz4_slot_stride(tile));
}

// memmove + fused dual hash used by the compaction pass below: blocks
// stay cache-hot between the move and the two hash lanes, and forward
// block order makes the leftward overlapping move safe.
static void movehash_tile(uint8_t* dst, const uint8_t* src, size_t len,
                          uint32_t* crc_out, uint64_t* xxh_out,
                          int want_xxh) {
  const size_t kBlock = 256u << 10;  // multiple of the 32-byte stripe
  uint32_t crc = 0;
  Xxh64State s(0);
  size_t done = 0;
  while (done < len) {
    const size_t blk = (len - done < kBlock) ? (len - done) : kBlock;
    if (dst != src) std::memmove(dst + done, src + done, blk);
    crc = ts_crc32c(dst + done, blk, crc);
    if (want_xxh) {
      if (done + blk < len) {
        xxh_consume_stripes(s, reinterpret_cast<const char*>(dst + done),
                            blk);
      } else {
        const size_t c = xxh_consume_stripes(
            s, reinterpret_cast<const char*>(dst + done), blk);
        *xxh_out = xxh_finalize(
            s, 0, reinterpret_cast<const char*>(dst + done) + c, blk - c);
      }
    }
    done += blk;
  }
  if (want_xxh && len == 0)
    *xxh_out = xxh_finalize(s, 0, reinterpret_cast<const char*>(dst), 0);
  *crc_out = crc;
}

// Fused per-tile shuffle + LZ4 + dual hash over the COMPRESSED bytes —
// the compression analog of ts_memcpy_crc_xxh_tiles. Tiles compress in
// parallel into per-tile slots of ``dst`` (cap from ts_compress_bound),
// then one sequential compaction pass packs them contiguously while
// computing each tile's CRC32C (+ XXH64 when want_xxh) of the stored
// bytes — the values the manifest, the journal's salvage evidence and
// the upload journal all record, so the dual-hash rule holds unchanged
// over compressed blobs. A tile whose LZ4 output would not SHRINK it is
// stored raw (comp_size == raw tile size — the unambiguous marker the
// decoder keys on, since a stored LZ4 stream is always strictly
// smaller). Returns the total compressed size.
int64_t ts_compress_tiles(const void* src_, size_t n, size_t tile, int elem,
                          void* dst_, size_t dst_cap, int64_t* comp_sizes,
                          uint32_t* crcs, uint64_t* xxhs, int want_xxh,
                          int nthreads) {
  if (n == 0) return 0;
  if (tile == 0 || tile > n) tile = n;
  const size_t n_tiles = (n + tile - 1) / tile;
  const size_t stride = lz4_slot_stride(tile);
  if (dst_cap < n_tiles * stride) return -1;
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  const uint8_t* src = static_cast<const uint8_t*>(src_);
  uint8_t* dst = static_cast<uint8_t*>(dst_);
  std::atomic<size_t> next{0};
  auto work = [&] {
    std::vector<uint32_t> table(kLz4TableSize);
    std::vector<uint8_t> shuffled;
    if (elem > 1) shuffled.resize(tile);
    for (;;) {
      const size_t i = next.fetch_add(1);
      if (i >= n_tiles) return;
      const size_t off = i * tile;
      const size_t len = (n - off < tile) ? (n - off) : tile;
      const uint8_t* in = src + off;
      if (elem > 1) {
        byte_shuffle(in, shuffled.data(), len, elem);
        in = shuffled.data();
      }
      uint8_t* slot = dst + i * stride;
      // Cap at len - 1: output must be strictly smaller than the input
      // or the tile stores raw (the size-equality marker must stay
      // unambiguous).
      const size_t got =
          lz4_compress_block(in, len, slot, len > 0 ? len - 1 : 0,
                             table.data());
      if (got == 0) {
        std::memcpy(slot, src + off, len);  // raw: ORIGINAL bytes
        comp_sizes[i] = static_cast<int64_t>(len);
      } else {
        comp_sizes[i] = static_cast<int64_t>(got);
      }
    }
  };
  if (nthreads <= 1 || n_tiles == 1 || n < (8u << 20)) {
    work();
  } else {
    const int nt = (static_cast<size_t>(nthreads) < n_tiles)
                       ? nthreads
                       : static_cast<int>(n_tiles);
    std::vector<std::thread> threads;
    threads.reserve(nt);
    for (int t = 0; t < nt; ++t) threads.emplace_back(work);
    for (auto& t : threads) t.join();
  }
  // Compaction + fused hash, strictly left-to-right (each tile's packed
  // offset is <= its slot offset, so the overlapping move is leftward).
  size_t out = 0;
  for (size_t i = 0; i < n_tiles; ++i) {
    const size_t len = static_cast<size_t>(comp_sizes[i]);
    uint64_t xxh = 0;
    movehash_tile(dst + out, dst + i * stride, len, &crcs[i], &xxh,
                  want_xxh);
    if (want_xxh) xxhs[i] = xxh;
    out += len;
  }
  return static_cast<int64_t>(out);
}

// Parallel tile decompress: the restore-side counterpart. ``src`` holds
// the concatenated compressed tiles (sizes in ``comp_sizes``); each
// decodes (LZ4 + unshuffle, or a raw copy when comp == raw size) into
// its row range of ``dst``. Returns total_raw, or -1 on malformed
// input/size mismatch (the caller surfaces a checksum-style error; the
// CRC over stored bytes has already vouched for transport integrity).
int64_t ts_decompress_tiles(const void* src_, size_t src_n,
                            const int64_t* comp_sizes, size_t n_tiles,
                            size_t tile_raw, size_t total_raw, void* dst_,
                            int elem, int nthreads) {
  if (n_tiles == 0) return total_raw == 0 ? 0 : -1;
  if (tile_raw == 0) tile_raw = total_raw;
  const uint8_t* src = static_cast<const uint8_t*>(src_);
  uint8_t* dst = static_cast<uint8_t*>(dst_);
  std::vector<size_t> offsets(n_tiles);
  size_t off = 0;
  for (size_t i = 0; i < n_tiles; ++i) {
    offsets[i] = off;
    if (comp_sizes[i] < 0) return -1;
    off += static_cast<size_t>(comp_sizes[i]);
  }
  if (off != src_n) return -1;
  if (nthreads < 1) nthreads = 1;
  if (nthreads > 16) nthreads = 16;
  std::atomic<size_t> next{0};
  std::atomic<int> bad{0};
  auto work = [&] {
    std::vector<uint8_t> scratch;
    for (;;) {
      const size_t i = next.fetch_add(1);
      if (i >= n_tiles || bad.load()) return;
      const size_t raw_off = i * tile_raw;
      if (raw_off >= total_raw) {
        bad.store(1);
        return;
      }
      const size_t raw_len =
          (total_raw - raw_off < tile_raw) ? (total_raw - raw_off) : tile_raw;
      const uint8_t* in = src + offsets[i];
      const size_t clen = static_cast<size_t>(comp_sizes[i]);
      uint8_t* out = dst + raw_off;
      if (clen == raw_len) {
        std::memcpy(out, in, raw_len);  // stored raw
        continue;
      }
      if (clen > raw_len) {
        bad.store(1);
        return;
      }
      if (elem > 1) {
        if (scratch.size() < raw_len) scratch.resize(raw_len);
        const int64_t got =
            lz4_decompress_block(in, clen, scratch.data(), raw_len);
        if (got != static_cast<int64_t>(raw_len)) {
          bad.store(1);
          return;
        }
        byte_unshuffle(scratch.data(), out, raw_len, elem);
      } else {
        const int64_t got = lz4_decompress_block(in, clen, out, raw_len);
        if (got != static_cast<int64_t>(raw_len)) {
          bad.store(1);
          return;
        }
      }
    }
  };
  if (nthreads <= 1 || n_tiles == 1 || total_raw < (8u << 20)) {
    work();
  } else {
    const int nt = (static_cast<size_t>(nthreads) < n_tiles)
                       ? nthreads
                       : static_cast<int>(n_tiles);
    std::vector<std::thread> threads;
    threads.reserve(nt);
    for (int t = 0; t < nt; ++t) threads.emplace_back(work);
    for (auto& t : threads) t.join();
  }
  if (bad.load()) return -1;
  return static_cast<int64_t>(total_raw);
}

}  // extern "C"
