// Native helpers for tpusnap's hot I/O paths.
//
// The reference gets GIL-released native copies/writes for free through
// torch (TorchScripted tensor copies, torch's file I/O —
// /root/reference/torchsnapshot/io_preparers/tensor.py:351-358). JAX has no
// such runtime, so this tiny C++ library supplies the equivalents:
//
//   ts_write_file    — whole-buffer file write (single open/write loop, no
//                      Python-level chunking, GIL released by the caller)
//   ts_write_file_direct — O_DIRECT double-buffered write: bypasses the
//                      page cache (whose dirty-page writeback throttling
//                      caps buffered writes well below device speed on
//                      large checkpoint streams); memcpy into an aligned
//                      bounce buffer overlaps with the in-flight pwrite
//   ts_read_range    — positional ranged read into a caller buffer
//   ts_memcpy_par    — multi-threaded memcpy for staging large host buffers
//   ts_crc32c        — CRC32C (Castagnoli, software slice-by-8) for
//                      optional integrity checksums
//
// Built on demand by tpusnap/_native/__init__.py with:
//   g++ -O3 -shared -fPIC -pthread -o libtpusnap_native.so tpusnap_native.cpp

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

#ifndef O_DIRECT
#define O_DIRECT 0
#endif

extern "C" {

// Returns 0 on success, -errno on failure.
int ts_write_file(const char* path, const void* buf, size_t n) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  const char* p = static_cast<const char*>(buf);
  size_t remaining = n;
  while (remaining > 0) {
    ssize_t written = ::write(fd, p, remaining);
    if (written < 0) {
      if (errno == EINTR) continue;
      int err = errno;
      ::close(fd);
      return -err;
    }
    p += written;
    remaining -= static_cast<size_t>(written);
  }
  if (::close(fd) < 0) return -errno;
  return 0;
}

// O_DIRECT double-buffered whole-file write. Returns 0 on success or
// -errno. Falls back to the buffered path when O_DIRECT open fails (tmpfs,
// overlayfs, unsupported filesystems) or for small buffers where the setup
// cost outweighs the page-cache bypass.
int ts_write_file_direct(const char* path, const void* buf, size_t n) {
  static const size_t kAlign = 4096;
  static const size_t kChunk = 8u << 20;  // 8 MiB: past the point where
                                          // direct-IO throughput saturates
  if (O_DIRECT == 0 || n < (4u << 20)) return ts_write_file(path, buf, n);
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC | O_DIRECT, 0644);
  if (fd < 0) return ts_write_file(path, buf, n);
#ifdef __linux__
  // Reserve the full extent up front: without this, concurrent direct
  // writers allocate blocks chunk-by-chunk and interleave their extents,
  // which turns later sequential restore reads into seek storms.
  // posix_fallocate returns the error number directly (not via errno).
  // ENOSPC must fail now: letting the write proceed surfaces the failure
  // later and then masks it behind a full buffered rewrite of a possibly
  // multi-GB file. Other errors (EOPNOTSUPP on odd filesystems) are
  // non-fatal — the writes below allocate blocks themselves.
  int fa = ::posix_fallocate(fd, 0, static_cast<off_t>(n));
  if (fa == ENOSPC) {
    ::close(fd);
    ::unlink(path);
    return -ENOSPC;
  }
#endif

  const size_t aligned_n = n & ~(kAlign - 1);
  void* bounce[2] = {nullptr, nullptr};
  if (::posix_memalign(&bounce[0], kAlign, kChunk) != 0 ||
      ::posix_memalign(&bounce[1], kAlign, kChunk) != 0) {
    std::free(bounce[0]);
    std::free(bounce[1]);
    ::close(fd);
    return ts_write_file(path, buf, n);
  }

  const char* src = static_cast<const char*>(buf);
  std::atomic<int> werr{0};
  std::thread writer;
  size_t off = 0;
  int idx = 0;
  while (off < aligned_n) {
    const size_t len = (aligned_n - off < kChunk) ? (aligned_n - off) : kChunk;
    std::memcpy(bounce[idx], src + off, len);  // overlaps the prior pwrite
    if (writer.joinable()) writer.join();
    if (werr.load()) break;
    char* wbuf = static_cast<char*>(bounce[idx]);
    const size_t woff = off;
    writer = std::thread([fd, wbuf, len, woff, &werr] {
      size_t pos = 0;
      while (pos < len) {
        ssize_t w = ::pwrite(fd, wbuf + pos, len - pos, woff + pos);
        if (w < 0) {
          if (errno == EINTR) continue;
          werr.store(errno);
          return;
        }
        pos += static_cast<size_t>(w);
      }
    });
    off += len;
    idx ^= 1;
  }
  if (writer.joinable()) writer.join();
  std::free(bounce[0]);
  std::free(bounce[1]);
  ::close(fd);
  if (werr.load() == ENOSPC) {
    // A full disk won't be cured by a buffered rewrite of the same bytes
    // — fail now instead of doubling the multi-GB I/O on the error path
    // (reachable when posix_fallocate was unsupported, e.g. FUSE).
    ::unlink(path);
    return -ENOSPC;
  }
  if (werr.load()) {
    // Write-phase failure. This covers filesystems/devices that accept
    // O_DIRECT at open() but reject the I/O (logical block size > kAlign,
    // FUSE quirks) and short writes that left the continuation offset
    // unaligned (EINVAL masking the true cause, e.g. a filling disk). A
    // buffered rewrite either succeeds or reports the real errno; when it
    // fails too (disk genuinely full), don't leave a partial blob behind.
    int rc = ts_write_file(path, buf, n);
    if (rc != 0) ::unlink(path);
    return rc;
  }

  // Unaligned tail: a buffered positional write (offset need not be
  // block-aligned once the O_DIRECT fd is closed).
  if (aligned_n < n) {
    int tfd = ::open(path, O_WRONLY);
    if (tfd < 0) return -errno;
    const char* p = src + aligned_n;
    size_t remaining = n - aligned_n;
    off_t pos = static_cast<off_t>(aligned_n);
    while (remaining > 0) {
      ssize_t w = ::pwrite(tfd, p, remaining, pos);
      if (w < 0) {
        if (errno == EINTR) continue;
        int err = errno;
        ::close(tfd);
        return -err;
      }
      p += w;
      pos += w;
      remaining -= static_cast<size_t>(w);
    }
    if (::close(tfd) < 0) return -errno;
  }
  return 0;
}

// Positional ranged read. Returns bytes read (>=0) or -errno.
int64_t ts_read_range(const char* path, void* out, int64_t offset, size_t n) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
#ifdef POSIX_FADV_SEQUENTIAL
  // Large sequential consumers: widen kernel readahead (the default
  // window caps buffered cold reads well below device speed).
  ::posix_fadvise(fd, offset, n, POSIX_FADV_SEQUENTIAL);
  ::posix_fadvise(fd, offset, n, POSIX_FADV_WILLNEED);
#endif
  char* p = static_cast<char*>(out);
  size_t remaining = n;
  int64_t pos = offset;
  while (remaining > 0) {
    ssize_t got = ::pread(fd, p, remaining, pos);
    if (got < 0) {
      if (errno == EINTR) continue;
      int err = errno;
      ::close(fd);
      return -err;
    }
    if (got == 0) break;  // EOF
    p += got;
    pos += got;
    remaining -= static_cast<size_t>(got);
  }
  ::close(fd);
  return static_cast<int64_t>(n - remaining);
}

// O_DIRECT double-buffered ranged read: bypasses the page cache, whose
// bounded readahead window caps cold buffered reads far below device
// speed. The requested range is covered by aligned block reads through a
// bounce buffer (memcpy out overlaps the next in-flight pread); any
// misaligned head/tail falls back to a buffered pread. Returns bytes
// read or -errno; falls back to ts_read_range when O_DIRECT open fails.
int64_t ts_read_range_direct(const char* path, void* out, int64_t offset,
                             size_t n) {
  static const int64_t kAlign = 4096;
  static const size_t kChunk = 8u << 20;
  if (O_DIRECT == 0 || n < (4u << 20))
    return ts_read_range(path, out, offset, n);
  int fd = ::open(path, O_RDONLY | O_DIRECT, 0);
  if (fd < 0) return ts_read_range(path, out, offset, n);

  struct stat st;
  if (::fstat(fd, &st) != 0) {
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }
  const int64_t file_size = st.st_size;
  const int64_t req_end =
      (offset + static_cast<int64_t>(n) < file_size)
          ? offset + static_cast<int64_t>(n)
          : file_size;
  if (req_end <= offset) {
    ::close(fd);
    return 0;
  }
  // Aligned window fully covered by whole blocks inside the file. When
  // the request starts inside the file's final partial block the window
  // is empty (a_end < a_start) — nothing direct-readable, use buffered.
  const int64_t a_start = (offset + kAlign - 1) & ~(kAlign - 1);
  const int64_t a_end = req_end & ~(kAlign - 1);
  if (a_end <= a_start) {
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }

  void* bounce[2] = {nullptr, nullptr};
  if (::posix_memalign(&bounce[0], kAlign, kChunk) != 0 ||
      ::posix_memalign(&bounce[1], kAlign, kChunk) != 0) {
    std::free(bounce[0]);
    std::free(bounce[1]);
    ::close(fd);
    return ts_read_range(path, out, offset, n);
  }

  char* dst = static_cast<char*>(out);
  // Per-buffer results: chunk i writes slot i&1, so the two in-flight
  // chunks never share a result slot. <0: -errno; >=0: bytes read.
  std::atomic<int64_t> rres[2] = {{0}, {0}};
  std::thread reader;
  int err = 0;
  int64_t pos = a_start;
  int idx = 0;
  int64_t pending_len = 0;  // length of the chunk the reader is filling
  int pending_idx = 0;
  int64_t pending_pos = 0;
  bool short_read = false;
  while (pos < a_end && !short_read) {
    const int64_t len =
        (a_end - pos < static_cast<int64_t>(kChunk)) ? (a_end - pos)
                                                     : static_cast<int64_t>(kChunk);
    char* buf = static_cast<char*>(bounce[idx]);
    std::atomic<int64_t>* slot = &rres[idx];
    // Kick off the pread for this chunk, then (on the main thread) copy
    // the PREVIOUS chunk out while it is in flight.
    std::thread t([fd, buf, len, pos, slot] {
      int64_t done = 0;
      while (done < len) {
        ssize_t got = ::pread(fd, buf + done, len - done, pos + done);
        if (got < 0) {
          if (errno == EINTR) continue;
          slot->store(-static_cast<int64_t>(errno));
          return;
        }
        if (got == 0) break;  // EOF (file shrank under us)
        done += got;
      }
      slot->store(done);
    });
    if (reader.joinable()) {
      reader.join();
      const int64_t got = rres[pending_idx].load();
      if (got < 0) {
        err = static_cast<int>(-got);
        t.join();
        reader = std::thread();
        break;
      }
      std::memcpy(dst + (pending_pos - offset), bounce[pending_idx],
                  static_cast<size_t>(got));
      if (got < pending_len) short_read = true;
    }
    reader = std::move(t);
    pending_len = len;
    pending_idx = idx;
    pending_pos = pos;
    pos += len;
    idx ^= 1;
  }
  if (reader.joinable()) {
    reader.join();
    const int64_t got = rres[pending_idx].load();
    if (got < 0) {
      if (err == 0) err = static_cast<int>(-got);
    } else if (err == 0 && !short_read) {
      std::memcpy(dst + (pending_pos - offset), bounce[pending_idx],
                  static_cast<size_t>(got));
      if (got < pending_len) short_read = true;
    }
  }
  std::free(bounce[0]);
  std::free(bounce[1]);
  ::close(fd);
  if (err != 0) return ts_read_range(path, out, offset, n);

  // Misaligned head ([offset, a_start)) and tail ([a_end, req_end)) via
  // buffered preads; also re-read everything after an unexpected short
  // direct read through the buffered path.
  if (short_read) return ts_read_range(path, out, offset, n);
  int64_t total = a_end - a_start;
  if (a_start > offset) {
    int64_t head = ts_read_range(path, dst, offset, a_start - offset);
    if (head < 0) return head;
    total += head;
  }
  if (req_end > a_end) {
    int64_t tail = ts_read_range(path, dst + (a_end - offset), a_end,
                                 static_cast<size_t>(req_end - a_end));
    if (tail < 0) return tail;
    total += tail;
  }
  return total;
}

// Multi-threaded memcpy; nthreads <= 1 degrades to plain memcpy.
void ts_memcpy_par(void* dst, const void* src, size_t n, int nthreads) {
  if (nthreads <= 1 || n < (8u << 20)) {
    std::memcpy(dst, src, n);
    return;
  }
  size_t chunk = (n + nthreads - 1) / nthreads;
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int i = 0; i < nthreads; ++i) {
    size_t off = static_cast<size_t>(i) * chunk;
    if (off >= n) break;
    size_t len = (off + chunk <= n) ? chunk : (n - off);
    threads.emplace_back([=] {
      std::memcpy(static_cast<char*>(dst) + off,
                  static_cast<const char*>(src) + off, len);
    });
  }
  for (auto& t : threads) t.join();
}

static uint32_t kCrcTable[8][256];
static bool kCrcInit = [] {
  const uint32_t poly = 0x82f63b78u;  // CRC32C (Castagnoli), reflected
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kCrcTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int s = 1; s < 8; ++s)
      kCrcTable[s][i] =
          (kCrcTable[s - 1][i] >> 8) ^ kCrcTable[0][kCrcTable[s - 1][i] & 0xff];
  return true;
}();

uint32_t ts_crc32c(const void* buf, size_t n, uint32_t seed) {
  (void)kCrcInit;
  uint32_t crc = ~seed;
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n >= 8) {
    crc ^= static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
    crc = kCrcTable[7][crc & 0xff] ^ kCrcTable[6][(crc >> 8) & 0xff] ^
          kCrcTable[5][(crc >> 16) & 0xff] ^ kCrcTable[4][crc >> 24] ^
          kCrcTable[3][p[4]] ^ kCrcTable[2][p[5]] ^ kCrcTable[1][p[6]] ^
          kCrcTable[0][p[7]];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *p++) & 0xff];
  return ~crc;
}

}  // extern "C"
