// Native helpers for tpusnap's hot I/O paths.
//
// The reference gets GIL-released native copies/writes for free through
// torch (TorchScripted tensor copies, torch's file I/O —
// /root/reference/torchsnapshot/io_preparers/tensor.py:351-358). JAX has no
// such runtime, so this tiny C++ library supplies the equivalents:
//
//   ts_write_file    — whole-buffer file write (single open/write loop, no
//                      Python-level chunking, GIL released by the caller)
//   ts_read_range    — positional ranged read into a caller buffer
//   ts_memcpy_par    — multi-threaded memcpy for staging large host buffers
//   ts_crc32c        — CRC32C (Castagnoli, software slice-by-8) for
//                      optional integrity checksums
//
// Built on demand by tpusnap/_native/__init__.py with:
//   g++ -O3 -shared -fPIC -pthread -o libtpusnap_native.so tpusnap_native.cpp

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <thread>
#include <unistd.h>
#include <vector>

extern "C" {

// Returns 0 on success, -errno on failure.
int ts_write_file(const char* path, const void* buf, size_t n) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
  if (fd < 0) return -errno;
  const char* p = static_cast<const char*>(buf);
  size_t remaining = n;
  while (remaining > 0) {
    ssize_t written = ::write(fd, p, remaining);
    if (written < 0) {
      if (errno == EINTR) continue;
      int err = errno;
      ::close(fd);
      return -err;
    }
    p += written;
    remaining -= static_cast<size_t>(written);
  }
  if (::close(fd) < 0) return -errno;
  return 0;
}

// Positional ranged read. Returns bytes read (>=0) or -errno.
int64_t ts_read_range(const char* path, void* out, int64_t offset, size_t n) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -errno;
  char* p = static_cast<char*>(out);
  size_t remaining = n;
  int64_t pos = offset;
  while (remaining > 0) {
    ssize_t got = ::pread(fd, p, remaining, pos);
    if (got < 0) {
      if (errno == EINTR) continue;
      int err = errno;
      ::close(fd);
      return -err;
    }
    if (got == 0) break;  // EOF
    p += got;
    pos += got;
    remaining -= static_cast<size_t>(got);
  }
  ::close(fd);
  return static_cast<int64_t>(n - remaining);
}

// Multi-threaded memcpy; nthreads <= 1 degrades to plain memcpy.
void ts_memcpy_par(void* dst, const void* src, size_t n, int nthreads) {
  if (nthreads <= 1 || n < (8u << 20)) {
    std::memcpy(dst, src, n);
    return;
  }
  size_t chunk = (n + nthreads - 1) / nthreads;
  std::vector<std::thread> threads;
  threads.reserve(nthreads);
  for (int i = 0; i < nthreads; ++i) {
    size_t off = static_cast<size_t>(i) * chunk;
    if (off >= n) break;
    size_t len = (off + chunk <= n) ? chunk : (n - off);
    threads.emplace_back([=] {
      std::memcpy(static_cast<char*>(dst) + off,
                  static_cast<const char*>(src) + off, len);
    });
  }
  for (auto& t : threads) t.join();
}

static uint32_t kCrcTable[8][256];
static bool kCrcInit = [] {
  const uint32_t poly = 0x82f63b78u;  // CRC32C (Castagnoli), reflected
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t crc = i;
    for (int j = 0; j < 8; ++j) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kCrcTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int s = 1; s < 8; ++s)
      kCrcTable[s][i] =
          (kCrcTable[s - 1][i] >> 8) ^ kCrcTable[0][kCrcTable[s - 1][i] & 0xff];
  return true;
}();

uint32_t ts_crc32c(const void* buf, size_t n, uint32_t seed) {
  (void)kCrcInit;
  uint32_t crc = ~seed;
  const uint8_t* p = static_cast<const uint8_t*>(buf);
  while (n >= 8) {
    crc ^= static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
           (static_cast<uint32_t>(p[2]) << 16) |
           (static_cast<uint32_t>(p[3]) << 24);
    crc = kCrcTable[7][crc & 0xff] ^ kCrcTable[6][(crc >> 8) & 0xff] ^
          kCrcTable[5][(crc >> 16) & 0xff] ^ kCrcTable[4][crc >> 24] ^
          kCrcTable[3][p[4]] ^ kCrcTable[2][p[5]] ^ kCrcTable[1][p[6]] ^
          kCrcTable[0][p[7]];
    p += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *p++) & 0xff];
  return ~crc;
}

}  // extern "C"
