"""ctypes bindings for tpusnap's native C++ helpers, compiled on demand.

The .so is built from src/tpusnap_native.cpp with g++ the first time it is
needed (or when the source is newer than the binary). Every entry point has
a pure-Python fallback, and ``TPUSNAP_DISABLE_NATIVE=1`` forces the
fallbacks — so the library works (slower) without a toolchain.

ctypes releases the GIL around foreign calls, which is the whole point:
file writes, ranged reads, and large memcpys run concurrently with Python
threads, the role torch's native ops play in the reference
(/root/reference/torchsnapshot/io_preparers/tensor.py:351-358).
"""

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "tpusnap_native.cpp")
_SO = os.path.join(_DIR, "libtpusnap_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False
_lock = threading.Lock()


def _build() -> bool:
    # Link to a temp path, then rename into place: the final .so may
    # already be dlopen-mapped (by this or another process), and letting
    # the linker truncate a live mapping corrupts it. os.replace gives the
    # new build a fresh inode, so a subsequent CDLL(_SO) maps the new
    # library instead of returning glibc's cached handle for the old one.
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = [
        "g++",
        "-O3",
        "-march=native",
        "-shared",
        "-fPIC",
        "-pthread",
        "-std=c++17",
        "-o",
        tmp,
        _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except Exception as e:  # toolchain missing/failed: fall back to Python
        logger.warning("tpusnap native build failed (%s); using Python fallbacks", e)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        from ..knobs import is_native_disabled

        if is_native_disabled():
            return None
        stale = not os.path.exists(_SO) or (
            os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
        )
        built = False
        if stale:
            if not _build():
                return None
            built = True
        # A cached .so from an older source revision can pass the mtime
        # check (cp/checkout preserve equal mtimes) yet lack newer symbols.
        # On missing symbols, rebuild once and retry — unless this .so was
        # just built from current source, where a second identical build
        # cannot help and the Python fallbacks are the only option.
        for _ in range(2):
            try:
                lib = ctypes.CDLL(_SO)
            except OSError as e:
                logger.warning("tpusnap native load failed (%s)", e)
                return None
            try:
                _bind(lib)
            except AttributeError as e:
                if built:
                    logger.warning(
                        "tpusnap native .so is missing expected symbols "
                        "(%s); using Python fallbacks",
                        e,
                    )
                    return None
                logger.warning(
                    "tpusnap native .so is missing expected symbols; "
                    "rebuilding"
                )
                if not _build():
                    return None
                built = True
                continue
            _lib = lib
            return _lib
        return None


def _bind(lib: ctypes.CDLL) -> None:
    lib.ts_write_file.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    lib.ts_write_file.restype = ctypes.c_int
    lib.ts_write_file_direct2.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.c_size_t,
    ]
    lib.ts_write_file_direct2.restype = ctypes.c_int
    lib.ts_write_file_auto.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.c_size_t,
        ctypes.c_int,
    ]
    lib.ts_write_file_auto.restype = ctypes.c_int
    lib.ts_read_range.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_size_t,
    ]
    lib.ts_read_range.restype = ctypes.c_int64
    lib.ts_read_range_direct.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_size_t,
    ]
    lib.ts_read_range_direct.restype = ctypes.c_int64
    lib.ts_read_range_direct2.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.c_size_t,
    ]
    lib.ts_read_range_direct2.restype = ctypes.c_int64
    lib.ts_read_range_into_crc.argtypes = [
        ctypes.c_char_p,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.ts_read_range_into_crc.restype = ctypes.c_int64
    lib.ts_memcpy_par.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
    ]
    lib.ts_memcpy_par.restype = None
    lib.ts_memcpy_crc_tiles.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int,
    ]
    lib.ts_memcpy_crc_tiles.restype = None
    lib.ts_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_size_t, ctypes.c_uint32]
    lib.ts_crc32c.restype = ctypes.c_uint32
    lib.ts_xxh64.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_uint64,
    ]
    lib.ts_xxh64.restype = ctypes.c_uint64
    lib.ts_crc_xxh_tiles.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int,
    ]
    lib.ts_crc_xxh_tiles.restype = None
    lib.ts_memcpy_crc_xxh_tiles.argtypes = [
        ctypes.c_void_p,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int,
    ]
    lib.ts_memcpy_crc_xxh_tiles.restype = None
    lib.ts_crc32c_combine.argtypes = [
        ctypes.c_uint32,
        ctypes.c_uint32,
        ctypes.c_uint64,
    ]
    lib.ts_crc32c_combine.restype = ctypes.c_uint32
    lib.ts_lz4_compress.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
    ]
    lib.ts_lz4_compress.restype = ctypes.c_int64
    lib.ts_lz4_decompress.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
    ]
    lib.ts_lz4_decompress.restype = ctypes.c_int64
    lib.ts_compress_bound.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
    lib.ts_compress_bound.restype = ctypes.c_int64
    lib.ts_compress_tiles.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.c_int,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.ts_compress_tiles.restype = ctypes.c_int64
    lib.ts_decompress_tiles.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.ts_decompress_tiles.restype = ctypes.c_int64


def available() -> bool:
    return _load() is not None


def _ptr(buf) -> Tuple[int, np.ndarray]:
    """Raw data pointer of any buffer (incl. read-only), zero-copy.

    Returns (address, keepalive) — the caller must hold ``keepalive`` for
    the duration of the foreign call.
    """
    arr = np.frombuffer(memoryview(buf).cast("B"), dtype=np.uint8)
    return arr.ctypes.data, arr


_MADV_HUGEPAGE = 14
_PAGE = 4096
_libc: Optional[ctypes.CDLL] = None
_libc_failed = False


def advise_hugepages(buf) -> None:
    """Best-effort ``madvise(MADV_HUGEPAGE)`` on a buffer's pages.

    Restores into freshly allocated destinations pay a first-touch
    page-fault per 4 KiB; on hosts with anonymous THP available
    (``transparent_hugepage=madvise``, the common TPU-VM configuration)
    advising large buffers tpusnap allocates itself (read scratch,
    tiled-read/shard destinations, slabs, clones) lets them fault as
    2 MiB pages — ~500x fewer faults on the restore path. Purely
    advisory: on kernels without anon THP (some virtualized guests,
    including this dev host) the call succeeds but changes nothing, and
    any failure (non-Linux, tiny buffer) is silently ignored."""
    global _libc, _libc_failed
    if _libc_failed:
        return
    if _libc is None:
        try:
            lc = ctypes.CDLL(None, use_errno=True)
            lc.madvise.argtypes = [
                ctypes.c_void_p,
                ctypes.c_size_t,
                ctypes.c_int,
            ]
            lc.madvise.restype = ctypes.c_int
            _libc = lc
        except Exception:
            # Only libc/symbol unavailability latches the kill flag;
            # per-buffer oddities below must not disable the advice for
            # the rest of the process.
            _libc_failed = True
            return
    try:
        if isinstance(buf, np.ndarray):
            # ndarray path works for dtypes with no buffer protocol too
            # (bf16/fp8 ml_dtypes arrays reject memoryview()).
            addr, nbytes, keep = buf.ctypes.data, buf.nbytes, buf
        else:
            mv = memoryview(buf)
            nbytes = mv.nbytes
            addr, keep = (0, None) if nbytes == 0 else _ptr(mv)
        if nbytes < (4 << 20):
            return
        start = (addr + _PAGE - 1) & ~(_PAGE - 1)
        end = (addr + nbytes) & ~(_PAGE - 1)
        if end > start:
            _libc.madvise(start, end - start, _MADV_HUGEPAGE)
        del keep
    except Exception:
        return


def empty_advised(shape, dtype) -> np.ndarray:
    """``np.empty`` + ``advise_hugepages``: the allocation for any large
    fresh destination tpusnap creates itself (tiled-read/chunk/shard
    buffers, owning copies)."""
    out = np.empty(shape, dtype=dtype)
    advise_hugepages(out)
    return out


def aligned_empty(nbytes: int, align: int = 4096) -> np.ndarray:
    """Uninitialized uint8 buffer whose data pointer is ``align``-aligned.

    Buffers tpusnap allocates itself (batcher slabs, async-snapshot
    clones, staged copies) are aligned so the O_DIRECT writer can pwrite
    straight from them — the zero-copy branch of ts_write_file_direct2 —
    instead of bouncing every chunk through an aligned copy. Large
    buffers are THP-advised (``advise_hugepages``) before first touch."""
    raw = np.empty(nbytes + align, dtype=np.uint8)
    off = (-raw.ctypes.data) % align
    out = raw[off : off + nbytes]
    advise_hugepages(out)
    return out


def write_file(path: str, buf) -> None:
    """Whole-buffer file write with the GIL released for the full transfer.

    Large buffers go through the O_DIRECT double-buffered writer (page-cache
    writeback throttling caps buffered streams far below device speed on
    multi-GB checkpoints); the native layer falls back to a buffered write
    automatically when the filesystem rejects O_DIRECT."""
    mv = memoryview(buf).cast("B")
    lib = _load()
    if lib is None:
        _write_all(path, mv)
        return
    if mv.nbytes == 0:
        open(path, "wb").close()
        return
    from ..knobs import (
        get_direct_io_chunk_bytes,
        get_direct_io_qd,
        is_direct_io_disabled,
        is_dontcache_disabled,
    )

    ptr, keepalive = _ptr(mv)
    if is_direct_io_disabled():
        rc = lib.ts_write_file(path.encode(), ptr, mv.nbytes)
    else:
        rc = lib.ts_write_file_auto(
            path.encode(),
            ptr,
            mv.nbytes,
            get_direct_io_qd(),
            get_direct_io_chunk_bytes(),
            0 if is_dontcache_disabled() else 1,
        )
    del keepalive
    if rc != 0:
        raise OSError(-rc, os.strerror(-rc), path)


def _write_all(path: str, mv: memoryview) -> None:
    """Unbuffered write loop: a single ``FileIO.write`` maps to one
    write(2), which can be short (near-full disk) and is capped at
    0x7ffff000 bytes on Linux — ignoring its return would silently
    truncate buffers >= 2 GiB."""
    with open(path, "wb", buffering=0) as f:
        pos = 0
        while pos < mv.nbytes:
            written = f.write(mv[pos:])
            if not written:
                raise OSError(f"short write at {pos}/{mv.nbytes}: {path}")
            pos += written


def read_range(path: str, offset: int, n: int, out) -> int:
    """Positional ranged read into ``out`` (writable buffer); returns bytes
    read (short only at EOF). Large ranges go through the O_DIRECT
    double-buffered reader — the page cache's bounded readahead window
    caps cold buffered reads ~10x below device speed — with automatic
    buffered fallback on filesystems without O_DIRECT."""
    mv = memoryview(out).cast("B")
    if mv.readonly:
        raise ValueError("out buffer must be writable")
    if n > mv.nbytes:
        raise ValueError(f"out buffer too small: {mv.nbytes} < {n}")
    lib = _load()
    if lib is None:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(n)
        mv[: len(data)] = data
        return len(data)
    if n == 0:
        return 0
    from ..knobs import (
        get_direct_io_chunk_bytes,
        get_direct_io_qd,
        is_direct_io_disabled,
    )

    # Direct reads only pay off for large streams: many concurrent small
    # direct reads thrash the device queue (each chunk is a synchronous
    # round trip with no readahead) and measurably lose to buffered reads
    # + POSIX_FADV_SEQUENTIAL. 64 MiB is past the crossover on the
    # measured virtio/NVMe configs. Aligned destinations (fs-plugin read
    # buffers are) take the zero-copy pread path inside direct2.
    use_direct = n >= (64 << 20) and not is_direct_io_disabled()
    ptr, keepalive = _ptr(mv)
    if use_direct:
        got = lib.ts_read_range_direct2(
            path.encode(),
            ptr,
            offset,
            n,
            get_direct_io_qd(),
            get_direct_io_chunk_bytes(),
        )
    else:
        got = lib.ts_read_range(path.encode(), ptr, offset, n)
    del keepalive
    if got < 0:
        raise OSError(-got, os.strerror(-got), path)
    return got


def read_range_into(
    path: str, offset: int, n: int, out, want_crc: bool = False
) -> Tuple[int, Optional[int], str]:
    """Ranged read landing directly in ``out`` (the restore target's own
    memory), with the checksum fused into the bounce copy-out.

    Returns ``(bytes_read, crc_or_None, algorithm)``. Compared to
    ``read_range`` + a separate verify + a separate copy, this makes one
    RAM-read + one RAM-write pass per byte total — the difference between
    a CPU-ceiling-bound and a disk-bound restore on few-core hosts."""
    mv = memoryview(out).cast("B")
    if mv.readonly:
        raise ValueError("out buffer must be writable")
    if n > mv.nbytes:
        raise ValueError(f"out buffer too small: {mv.nbytes} < {n}")
    lib = _load()
    if lib is None:
        # readinto the destination directly — the in-place path's whole
        # premise is that no full-size scratch buffer exists.
        got = 0
        with open(path, "rb") as f:
            f.seek(offset)
            while got < n:
                r = f.readinto(mv[got:n])
                if not r:
                    break  # EOF
                got += r
        if want_crc:
            import zlib

            return got, zlib.crc32(mv[:got]), "zlib-crc32"
        return got, None, "zlib-crc32"
    if n == 0:
        return 0, (crc32c(b"") if want_crc else None), "crc32c"
    from ..knobs import (
        get_direct_io_chunk_bytes,
        get_direct_io_qd,
        is_direct_io_disabled,
    )

    ptr, keepalive = _ptr(mv)
    crc_out = ctypes.c_uint32(0)
    if is_direct_io_disabled():
        got = lib.ts_read_range(path.encode(), ptr, offset, n)
        if got >= 0 and want_crc:
            crc_val = lib.ts_crc32c(ptr, got, 0) if got else crc32c(b"")
        else:
            crc_val = None
    else:
        got = lib.ts_read_range_into_crc(
            path.encode(),
            ptr,
            offset,
            n,
            get_direct_io_qd(),
            get_direct_io_chunk_bytes(),
            ctypes.byref(crc_out) if want_crc else None,
        )
        crc_val = crc_out.value if (want_crc and got >= 0) else None
    del keepalive
    if got < 0:
        raise OSError(-got, os.strerror(-got), path)
    return got, crc_val, "crc32c"


def memcpy(dst, src, nthreads: int = 4) -> None:
    """GIL-released (and multi-threaded for large buffers) memcpy."""
    dst_mv = memoryview(dst).cast("B")
    src_mv = memoryview(src).cast("B")
    if dst_mv.readonly:
        raise ValueError("dst must be writable")
    if dst_mv.nbytes != src_mv.nbytes:
        raise ValueError(f"size mismatch: {dst_mv.nbytes} != {src_mv.nbytes}")
    lib = _load()
    if lib is None or dst_mv.nbytes < (1 << 20):
        dst_mv[:] = src_mv
        return
    dst_ptr, dst_keep = _ptr(dst_mv)
    src_ptr, src_keep = _ptr(src_mv)
    lib.ts_memcpy_par(dst_ptr, src_ptr, dst_mv.nbytes, nthreads)
    del dst_keep, src_keep


def memcpy_crc_tiles(dst, src, tile_nbytes: int, nthreads: int = 4) -> list:
    """Copy ``src`` into ``dst`` while computing an independent seed-0
    checksum per ``tile_nbytes`` bytes — ONE memory pass for what would
    otherwise be a hash pass plus a clone pass (the async-snapshot
    staging path). Returns the per-tile checksum values (one entry, the
    whole-buffer value, when ``tile_nbytes`` >= the buffer size).
    Combine with ``crc_combine`` for the whole-blob value."""
    dst_mv = memoryview(dst).cast("B")
    src_mv = memoryview(src).cast("B")
    if dst_mv.readonly:
        raise ValueError("dst must be writable")
    if dst_mv.nbytes != src_mv.nbytes:
        raise ValueError(f"size mismatch: {dst_mv.nbytes} != {src_mv.nbytes}")
    n = src_mv.nbytes
    if n == 0:
        return [crc32c(b"")]
    if tile_nbytes <= 0 or tile_nbytes > n:
        tile_nbytes = n
    n_tiles = (n + tile_nbytes - 1) // tile_nbytes
    lib = _load()
    if lib is None:
        out = []
        for i in range(n_tiles):
            sub = src_mv[i * tile_nbytes : min((i + 1) * tile_nbytes, n)]
            out.append(crc32c(sub))
            dst_mv[i * tile_nbytes : i * tile_nbytes + sub.nbytes] = sub
        return out
    crcs = (ctypes.c_uint32 * n_tiles)()
    dst_ptr, dst_keep = _ptr(dst_mv)
    src_ptr, src_keep = _ptr(src_mv)
    lib.ts_memcpy_crc_tiles(dst_ptr, src_ptr, n, tile_nbytes, crcs, nthreads)
    del dst_keep, src_keep
    return list(crcs)


def xxh64(buf, seed: int = 0) -> int:
    """XXH64 of a buffer — the second, independent hash backing
    incremental-dedup equality (see dedup_hash_algorithm). The fallback
    is sha256 truncated to 64 bits: a different algorithm, so values are
    only ever compared under a matching recorded algorithm string."""
    mv = memoryview(buf).cast("B")
    lib = _load()
    if lib is None:
        return _sha256_64(mv)
    if mv.nbytes == 0:
        return lib.ts_xxh64(None, 0, seed)
    ptr, keepalive = _ptr(mv)
    out = lib.ts_xxh64(ptr, mv.nbytes, seed)
    del keepalive
    return out


def _sha256_64(mv) -> int:
    import hashlib

    return int.from_bytes(hashlib.sha256(mv).digest()[:8], "big")


def dedup_hash_algorithm() -> str:
    return "xxh64" if available() else "sha256-64"


def dedup_hash_string(buf) -> str:
    """``"<algo>:<16-hex>"`` dedup hash of a buffer, for manifest
    entries. Incremental dedup requires this 64-bit value to match IN
    ADDITION to the 32-bit CRC — a single CRC leaves a ~2^-32
    silent-collision channel per blob-take at fleet scale."""
    return f"{dedup_hash_algorithm()}:{xxh64(buf) & _U64:016x}"


_U64 = (1 << 64) - 1


def crc_xxh_tiles(buf, tile_nbytes: int, nthreads: int = 4):
    """Per-``tile_nbytes`` (CRC32C, XXH64) of ``buf`` in ONE fused memory
    pass — the stage-time hash pass that feeds both the integrity
    checksums and the dedup hashes. Returns ``(crcs, xxhs)`` lists (one
    entry each when ``tile_nbytes`` >= the buffer size)."""
    mv = memoryview(buf).cast("B")
    n = mv.nbytes
    if n == 0:
        return [crc32c(b"")], [xxh64(b"")]
    if tile_nbytes <= 0 or tile_nbytes > n:
        tile_nbytes = n
    n_tiles = (n + tile_nbytes - 1) // tile_nbytes
    lib = _load()
    if lib is None:
        crcs, xxhs = [], []
        for i in range(n_tiles):
            sub = mv[i * tile_nbytes : min((i + 1) * tile_nbytes, n)]
            crcs.append(crc32c(sub))
            xxhs.append(_sha256_64(sub))
        return crcs, xxhs
    crcs = (ctypes.c_uint32 * n_tiles)()
    xxhs = (ctypes.c_uint64 * n_tiles)()
    ptr, keepalive = _ptr(mv)
    lib.ts_crc_xxh_tiles(ptr, n, tile_nbytes, crcs, xxhs, nthreads)
    del keepalive
    return list(crcs), list(xxhs)


def memcpy_crc_xxh_tiles(dst, src, tile_nbytes: int, nthreads: int = 4):
    """Copy ``src`` into ``dst`` while computing per-tile (CRC32C, XXH64)
    — ONE memory pass for what would otherwise be a clone pass plus two
    hash passes (the async-snapshot staging path). Returns
    ``(crcs, xxhs)``."""
    dst_mv = memoryview(dst).cast("B")
    src_mv = memoryview(src).cast("B")
    if dst_mv.readonly:
        raise ValueError("dst must be writable")
    if dst_mv.nbytes != src_mv.nbytes:
        raise ValueError(f"size mismatch: {dst_mv.nbytes} != {src_mv.nbytes}")
    n = src_mv.nbytes
    if n == 0:
        return [crc32c(b"")], [xxh64(b"")]
    if tile_nbytes <= 0 or tile_nbytes > n:
        tile_nbytes = n
    n_tiles = (n + tile_nbytes - 1) // tile_nbytes
    lib = _load()
    if lib is None:
        crcs, xxhs = [], []
        for i in range(n_tiles):
            sub = src_mv[i * tile_nbytes : min((i + 1) * tile_nbytes, n)]
            crcs.append(crc32c(sub))
            xxhs.append(_sha256_64(sub))
            dst_mv[i * tile_nbytes : i * tile_nbytes + sub.nbytes] = sub
        return crcs, xxhs
    crcs = (ctypes.c_uint32 * n_tiles)()
    xxhs = (ctypes.c_uint64 * n_tiles)()
    dst_ptr, dst_keep = _ptr(dst_mv)
    src_ptr, src_keep = _ptr(src_mv)
    lib.ts_memcpy_crc_xxh_tiles(
        dst_ptr, src_ptr, n, tile_nbytes, crcs, xxhs, nthreads
    )
    del dst_keep, src_keep
    return list(crcs), list(xxhs)


def crc32c(buf, seed: int = 0) -> int:
    """CRC32C (Castagnoli) of a buffer. The pure-Python fallback uses
    zlib.crc32 — a different polynomial — so checksums must only ever be
    compared when produced by the same implementation; callers record the
    algorithm alongside the value."""
    mv = memoryview(buf).cast("B")
    lib = _load()
    if lib is None:
        import zlib

        return zlib.crc32(mv, seed)
    if mv.nbytes == 0:
        return lib.ts_crc32c(None, 0, seed)
    ptr, keepalive = _ptr(mv)
    out = lib.ts_crc32c(ptr, mv.nbytes, seed)
    del keepalive
    return out


def crc_combine(crc1: int, crc2: int, len2: int) -> int:
    """CRC of a concatenation A||B from crc(A), crc(B), len(B) —
    O(log len2), no data pass. Uses whichever polynomial this build's
    ``crc32c`` computes (CRC32C native / CRC-32 zlib fallback), so
    combined values are always comparable to directly-computed ones."""
    lib = _load()
    if lib is not None:
        return lib.ts_crc32c_combine(crc1 & 0xFFFFFFFF, crc2 & 0xFFFFFFFF, len2)
    return _crc_combine_py(crc1, crc2, len2, poly=0xEDB88320)


def _crc_combine_py(crc1: int, crc2: int, len2: int, poly: int) -> int:
    """Pure-Python GF(2) combine (zlib crc32_combine algorithm)."""
    if len2 == 0:
        return crc1 & 0xFFFFFFFF

    def times(mat, vec):
        s = 0
        i = 0
        while vec:
            if vec & 1:
                s ^= mat[i]
            vec >>= 1
            i += 1
        return s

    def square(mat):
        return [times(mat, mat[n]) for n in range(32)]

    odd = [poly] + [1 << n for n in range(31)]
    even = square(odd)
    odd = square(even)
    crc1 &= 0xFFFFFFFF
    while True:
        even = square(odd)
        if len2 & 1:
            crc1 = times(even, crc1)
        len2 >>= 1
        if not len2:
            break
        odd = square(even)
        if len2 & 1:
            crc1 = times(odd, crc1)
        len2 >>= 1
        if not len2:
            break
    return (crc1 ^ crc2) & 0xFFFFFFFF


# --- dtype-aware fused tile compression ------------------------------------
#
# LZ4 block codec + byte-shuffle filter implemented inside the native
# engine (the container ships no lz4/zstd). Compression REQUIRES the
# native library (the policy bypasses without it — a pure-Python encoder
# would be slower than any pipe); decompression has a pure-Python
# fallback so compressed snapshots restore under TPUSNAP_DISABLE_NATIVE=1
# or on hosts without a toolchain (slow, but bit-exact).


class CompressionError(IOError):
    """A compressed tile failed to decode — the stored bytes are
    malformed (normally caught earlier by the CRC over the stored
    bytes; this is the defense-in-depth layer)."""


def compression_available() -> bool:
    return _load() is not None


def compress_bound(n: int, tile_nbytes: int) -> int:
    """Destination capacity ``compress_tiles`` requires (per-tile
    worst-case slots, native-side formula)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable: cannot compress")
    return int(lib.ts_compress_bound(n, tile_nbytes))


def compress_tiles(buf, tile_nbytes: int, elem: int, want_xxh: bool,
                   nthreads: int = 4):
    """Fused shuffle+LZ4+dual-hash of ``buf`` per ``tile_nbytes`` tile.

    Returns ``(out, comp_sizes, crcs, xxhs)`` where ``out`` is an
    aligned uint8 array holding the concatenated compressed tiles
    (sliced to the exact total), ``comp_sizes`` the per-tile stored
    sizes (a tile stored raw has size == its uncompressed size), and
    ``crcs``/``xxhs`` the hashes of each tile's STORED bytes (``xxhs``
    is None unless ``want_xxh``). Deterministic: equal input bytes
    always produce equal output bytes — the property incremental dedup
    and salvage-resume rest on."""
    mv = memoryview(buf).cast("B")
    n = mv.nbytes
    if n == 0:
        raise ValueError("cannot compress an empty buffer")
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable: cannot compress")
    if tile_nbytes <= 0 or tile_nbytes > n:
        tile_nbytes = n
    n_tiles = (n + tile_nbytes - 1) // tile_nbytes
    cap = int(lib.ts_compress_bound(n, tile_nbytes))
    out = aligned_empty(cap)
    comp_sizes = (ctypes.c_int64 * n_tiles)()
    crcs = (ctypes.c_uint32 * n_tiles)()
    xxhs = (ctypes.c_uint64 * n_tiles)()
    src_ptr, src_keep = _ptr(mv)
    total = lib.ts_compress_tiles(
        src_ptr,
        n,
        tile_nbytes,
        elem,
        out.ctypes.data,
        cap,
        comp_sizes,
        crcs,
        xxhs,
        1 if want_xxh else 0,
        nthreads,
    )
    del src_keep
    if total < 0:
        raise RuntimeError("native tile compression failed (capacity)")
    return (
        out[:total],
        list(comp_sizes),
        list(crcs),
        list(xxhs) if want_xxh else None,
    )


def decompress_tiles(src, comp_sizes, tile_raw: int, total_raw: int,
                     elem: int, out, nthreads: int = 4) -> None:
    """Decode concatenated compressed tiles into ``out`` (writable,
    exactly ``total_raw`` bytes). Raises :class:`CompressionError` on
    malformed input."""
    src_mv = memoryview(src).cast("B")
    out_mv = memoryview(out).cast("B")
    if out_mv.readonly:
        raise ValueError("out buffer must be writable")
    if out_mv.nbytes != total_raw:
        raise ValueError(
            f"out buffer size {out_mv.nbytes} != total_raw {total_raw}"
        )
    if total_raw == 0:
        if src_mv.nbytes != 0:
            raise CompressionError("trailing bytes after empty payload")
        return
    n_tiles = len(comp_sizes)
    lib = _load()
    if lib is None:
        _py_decompress_tiles(
            src_mv, comp_sizes, tile_raw, total_raw, elem, out_mv
        )
        return
    sizes = (ctypes.c_int64 * n_tiles)(*comp_sizes)
    src_ptr, src_keep = _ptr(src_mv)
    out_ptr, out_keep = _ptr(out_mv)
    got = lib.ts_decompress_tiles(
        src_ptr,
        src_mv.nbytes,
        sizes,
        n_tiles,
        tile_raw,
        total_raw,
        out_ptr,
        elem,
        nthreads,
    )
    del src_keep, out_keep
    if got != total_raw:
        raise CompressionError(
            f"compressed tile payload failed to decode ({got} of "
            f"{total_raw} bytes) — the stored bytes are malformed"
        )


def lz4_compress(buf, elem: int = 1) -> Optional[bytes]:
    """Raw single-block shuffle+LZ4 (tests, codec micro-benchmark).
    Returns None when the input does not shrink (or native is absent)."""
    mv = memoryview(buf).cast("B")
    lib = _load()
    if lib is None or mv.nbytes == 0:
        return None
    out = np.empty(mv.nbytes, dtype=np.uint8)  # must be strictly smaller
    ptr, keep = _ptr(mv)
    got = lib.ts_lz4_compress(ptr, mv.nbytes, out.ctypes.data, mv.nbytes - 1, elem)
    del keep
    if got < 0:
        return None
    return out[:got].tobytes()


def lz4_decompress(buf, raw_nbytes: int, elem: int = 1) -> bytes:
    """Decode one shuffle+LZ4 block of known decoded size."""
    mv = memoryview(buf).cast("B")
    out = np.empty(raw_nbytes, dtype=np.uint8)
    lib = _load()
    if lib is None:
        shuffled = _py_lz4_decompress_block(mv, raw_nbytes)
        out[:] = np.frombuffer(
            _py_unshuffle(shuffled, elem), dtype=np.uint8
        )
        return out.tobytes()
    ptr, keep = _ptr(mv)
    got = lib.ts_lz4_decompress(
        ptr, mv.nbytes, out.ctypes.data, raw_nbytes, elem
    )
    del keep
    if got != raw_nbytes:
        raise CompressionError("LZ4 block failed to decode")
    return out.tobytes()


def _py_lz4_decompress_block(mv: memoryview, raw_nbytes: int) -> bytes:
    """Pure-Python bounds-checked LZ4 block decode (fallback restore
    path only — never the hot path)."""
    src = bytes(mv)
    n = len(src)
    out = bytearray()
    ip = 0
    while ip < n:
        token = src[ip]
        ip += 1
        litlen = token >> 4
        if litlen == 15:
            while True:
                if ip >= n:
                    raise CompressionError("truncated literal length")
                b = src[ip]
                ip += 1
                litlen += b
                if b != 255:
                    break
        if ip + litlen > n or len(out) + litlen > raw_nbytes:
            raise CompressionError("literal run out of bounds")
        out += src[ip : ip + litlen]
        ip += litlen
        if ip >= n:
            break
        if ip + 2 > n:
            raise CompressionError("truncated match offset")
        offset = src[ip] | (src[ip + 1] << 8)
        ip += 2
        if offset == 0 or offset > len(out):
            raise CompressionError("match offset out of bounds")
        mlen = token & 15
        if mlen == 15:
            while True:
                if ip >= n:
                    raise CompressionError("truncated match length")
                b = src[ip]
                ip += 1
                mlen += b
                if b != 255:
                    break
        mlen += 4
        if len(out) + mlen > raw_nbytes:
            raise CompressionError("match run out of bounds")
        start = len(out) - offset
        for i in range(mlen):  # forward copy handles overlap (RLE)
            out.append(out[start + i])
    if len(out) != raw_nbytes:
        raise CompressionError(
            f"decoded {len(out)} bytes, expected {raw_nbytes}"
        )
    return bytes(out)


def _py_unshuffle(data: bytes, elem: int) -> bytes:
    if elem <= 1 or not data:
        return data
    n = len(data)
    ne = n // elem
    body = ne * elem
    planes = np.frombuffer(data[:body], dtype=np.uint8).reshape(elem, ne)
    return planes.T.tobytes() + data[body:]


def _py_decompress_tiles(
    src_mv, comp_sizes, tile_raw, total_raw, elem, out_mv
) -> None:
    off = 0
    raw_off = 0
    if tile_raw <= 0:
        tile_raw = total_raw
    for clen in comp_sizes:
        raw_len = min(tile_raw, total_raw - raw_off)
        if raw_len <= 0 or off + clen > src_mv.nbytes:
            raise CompressionError("compressed tile sizes out of bounds")
        tile = src_mv[off : off + clen]
        if clen == raw_len:
            out_mv[raw_off : raw_off + raw_len] = tile  # stored raw
        elif clen > raw_len:
            raise CompressionError("compressed tile larger than raw tile")
        else:
            shuffled = _py_lz4_decompress_block(tile, raw_len)
            out_mv[raw_off : raw_off + raw_len] = _py_unshuffle(
                shuffled, elem
            )
        off += clen
        raw_off += raw_len
    if off != src_mv.nbytes or raw_off != total_raw:
        raise CompressionError("compressed tile sizes do not cover payload")


def checksum_algorithm() -> str:
    return "crc32c" if available() else "zlib-crc32"


def checksum_string(buf) -> str:
    """``"<algo>:<8-hex>"`` checksum of a buffer, for manifest entries."""
    return f"{checksum_algorithm()}:{crc32c(buf) & 0xFFFFFFFF:08x}"


class ChecksumError(IOError):
    """A restored blob's bytes do not match the checksum recorded at save
    time — storage or transport corrupted the data."""


def verify_checksum_value(
    crc: int, algo: str, recorded: str, location: str
) -> None:
    """Verify a read-time-computed checksum value (from the fused native
    read) against the manifest-recorded string — no data pass needed.

    Mirrors ``verify_checksum``'s algorithm-mismatch policy: a snapshot
    written by a build with a different checksum implementation is skipped
    with a warning; only a same-algorithm mismatch is proof of corruption.
    """
    rec_algo, _, value = recorded.partition(":")
    if rec_algo != algo:
        logger.warning(
            "skipping checksum verification for %s: snapshot used %s, "
            "this read computed %s",
            location,
            rec_algo,
            algo,
        )
        return
    try:
        recorded_value = int(value, 16)
    except ValueError:
        raise ChecksumError(
            f"malformed checksum {recorded!r} recorded for {location!r} — "
            "the snapshot metadata itself is corrupt"
        ) from None
    if (crc & 0xFFFFFFFF) != recorded_value:
        raise ChecksumError(
            f"checksum mismatch for {location!r}: stored {recorded}, "
            f"read bytes hash to {algo}:{crc & 0xFFFFFFFF:08x} — the blob "
            "was corrupted in storage or transit"
        )


def verify_checksum(buf, recorded: str, location: str) -> None:
    """Verify a read buffer against the manifest-recorded checksum.

    An algorithm mismatch (snapshot written by a build whose native
    helper/fallback used a different polynomial) is skipped with a
    warning — the bytes may be fine; only a same-algorithm mismatch is
    proof of corruption."""
    algo = checksum_algorithm()
    if not recorded.startswith(algo + ":"):
        # Defer hashing: nothing to compare against. Value 0 is unused.
        verify_checksum_value(0, algo, recorded, location)
        return
    verify_checksum_value(crc32c(buf), algo, recorded, location)
