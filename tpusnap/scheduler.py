"""Async execution engine: budget-gated, pipelined staging and storage I/O.

TPU-native counterpart of /root/reference/torchsnapshot/scheduler.py.
Semantics preserved:

- Write path (scheduler.py:220-337): each WriteReq becomes a pipeline moving
  ready_for_staging → staging → ready_for_io → io → done. Staging (device→
  host DMA + serialization, in a thread pool with the GIL released by
  numpy/ctypes/XLA) is dispatched only while the outstanding staging cost
  fits the memory budget — but at least one request is always allowed so a
  single over-budget item can't deadlock (scheduler.py:264-275). Storage
  I/O keeps ≤16 requests in flight; staging uses ≤4 threads.
- ``execute_write_reqs`` returns once **staging** completes — the snapshot
  is then consistent (buffers no longer alias live arrays) and residual
  storage I/O is handed back as ``PendingIOWork`` (scheduler.py:178-217),
  which ``take`` drains synchronously and ``async_take`` drains in a
  background thread.
- Read path mirrors it (scheduler.py:357-444): read (≤16 concurrent,
  budget-gated on consuming cost) ∥ consume (deserialize + copy into the
  restore target, thread pool).
- Memory budget = min(0.6 × available host RAM / local_world_size, 32GB),
  env-overridable; local world size discovered by all-gathering hostnames
  (scheduler.py:27-65).
"""

from __future__ import annotations

import asyncio
import logging
import socket
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import List, Optional, Set

import psutil

from . import telemetry
from .io_types import ReadIO, ReadReq, StoragePlugin, WriteIO, WriteReq, run_on_loop
from .knobs import get_memory_budget_override_bytes

logger = logging.getLogger(__name__)

import os as _os

_MAX_IO_CONCURRENCY = 16
# Staging/consume threads do memory-bandwidth work (memcpy, CRC,
# deserialize) with the GIL released; more threads than cores only adds
# GIL ping-pong and context switching (measured on the 1-vCPU dev host:
# 4 interleaved clone threads ran ~1 GB/s aggregate vs ~4 GB/s for one).
_MAX_CPU_CONCURRENCY = max(1, min(4, _os.cpu_count() or 4))
_MAX_PER_RANK_MEMORY_BUDGET_BYTES = 32 * 1024 * 1024 * 1024
_AVAILABLE_MEMORY_FRACTION = 0.6
_REPORT_INTERVAL_SEC = 10.0


# local_world_size is stable for the life of a job; cache it so restore
# and read_object never pay a collective for it (take threads it through
# explicitly from its coalescing gather).
_cached_local_world_size: Optional[int] = None


def get_process_memory_budget_bytes(
    comm=None, local_world_size: Optional[int] = None
) -> int:
    """Per-process host-memory budget for staging/consuming buffers
    (reference scheduler.py:45-65). ``local_world_size`` (ranks sharing
    this host) may be passed by callers that already gathered hostnames;
    otherwise it is discovered once per process and cached."""
    global _cached_local_world_size
    override = get_memory_budget_override_bytes()
    if override is not None:
        return override
    if local_world_size is not None:
        _cached_local_world_size = local_world_size
    elif _cached_local_world_size is not None:
        local_world_size = _cached_local_world_size
    elif comm is not None and comm.world_size > 1:
        from .knobs import get_node_name

        hostnames = comm.all_gather_object(get_node_name())
        local_world_size = hostnames.count(get_node_name())
        _cached_local_world_size = local_world_size
    else:
        local_world_size = 1
    available = psutil.virtual_memory().available
    budget = int(available * _AVAILABLE_MEMORY_FRACTION / max(local_world_size, 1))
    return min(budget, _MAX_PER_RANK_MEMORY_BUDGET_BYTES)


async def _cancel_and_drain(tasks: Set[asyncio.Task]) -> None:
    """Abort helper shared by the write loop and PendingIOWork: cancel
    in-flight tasks and await them so the loop can close cleanly and no
    write keeps running into an aborted snapshot directory."""
    for task in tasks:
        task.cancel()
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)


# Stats of the most recent completed write/read execution in this process,
# keyed by verb ("write"/"read"). Benchmarks and tests read this to get the
# staging-time vs total-time split without parsing logs.
LAST_EXECUTION_STATS: dict = {}


class _Reporter:
    """Periodic pipeline progress logging (reference scheduler.py:96-175):
    per-stage pipeline counts, RSS delta, remaining memory budget, and a
    staging-time vs total-time summary — the observability needed to tell
    a staging-bound pipeline from an I/O-bound one."""

    def __init__(self, rank: int, verb: str, total_reqs: int) -> None:
        self.rank = rank
        self.verb = verb
        self.total_reqs = total_reqs
        self.begin_ts = time.monotonic()
        self.last_report_ts = self.begin_ts
        self.bytes_done = 0
        self.reqs_done = 0
        self.rss_begin = psutil.Process().memory_info().rss
        self.staging_done_ts: Optional[float] = None
        # Live pipeline-stage counts, updated by the execution loop:
        # {stage: count} with stages ready_for_staging/staging/ready_for_io/io.
        self.stage_counts: dict = {}
        self.budget_remaining: Optional[int] = None
        self.total_budget: Optional[int] = None

    def mark_staging_complete(self) -> None:
        if self.staging_done_ts is None:
            self.staging_done_ts = time.monotonic()

    def report_request_done(self, nbytes: int) -> None:
        self.reqs_done += 1
        self.bytes_done += nbytes
        now = time.monotonic()
        if now - self.last_report_ts >= _REPORT_INTERVAL_SEC:
            self.last_report_ts = now
            rss_delta = psutil.Process().memory_info().rss - self.rss_begin
            counts = " ".join(
                f"{k}={v}" for k, v in self.stage_counts.items()
            )
            budget = (
                f", budget {self.budget_remaining / 1e9:.1f}/"
                f"{self.total_budget / 1e9:.1f} GB free"
                if self.budget_remaining is not None
                and self.total_budget is not None
                else ""
            )
            logger.info(
                "Rank %d: %s %d/%d reqs [%s done=%d], %.2f GB, %.1f MB/s, "
                "rss delta %.0f MB%s",
                self.rank,
                self.verb,
                self.reqs_done,
                self.total_reqs,
                counts,
                self.reqs_done,
                self.bytes_done / 1e9,
                self.bytes_done / 1e6 / max(now - self.begin_ts, 1e-9),
                rss_delta / 1e6,
                budget,
            )

    def summarize(self) -> None:
        end_ts = time.monotonic()
        elapsed = max(end_ts - self.begin_ts, 1e-9)
        staging_elapsed = (
            max(self.staging_done_ts - self.begin_ts, 0.0)
            if self.staging_done_ts is not None
            else None
        )
        stats = {
            "reqs": self.reqs_done,
            "bytes": self.bytes_done,
            "total_s": elapsed,
            "staging_s": staging_elapsed,
            "throughput_mbps": self.bytes_done / 1e6 / elapsed,
            "budget_bytes": self.total_budget,
        }
        LAST_EXECUTION_STATS[self.verb] = stats
        if staging_elapsed is not None:
            # The number async_take exists to minimize: training is blocked
            # only for the staging window, not the full I/O drain.
            logger.info(
                "Rank %d: %s complete: %d reqs, %.2f GB in %.2fs "
                "(%.1f MB/s); staging %.2fs / residual I/O %.2fs",
                self.rank,
                self.verb,
                self.reqs_done,
                self.bytes_done / 1e9,
                elapsed,
                self.bytes_done / 1e6 / elapsed,
                staging_elapsed,
                elapsed - staging_elapsed,
            )
        else:
            logger.info(
                "Rank %d: %s complete: %d reqs, %.2f GB in %.2fs (%.1f MB/s)",
                self.rank,
                self.verb,
                self.reqs_done,
                self.bytes_done / 1e9,
                elapsed,
                self.bytes_done / 1e6 / elapsed,
            )


@dataclass
class PendingIOWork:
    """Residual storage I/O after staging completed (reference
    scheduler.py:178-217). Keeps honoring the I/O concurrency cap while
    draining."""

    io_tasks: Set[asyncio.Task] = field(default_factory=set)
    pending_pipelines: List["_WritePipeline"] = field(default_factory=list)
    executor: Optional[ThreadPoolExecutor] = None
    hash_executor: Optional[ThreadPoolExecutor] = None
    reporter: Optional[_Reporter] = None

    async def complete(self) -> None:
        io_tasks = set(self.io_tasks)
        try:
            pending = list(self.pending_pipelines)
            while io_tasks or pending:
                while pending and len(io_tasks) < _MAX_IO_CONCURRENCY:
                    io_tasks.add(asyncio.ensure_future(pending.pop(0).write()))
                done, io_tasks = await asyncio.wait(
                    io_tasks, return_when=asyncio.FIRST_COMPLETED
                )
                for task in done:
                    pipeline = task.result()
                    if self.reporter is not None:
                        self.reporter.report_request_done(pipeline.buf_size)
        except BaseException:
            await _cancel_and_drain(io_tasks)
            raise
        finally:
            if self.executor is not None:
                self.executor.shutdown(wait=True)
            if self.hash_executor is not None:
                self.hash_executor.shutdown(wait=True)
        if self.reporter is not None:
            self.reporter.summarize()

    def sync_complete(self, event_loop: asyncio.AbstractEventLoop) -> None:
        # run_on_loop: the commit path reuses this loop for the metadata
        # write and close afterwards — a stranded task would be resumed
        # mid-commit.
        run_on_loop(event_loop, self.complete())


class _WritePipeline:
    def __init__(
        self,
        write_req: WriteReq,
        storage: StoragePlugin,
        executor: Optional[ThreadPoolExecutor] = None,
        hash_executor: Optional[ThreadPoolExecutor] = None,
        tele: Optional[telemetry.TakeTelemetry] = None,
    ) -> None:
        self.write_req = write_req
        self.storage = storage
        self.executor = executor
        self.tele = tele
        # Deferred checksums run here, NEVER on the staging executor:
        # queued hash jobs behind staging tasks would stall staging
        # completion — the async blocked window — behind work that was
        # deferred precisely to leave that window (measured at 20 GB:
        # staging_s 50 s of a 52 s take with the shared 1-worker pool).
        self.hash_executor = hash_executor or executor
        self.staging_cost = write_req.buffer_stager.get_staging_cost_bytes()
        self.buf = None
        self.buf_size = 0
        # True when the stager reported the content is already persisted
        # (incremental dedup): the request completes with no storage I/O.
        self.skipped = False

    async def stage(self, executor: ThreadPoolExecutor) -> "_WritePipeline":
        from .io_types import SKIP_WRITE

        start = self.tele.now() if self.tele is not None else 0.0
        token = self.tele.op_enter("stage_buffer") if self.tele is not None else None
        try:
            buf = await self.write_req.buffer_stager.stage_buffer(executor)
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        if self.tele is not None:
            self.tele.record_span(
                "stage_buffer",
                start,
                self.tele.now() - start,
                path=self.write_req.path,
                bytes=self.staging_cost,
            )
        if buf is SKIP_WRITE:
            self.skipped = True
            telemetry.incr("scheduler.dedup_skipped", rec=self.tele)
            return self
        self.buf = buf
        self.buf_size = (
            memoryview(self.buf).cast("B").nbytes if self.buf is not None else 0
        )
        return self

    async def write(self) -> "_WritePipeline":
        stager = self.write_req.buffer_stager
        if getattr(stager, "defer_checksums", False) and self.buf is not None:
            # Deferred hashing (single-process, non-incremental takes):
            # checksums computed HERE, on the write path — overlapping
            # other requests' disk time instead of occupying the staging
            # window async_take blocks training on. The values land in
            # the same entry objects the manifest references, before the
            # post-drain metadata commit.
            late = getattr(stager, "late_checksum", None)
            if late is not None:
                hash_start = self.tele.now() if self.tele is not None else 0.0
                loop = asyncio.get_running_loop()
                if self.hash_executor is not None:
                    await loop.run_in_executor(
                        self.hash_executor, late, self.buf
                    )
                else:
                    late(self.buf)
                if self.tele is not None:
                    self.tele.record_span(
                        "checksum_late",
                        hash_start,
                        self.tele.now() - hash_start,
                        bytes=self.buf_size,
                    )
        write_start = self.tele.now() if self.tele is not None else 0.0
        token = (
            self.tele.op_enter("storage_write") if self.tele is not None else None
        )
        try:
            await self.storage.write(
                WriteIO(path=self.write_req.path, buf=self.buf)
            )
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        if self.tele is not None:
            self.tele.record_span(
                "storage_write",
                write_start,
                self.tele.now() - write_start,
                path=self.write_req.path,
                bytes=self.buf_size,
            )
        telemetry.incr("storage.bytes_written", self.buf_size, rec=self.tele)
        telemetry.incr("storage.writes", rec=self.tele)
        # Async-clone buffers go back to the staging pool (warm pages
        # for the next clone of this size); other buffers are ignored by
        # release(). The pool is bounded by TPUSNAP_STAGING_POOL_BYTES,
        # not by this take's budget — see execute_write_reqs.
        from ._staging_pool import release

        release(self.buf)
        self.buf = None  # release host memory
        return self


async def execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    prioritize_staging: bool = False,
) -> PendingIOWork:
    """``prioritize_staging`` (async takes): do not dispatch storage
    I/O while staging can still proceed — the blocked window an
    async_take exists to minimize ends at staging-complete, and on
    CPU-limited hosts concurrent write-path work (checksums, bounce
    copies, syscalls) steals core time from the staging pass and
    stretches that window several-fold (measured 2.8s vs a 0.5s pure
    clone pass on the 1-core dev host). Writes then drain in the
    background via PendingIOWork, exactly like orbax's async save
    defers its serialization+write behind the returned future. I/O IS
    dispatched mid-staging when staging is budget-starved (writes must
    complete to free budget — same deadlock-freedom as before). Sync
    takes keep full overlap: their metric is total time, and disk DMA
    waits overlap staging profitably even on one core."""
    executor = ThreadPoolExecutor(
        max_workers=_MAX_CPU_CONCURRENCY, thread_name_prefix="tpusnap-stage"
    )
    # Deferred write-path hashing gets its own pool so it can never
    # queue ahead of staging tasks (see _WritePipeline.hash_executor).
    hash_executor = ThreadPoolExecutor(
        max_workers=_MAX_CPU_CONCURRENCY, thread_name_prefix="tpusnap-hash"
    )
    reporter = _Reporter(rank=rank, verb="write", total_reqs=len(write_reqs))
    # Captured once: the drain (PendingIOWork) and late hashing may run
    # on a background thread after a newer take replaced the ambient
    # recorder.
    tele = telemetry.current()
    stage_phase_start = tele.now() if tele is not None else 0.0
    # Stage large requests first: they occupy budget longest and their I/O
    # overlaps with the staging of everything behind them.
    pipelines = deque(
        sorted(
            (
                _WritePipeline(wr, storage, executor, hash_executor, tele)
                for wr in write_reqs
            ),
            key=lambda p: p.staging_cost,
            reverse=True,
        )
    )
    # The budget governs IN-FLIGHT staging buffers: every dispatch
    # debits staging_cost, every write completion credits buf_size —
    # unconditionally. Buffers the staging pool retains after a write
    # are NOT withheld from the credit (ADVICE r4: withholding
    # re-debited the same resident bytes every reuse cycle, and a
    # budget-capped take whose cumulative clone bytes exceeded the
    # budget degraded to fully serialized stage-then-write) — the
    # pool is its own separately bounded cache: worst-case resident is
    # budget + TPUSNAP_STAGING_POOL_BYTES, and in practice ≈ budget,
    # because acquire() reuses parked buffers of recurring sizes
    # (uniform chunk sizes within a take, identical shapes across a
    # checkpoint loop's takes).
    budget = memory_budget_bytes
    staging_tasks: Set[asyncio.Task] = set()
    io_tasks: Set[asyncio.Task] = set()

    def dispatch_staging() -> None:
        nonlocal budget
        while pipelines and len(staging_tasks) < _MAX_CPU_CONCURRENCY:
            head = pipelines[0]
            # The ≥1 over-budget admission may only fire when NOTHING
            # can free budget: staged buffers waiting in ready_for_io
            # count in EVERY mode — they hold budget that the write
            # dispatched on the next loop turn will credit back.
            # Admitting over budget past them held every staged buffer
            # resident at once (observed as peak 3/2 budget whenever all
            # in-flight stagings completed in one wait batch before any
            # I/O was dispatched) and unenforced the budget entirely.
            in_flight = staging_tasks or io_tasks or ready_for_io
            if head.staging_cost > budget and in_flight:
                break  # wait for memory to free up
            pipelines.popleft()
            budget -= head.staging_cost
            if tele is not None:
                # High-water mark of budget in use (can exceed the
                # budget via the ≥1 over-budget admission).
                tele.gauge_max(
                    "scheduler.budget_used_bytes", memory_budget_bytes - budget
                )
            staging_tasks.add(asyncio.ensure_future(head.stage(executor)))

    def staging_budget_starved() -> bool:
        return (
            bool(pipelines)
            and len(staging_tasks) < _MAX_CPU_CONCURRENCY
            and pipelines[0].staging_cost > budget
        )

    def io_gate_open() -> bool:
        if not prioritize_staging:
            return True
        # Open ONLY while staging is budget-starved (requests pending
        # but none runnable): write completions are the only budget
        # source. Everything else drains via PendingIOWork after the
        # blocked window closes.
        return bool(pipelines and not staging_tasks)

    def dispatch_io(ready: List[_WritePipeline]) -> None:
        if not io_gate_open():
            return
        while ready and len(io_tasks) < _MAX_IO_CONCURRENCY:
            io_tasks.add(asyncio.ensure_future(ready.pop(0).write()))

    ready_for_io: List[_WritePipeline] = []
    reporter.total_budget = memory_budget_bytes

    def update_reporter_state() -> None:
        reporter.stage_counts = {
            "ready_for_staging": len(pipelines),
            "staging": len(staging_tasks),
            "ready_for_io": len(ready_for_io),
            "io": len(io_tasks),
        }
        reporter.budget_remaining = budget

    stall_start: Optional[float] = None
    try:
        dispatch_staging()
        while staging_tasks or pipelines:
            # Budget-stall EPISODES, not wait iterations: one span +
            # counter per contiguous window in which the head request
            # cannot be admitted, however many task completions the
            # window spans.
            if staging_budget_starved():
                if stall_start is None:
                    stall_start = tele.now() if tele is not None else 0.0
                    telemetry.incr("scheduler.budget_waits", rec=tele)
            elif stall_start is not None:
                if tele is not None:
                    tele.record_span(
                        "budget_wait", stall_start, tele.now() - stall_start
                    )
                stall_start = None
            done, _ = await asyncio.wait(
                staging_tasks | io_tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in staging_tasks:
                    staging_tasks.discard(task)
                    pipeline = task.result()  # re-raises staging failure
                    # Staged buffer may be smaller than the staging cost
                    # (e.g. cost model overestimates); credit the difference.
                    budget += pipeline.staging_cost - pipeline.buf_size
                    # Heartbeat feed: bytes past the staging stage (the
                    # window async_take blocks training on).
                    telemetry.incr(
                        "scheduler.bytes_staged", pipeline.buf_size, rec=tele
                    )
                    if pipeline.skipped:
                        # Dedup'd against a previous snapshot: no I/O.
                        reporter.report_request_done(0)
                    else:
                        ready_for_io.append(pipeline)
                elif task in io_tasks:
                    io_tasks.discard(task)
                    pipeline = task.result()
                    budget += pipeline.buf_size
                    reporter.report_request_done(pipeline.buf_size)
            # Staging first: the I/O gate (prioritize_staging) must see
            # the REFILLED staging set, or it opens spuriously in the
            # instant between one stager finishing and the next starting.
            dispatch_staging()
            dispatch_io(ready_for_io)
            update_reporter_state()
    except BaseException:
        await _cancel_and_drain(staging_tasks | io_tasks)
        executor.shutdown(wait=True)
        hash_executor.shutdown(wait=True)
        raise
    reporter.mark_staging_complete()
    if tele is not None:
        # Interior measurement of the staging window (the "stage" PHASE
        # is recorded by the take around the whole sync_execute call).
        tele.record_span(
            "stage_window",
            stage_phase_start,
            tele.now() - stage_phase_start,
            reqs=len(write_reqs),
        )

    # Staging complete: snapshot content is now frozen. Remaining I/O is
    # handed back so the caller decides whether to drain it in the
    # foreground (take) or a background thread (async_take).
    return PendingIOWork(
        io_tasks=io_tasks,
        pending_pipelines=ready_for_io,
        executor=executor,
        hash_executor=hash_executor,
        reporter=reporter,
    )


def sync_execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
    prioritize_staging: bool = False,
) -> PendingIOWork:
    return run_on_loop(
        event_loop,
        execute_write_reqs(
            write_reqs,
            storage,
            memory_budget_bytes,
            rank,
            prioritize_staging=prioritize_staging,
        ),
    )


class _ReadPipeline:
    def __init__(
        self,
        read_req: ReadReq,
        storage: StoragePlugin,
        tele: Optional[telemetry.TakeTelemetry] = None,
    ) -> None:
        self.read_req = read_req
        self.storage = storage
        self.tele = tele
        # In-place reads allocate no full-size scratch buffer (bytes land
        # in the caller-owned restore target), so they are charged only
        # the plugin's transient overhead — the fs engine's per-stream
        # bounce buffers, a cloud plugin's download chunk — instead of
        # the blob size. This is what lets a multi-GB tensor restore in
        # place under a small memory budget without serializing every
        # stream.
        cost = read_req.buffer_consumer.get_consuming_cost_bytes()
        if read_req.into is not None and storage.supports_in_place_reads:
            cost = min(cost, storage.in_place_read_overhead_bytes(cost))
        self.consuming_cost = cost
        self.read_io: Optional[ReadIO] = None

    def _read_nbytes(self) -> int:
        br = self.read_req.byte_range
        if br is not None:
            return int(br[1] - br[0])
        if self.read_io is not None and self.read_io.buf is not None:
            try:
                return self.read_io.buf.getbuffer().nbytes
            except Exception:
                pass
        return self.consuming_cost

    async def read(self) -> "_ReadPipeline":
        self.read_io = ReadIO(
            path=self.read_req.path,
            byte_range=self.read_req.byte_range,
            into=self.read_req.into,
            want_crc=self.read_req.want_crc,
        )
        start = self.tele.now() if self.tele is not None else 0.0
        token = (
            self.tele.op_enter("storage_read") if self.tele is not None else None
        )
        try:
            await self.storage.read(self.read_io)
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        nbytes = self._read_nbytes()
        if self.tele is not None:
            self.tele.record_span(
                "storage_read",
                start,
                self.tele.now() - start,
                path=self.read_req.path,
                bytes=nbytes,
            )
        telemetry.incr("storage.bytes_read", nbytes, rec=self.tele)
        telemetry.incr("storage.reads", rec=self.tele)
        return self

    async def consume(self, executor: ThreadPoolExecutor) -> "_ReadPipeline":
        # "consume" covers deserialize + the copy/`device_put` into the
        # restore target (the HtoD leg for jax targets).
        start = self.tele.now() if self.tele is not None else 0.0
        token = self.tele.op_enter("consume") if self.tele is not None else None
        try:
            await self.read_req.buffer_consumer.consume_read_io(
                self.read_io, executor
            )
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        if self.tele is not None:
            self.tele.record_span(
                "consume",
                start,
                self.tele.now() - start,
                path=self.read_req.path,
                bytes=self.consuming_cost,
            )
        self.read_io = None  # release
        return self


async def execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
) -> None:
    executor = ThreadPoolExecutor(
        max_workers=_MAX_CPU_CONCURRENCY, thread_name_prefix="tpusnap-consume"
    )
    reporter = _Reporter(rank=rank, verb="read", total_reqs=len(read_reqs))
    # Ambient recorder (the restore path installs one thread-locally);
    # None for uninstrumented callers (verify's own engine, read_object
    # outside a recorder) — spans then skip, counters stay global.
    tele = telemetry.current()
    pipelines = deque(
        sorted(
            (_ReadPipeline(rr, storage, tele) for rr in read_reqs),
            key=lambda p: p.consuming_cost,
            reverse=True,
        )
    )
    budget = memory_budget_bytes
    read_tasks: Set[asyncio.Task] = set()
    consume_tasks: Set[asyncio.Task] = set()

    # NOTE on destination prefaulting: a background thread first-touching
    # not-yet-dispatched ``into`` buffers (overlapping page faults with
    # the reads) was tried and MEASURED A LOSS on the 1-vCPU dev host
    # (20 GB restore: 88 s with, 55 s without) — the toucher competes for
    # the one core the bounce copies and fused CRCs run on, and its zero
    # writes evict cache the reads want. Multi-core hosts may differ;
    # revisit with real TPU-VM cores.

    def dispatch_reads() -> None:
        nonlocal budget
        while pipelines and len(read_tasks) < _MAX_IO_CONCURRENCY:
            head = pipelines[0]
            in_flight = read_tasks or consume_tasks
            if head.consuming_cost > budget and in_flight:
                break
            pipelines.popleft()
            budget -= head.consuming_cost
            read_tasks.add(asyncio.ensure_future(head.read()))

    reporter.total_budget = memory_budget_bytes
    try:
        dispatch_reads()
        while read_tasks or consume_tasks or pipelines:
            done, _ = await asyncio.wait(
                read_tasks | consume_tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in read_tasks:
                    read_tasks.discard(task)
                    pipeline = task.result()
                    consume_tasks.add(
                        asyncio.ensure_future(pipeline.consume(executor))
                    )
                elif task in consume_tasks:
                    consume_tasks.discard(task)
                    pipeline = task.result()
                    budget += pipeline.consuming_cost
                    reporter.report_request_done(pipeline.consuming_cost)
            dispatch_reads()
            reporter.stage_counts = {
                "ready_for_read": len(pipelines),
                "read": len(read_tasks),
                "consume": len(consume_tasks),
            }
            reporter.budget_remaining = budget
    except BaseException:
        # Mirror the write path: a failed request (e.g. checksum
        # mismatch) must not abandon in-flight tasks — orphans would be
        # resumed by the NEXT run_until_complete on a reused event loop
        # and write into a previous call's caller-owned buffers.
        await _cancel_and_drain(read_tasks | consume_tasks)
        # Task cancellation does not interrupt run_in_executor work: a
        # plugin thread may still be mid-write into a caller-owned
        # in-place destination. Wait it out (off-loop) before the error
        # reaches the caller.
        await asyncio.get_running_loop().run_in_executor(
            None, storage.drain_in_flight
        )
        raise
    finally:
        executor.shutdown(wait=True)
    reporter.summarize()


def sync_execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    run_on_loop(
        event_loop,
        execute_read_reqs(read_reqs, storage, memory_budget_bytes, rank),
    )
