"""Async execution engine: budget-gated, pipelined staging and storage I/O.

TPU-native counterpart of /root/reference/torchsnapshot/scheduler.py.
Semantics preserved:

- Write path (scheduler.py:220-337): each WriteReq becomes a pipeline moving
  ready_for_staging → staging → ready_for_io → io → done. Staging (device→
  host DMA + serialization, in a thread pool with the GIL released by
  numpy/ctypes/XLA) is dispatched only while the outstanding staging cost
  fits the memory budget — but at least one request is always allowed so a
  single over-budget item can't deadlock (scheduler.py:264-275). Storage
  I/O keeps ≤16 requests in flight; the staging executor is sized by
  TPUSNAP_STAGE_THREADS (default 1 — interleaved clone threads measured
  SLOWER in aggregate than one on this memory system).
- ``execute_write_reqs`` returns a ``PendingIOWork`` once the take's
  BLOCKED WINDOW closes. For sync takes and staging-priority async takes
  that is staging-complete (the snapshot is then consistent: buffers no
  longer alias live arrays). For PIPELINED async takes
  (``pipelined_staging=True``) it is first-window-staged: only a
  memory-budget-bounded window of write requests is staged before control
  returns, and the background drain keeps cloning window after window,
  releasing each to storage I/O — blocked time and clone RSS are
  O(window), not O(state). The engine itself is resumable
  (:class:`_WriteScheduler`): the same stage ∥ write loop runs to the
  blocked-window boundary on the caller's thread and to completion inside
  ``PendingIOWork`` (``take`` drains synchronously, ``async_take`` on a
  background thread).
- Read path mirrors it (scheduler.py:357-444): read (≤16 concurrent,
  budget-gated on consuming cost) ∥ consume (deserialize + copy into the
  restore target, thread pool).
- Memory budget = min(0.6 × available host RAM / local_world_size, 32GB),
  env-overridable; local world size discovered by all-gathering hostnames
  (scheduler.py:27-65). Pipelined async takes further clamp their
  in-flight staging budget to TPUSNAP_ASYNC_STAGE_WINDOW_BYTES.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, List, Optional, Set

import psutil

from . import access, flight, telemetry
from .io_types import (
    PROBE_DIR,
    ReadIO,
    ReadReq,
    StoragePlugin,
    WriteIO,
    WriteReq,
    run_on_loop,
)
from .knobs import get_memory_budget_override_bytes

logger = logging.getLogger(__name__)

import os as _os

_MAX_IO_CONCURRENCY = 16
# Staging/consume threads do memory-bandwidth work (memcpy, CRC,
# deserialize) with the GIL released; more threads than cores only adds
# GIL ping-pong and context switching (measured on the 1-vCPU dev host:
# 4 interleaved clone threads ran ~1 GB/s aggregate vs ~4 GB/s for one).
_MAX_CPU_CONCURRENCY = max(1, min(4, _os.cpu_count() or 4))
_MAX_PER_RANK_MEMORY_BUDGET_BYTES = 32 * 1024 * 1024 * 1024
_AVAILABLE_MEMORY_FRACTION = 0.6
_REPORT_INTERVAL_SEC = 10.0


# local_world_size is stable for the life of a job; cache it so restore
# and read_object never pay a collective for it (take threads it through
# explicitly from its coalescing gather).
_cached_local_world_size: Optional[int] = None


def get_process_memory_budget_bytes(
    comm=None, local_world_size: Optional[int] = None
) -> int:
    """Per-process host-memory budget for staging/consuming buffers
    (reference scheduler.py:45-65). ``local_world_size`` (ranks sharing
    this host) may be passed by callers that already gathered hostnames;
    otherwise it is discovered once per process and cached."""
    global _cached_local_world_size
    override = get_memory_budget_override_bytes()
    if override is not None:
        return override
    if local_world_size is not None:
        _cached_local_world_size = local_world_size
    elif _cached_local_world_size is not None:
        local_world_size = _cached_local_world_size
    elif comm is not None and comm.world_size > 1:
        from .knobs import get_node_name

        hostnames = comm.all_gather_object(get_node_name())
        local_world_size = hostnames.count(get_node_name())
        _cached_local_world_size = local_world_size
    else:
        local_world_size = 1
    available = psutil.virtual_memory().available
    budget = int(available * _AVAILABLE_MEMORY_FRACTION / max(local_world_size, 1))
    return min(budget, _MAX_PER_RANK_MEMORY_BUDGET_BYTES)


async def _cancel_and_drain(tasks: Set[asyncio.Task]) -> None:
    """Abort helper shared by the write loop and PendingIOWork: cancel
    in-flight tasks and await them so the loop can close cleanly and no
    write keeps running into an aborted snapshot directory."""
    for task in tasks:
        task.cancel()
    if tasks:
        await asyncio.gather(*tasks, return_exceptions=True)


# Stats of the most recent completed write/read execution in this process,
# keyed by verb ("write"/"read"). Benchmarks and tests read this to get the
# staging-time vs total-time split without parsing logs.
LAST_EXECUTION_STATS: dict = {}


class _Reporter:
    """Periodic pipeline progress logging (reference scheduler.py:96-175):
    per-stage pipeline counts, RSS delta, remaining memory budget, and a
    staging-time vs total-time summary — the observability needed to tell
    a staging-bound pipeline from an I/O-bound one."""

    def __init__(self, rank: int, verb: str, total_reqs: int) -> None:
        self.rank = rank
        self.verb = verb
        self.total_reqs = total_reqs
        self.begin_ts = time.monotonic()
        self.last_report_ts = self.begin_ts
        self.bytes_done = 0
        self.reqs_done = 0
        self.rss_begin = psutil.Process().memory_info().rss
        self.staging_done_ts: Optional[float] = None
        # Live pipeline-stage counts, updated by the execution loop:
        # {stage: count} with stages ready_for_staging/staging/ready_for_io/io.
        self.stage_counts: dict = {}
        self.budget_remaining: Optional[int] = None
        self.total_budget: Optional[int] = None
        # Pipelined async takes: wall-clock of the blocked window (first
        # window staged, control returned) and how many staging windows
        # the take ran in total.
        self.blocked_done_ts: Optional[float] = None
        self.stage_windows: Optional[int] = None

    def mark_staging_complete(self) -> None:
        if self.staging_done_ts is None:
            self.staging_done_ts = time.monotonic()

    def mark_blocked_window_done(self) -> None:
        if self.blocked_done_ts is None:
            self.blocked_done_ts = time.monotonic()

    def report_request_done(self, nbytes: int) -> None:
        self.reqs_done += 1
        self.bytes_done += nbytes
        now = time.monotonic()
        if now - self.last_report_ts >= _REPORT_INTERVAL_SEC:
            self.last_report_ts = now
            rss_delta = psutil.Process().memory_info().rss - self.rss_begin
            counts = " ".join(
                f"{k}={v}" for k, v in self.stage_counts.items()
            )
            budget = (
                f", budget {self.budget_remaining / 1e9:.1f}/"
                f"{self.total_budget / 1e9:.1f} GB free"
                if self.budget_remaining is not None
                and self.total_budget is not None
                else ""
            )
            logger.info(
                "Rank %d: %s %d/%d reqs [%s done=%d], %.2f GB, %.1f MB/s, "
                "rss delta %.0f MB%s",
                self.rank,
                self.verb,
                self.reqs_done,
                self.total_reqs,
                counts,
                self.reqs_done,
                self.bytes_done / 1e9,
                self.bytes_done / 1e6 / max(now - self.begin_ts, 1e-9),
                rss_delta / 1e6,
                budget,
            )

    def summarize(self) -> None:
        end_ts = time.monotonic()
        elapsed = max(end_ts - self.begin_ts, 1e-9)
        staging_elapsed = (
            max(self.staging_done_ts - self.begin_ts, 0.0)
            if self.staging_done_ts is not None
            else None
        )
        stats = {
            "reqs": self.reqs_done,
            "bytes": self.bytes_done,
            "total_s": elapsed,
            "staging_s": staging_elapsed,
            "throughput_mbps": self.bytes_done / 1e6 / elapsed,
            "budget_bytes": self.total_budget,
        }
        if self.blocked_done_ts is not None:
            stats["blocked_s"] = max(self.blocked_done_ts - self.begin_ts, 0.0)
        if self.stage_windows is not None:
            stats["stage_windows"] = self.stage_windows
        LAST_EXECUTION_STATS[self.verb] = stats
        if staging_elapsed is not None:
            # The number async_take exists to minimize: training is blocked
            # only for the staging window, not the full I/O drain.
            logger.info(
                "Rank %d: %s complete: %d reqs, %.2f GB in %.2fs "
                "(%.1f MB/s); staging %.2fs / residual I/O %.2fs",
                self.rank,
                self.verb,
                self.reqs_done,
                self.bytes_done / 1e9,
                elapsed,
                self.bytes_done / 1e6 / elapsed,
                staging_elapsed,
                elapsed - staging_elapsed,
            )
        else:
            logger.info(
                "Rank %d: %s complete: %d reqs, %.2f GB in %.2fs (%.1f MB/s)",
                self.rank,
                self.verb,
                self.reqs_done,
                self.bytes_done / 1e9,
                elapsed,
                self.bytes_done / 1e6 / elapsed,
            )


class _ProbeRunner:
    """In-take/in-restore roofline probes (``TPUSNAP_PROBE=1``):
    between I/O windows — once per TPUSNAP_PROBE_INTERVAL_BYTES of
    payload traffic, while no blob I/O is in flight — write (then read
    back, then delete) TPUSNAP_PROBE_BYTES of raw data through the
    operation's OWN storage plugin stack, across a few concurrent
    streams, and record the aggregate throughput as a probe sample.
    Each sample times BOTH legs: the take's summary derives
    ``roofline_fraction`` from the write leg, the restore's
    ``restore_roofline_fraction`` from the read leg — ceilings measured
    seconds (not minutes) from the I/O they judge, immune to the
    multi-minute disk drift that made separate full-scale roofline
    sessions scatter 3x (ROADMAP 5a). On the restore side the probe
    still writes its own scratch (the snapshot's blobs are immutable),
    under ``.tpusnap/probe/`` (journal-exempt sidecar space; a crash's
    leftovers are orphan-visible to fsck/gc). Failures never fail the
    take or restore — a failed probe is one missing sample."""

    _STREAMS = 4

    def __init__(
        self,
        storage: StoragePlugin,
        rank: int,
        tele: telemetry.TakeTelemetry,
    ) -> None:
        from .knobs import get_probe_bytes, get_probe_interval_bytes

        self.storage = storage
        self.rank = rank
        self.tele = tele
        self.interval_bytes = get_probe_interval_bytes()
        self.stream_bytes = max(get_probe_bytes() // self._STREAMS, 1 << 20)
        self.bytes_since_probe = 0
        self.ran = 0
        self._buf: Optional[memoryview] = None
        self._failed = False
        from . import compress as _compress

        try:
            # Same device/bucket-scoped key the auto policy looks up —
            # NOT the bare class label (two fs:// mounts with different
            # bandwidth must not share a ceiling sample).
            self._label = _compress.pipe_ceiling_key(storage)
        except Exception:
            self._label = ""

    @property
    def due(self) -> bool:
        return not self._failed and self.bytes_since_probe >= self.interval_bytes

    def note_written(self, nbytes: int) -> None:
        self.bytes_since_probe += nbytes

    def _buffer(self) -> memoryview:
        if self._buf is None:
            # Random-ish payload (tiled 1 MiB urandom block): constant
            # fill could be flattered by host-side image compression
            # and would not match what the take writes.
            block = _os.urandom(1 << 20)
            reps = (self.stream_bytes + len(block) - 1) // len(block)
            self._buf = memoryview(block * reps)[: self.stream_bytes]
        return self._buf

    def _path(self, i: int) -> str:
        return f"{PROBE_DIR}/rank_{self.rank}_{i}.bin"

    async def run(self) -> None:
        """One probe segment. Caller guarantees no blob I/O in flight
        (the scheduler parks its I/O gate until the window drains), so
        the sample measures the engine, not contention with the take."""
        self.bytes_since_probe = 0
        start = self.tele.now()
        nbytes = self.stream_bytes * self._STREAMS
        try:
            buf = self._buffer()
            paths = [self._path(i) for i in range(self._STREAMS)]
            t0 = time.monotonic()
            await asyncio.gather(
                *(self.storage.write(WriteIO(path=p, buf=buf)) for p in paths)
            )
            write_s = time.monotonic() - t0
            t0 = time.monotonic()
            await asyncio.gather(
                *(self.storage.read(ReadIO(path=p)) for p in paths)
            )
            read_s = time.monotonic() - t0
            await asyncio.gather(
                *(self.storage.delete(p) for p in paths),
                return_exceptions=True,
            )
        except Exception:
            # One WARNING, then stand down for this take: a backend
            # that cannot take probe traffic must not eat a retry storm.
            # Best-effort cleanup of any stream that did land (a
            # leftover would only be orphan debris for gc, but tidy is
            # cheaper than debris).
            self._failed = True
            logger.warning(
                "Rank %d: in-take roofline probe failed (non-fatal; "
                "disabled for the rest of this take)",
                self.rank,
                exc_info=True,
            )
            try:
                await asyncio.gather(
                    *(
                        self.storage.delete(self._path(i))
                        for i in range(self._STREAMS)
                    ),
                    return_exceptions=True,
                )
            except Exception:
                pass
            return
        elapsed = self.tele.now() - start
        sample = {
            "write_gbps": round(nbytes / max(write_s, 1e-9) / 1e9, 4),
            "read_gbps": round(nbytes / max(read_s, 1e-9) / 1e9, 4),
            "bytes": nbytes,
            "elapsed_s": round(elapsed, 6),
        }
        self.ran += 1
        # Feed the compression auto policy's ceiling registry: every
        # probe sample keeps the pipe ceiling a live measurement, so
        # the next take's compress-or-bypass decision is current.
        from . import compress as _compress

        _compress.note_pipe_ceiling(self._label, sample["write_gbps"])
        _compress.note_pipe_ceiling(
            self._label, sample["read_gbps"], lane="read"
        )
        self.tele.add_probe_sample(sample)
        self.tele.record_span("probe_roofline", start, elapsed, **sample)
        telemetry.incr("probe.probes", rec=self.tele)
        telemetry.incr("probe.bytes_written", nbytes, rec=self.tele)
        flight.record(
            "probe",
            write_gbps=sample["write_gbps"],
            read_gbps=sample["read_gbps"],
            bytes=nbytes,
        )


@dataclass
class PendingIOWork:
    """Work remaining after the blocked window closed (reference
    scheduler.py:178-217). ``complete`` resumes the same stage ∥ write
    engine: residual STAGING windows of a pipelined async take first
    (interleaved with their storage I/O), then the I/O drain — honoring
    the same budget and concurrency caps throughout."""

    scheduler: "_WriteScheduler"

    def staging_complete(self) -> bool:
        """Whether ALL staging is done (buffers no longer alias live
        arrays). True at construction except for pipelined async takes,
        whose residual windows stage inside ``complete``."""
        return self.scheduler.staging_complete

    def wait_staged(self, timeout: Optional[float] = None) -> bool:
        return self.scheduler.staging_done_event.wait(timeout)

    def drained(self) -> bool:
        """Whether THIS RANK's write drain (all writes + COW verifies)
        finished — under COW this, not staging-complete, is when live
        bytes stop being read."""
        return self.scheduler.drained_event.is_set()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self.scheduler.drained_event.wait(timeout)

    async def complete(self) -> None:
        await self.scheduler.drain()

    def sync_complete(self, event_loop: asyncio.AbstractEventLoop) -> None:
        # run_on_loop: the commit path reuses this loop for the metadata
        # write and close afterwards — a stranded task would be resumed
        # mid-commit.
        run_on_loop(event_loop, self.complete())


class _WritePipeline:
    def __init__(
        self,
        write_req: WriteReq,
        storage: StoragePlugin,
        executor: Optional[ThreadPoolExecutor] = None,
        hash_executor: Optional[ThreadPoolExecutor] = None,
        tele: Optional[telemetry.TakeTelemetry] = None,
    ) -> None:
        self.write_req = write_req
        self.storage = storage
        self.executor = executor
        self.tele = tele
        # Deferred checksums run here, NEVER on the staging executor:
        # queued hash jobs behind staging tasks would stall staging
        # completion — the async blocked window — behind work that was
        # deferred precisely to leave that window (measured at 20 GB:
        # staging_s 50 s of a 52 s take with the shared 1-worker pool).
        self.hash_executor = hash_executor or executor
        self.staging_cost = write_req.buffer_stager.get_staging_cost_bytes()
        self.buf = None
        self.buf_size = 0
        # True when the stager reported the content is already persisted
        # (incremental dedup): the request completes with no storage I/O.
        self.skipped = False

    async def stage(self, executor: ThreadPoolExecutor) -> "_WritePipeline":
        from .io_types import SKIP_WRITE

        start = self.tele.now() if self.tele is not None else 0.0
        token = self.tele.op_enter("stage_buffer") if self.tele is not None else None
        try:
            buf = await self.write_req.buffer_stager.stage_buffer(executor)
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        if self.tele is not None:
            self.tele.record_span(
                "stage_buffer",
                start,
                self.tele.now() - start,
                path=self.write_req.path,
                bytes=self.staging_cost,
            )
        if buf is SKIP_WRITE:
            self.skipped = True
            telemetry.incr("scheduler.dedup_skipped", rec=self.tele)
            # Byte-grain leg of the skip counter: the dual-hash pass
            # proved these planned payload bytes unchanged against the
            # base — the SLO tracker's data-at-risk accounting subtracts
            # them live (tpusnap.slo).
            telemetry.incr(
                "scheduler.dedup_skipped_bytes",
                self.write_req.buffer_stager.get_planned_bytes(),
                rec=self.tele,
            )
            return self
        self.buf = buf
        self.buf_size = (
            memoryview(self.buf).cast("B").nbytes if self.buf is not None else 0
        )
        return self

    async def write(self) -> "_WritePipeline":
        stager = self.write_req.buffer_stager
        if getattr(stager, "defer_checksums", False) and self.buf is not None:
            # Deferred hashing (single-process, non-incremental takes):
            # checksums computed HERE, on the write path — overlapping
            # other requests' disk time instead of occupying the staging
            # window async_take blocks training on. The values land in
            # the same entry objects the manifest references, before the
            # post-drain metadata commit.
            late = getattr(stager, "late_checksum", None)
            if late is not None:
                hash_start = self.tele.now() if self.tele is not None else 0.0
                loop = asyncio.get_running_loop()
                if self.hash_executor is not None:
                    await loop.run_in_executor(
                        self.hash_executor, late, self.buf
                    )
                else:
                    late(self.buf)
                if self.tele is not None:
                    self.tele.record_span(
                        "checksum_late",
                        hash_start,
                        self.tele.now() - hash_start,
                        bytes=self.buf_size,
                    )
        write_start = self.tele.now() if self.tele is not None else 0.0
        token = (
            self.tele.op_enter("storage_write") if self.tele is not None else None
        )
        try:
            await self.storage.write(
                WriteIO(path=self.write_req.path, buf=self.buf)
            )
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        if self.tele is not None:
            self.tele.record_span(
                "storage_write",
                write_start,
                self.tele.now() - write_start,
                path=self.write_req.path,
                bytes=self.buf_size,
            )
        telemetry.incr("storage.bytes_written", self.buf_size, rec=self.tele)
        telemetry.incr("storage.writes", rec=self.tele)
        if getattr(stager, "cow_pending", False):
            # Copy-on-write staging (TPUSNAP_ASYNC_COW): the buffer just
            # written IS the live array — re-hash it and compare with
            # the checksum recorded inside the blocked window. A
            # mismatch means the caller mutated the array mid-take; the
            # take must fail loudly rather than commit torn bytes.
            cow_start = self.tele.now() if self.tele is not None else 0.0
            loop = asyncio.get_running_loop()
            if self.hash_executor is not None:
                await loop.run_in_executor(
                    self.hash_executor, stager.verify_cow_after_write, self.buf
                )
            else:
                stager.verify_cow_after_write(self.buf)
            if self.tele is not None:
                self.tele.record_span(
                    "cow_verify",
                    cow_start,
                    self.tele.now() - cow_start,
                    bytes=self.buf_size,
                )
        # Async-clone buffers go back to the staging pool (warm pages
        # for the next clone of this size); other buffers are ignored by
        # release(). The pool is bounded by TPUSNAP_STAGING_POOL_BYTES,
        # not by this take's budget — see execute_write_reqs.
        from ._staging_pool import release

        release(self.buf)
        self.buf = None  # release host memory
        return self


class _WriteScheduler:
    """Resumable budget-gated stage ∥ write engine behind every take.

    One instance owns the whole pipeline state (request queue, in-flight
    staging/IO task sets, budget). ``run_blocked_window`` advances it to
    the take's blocked-window boundary on the calling thread;
    ``drain`` (via :class:`PendingIOWork`) resumes the SAME loop — on
    the same event loop, possibly from a background thread — until every
    request is staged AND written. Three modes:

    - default (sync takes): blocked window = staging complete, staging
      and storage I/O fully overlapped throughout (the metric is total
      time; disk DMA waits overlap staging profitably even on one core).
    - ``prioritize_staging`` (incremental async takes, whose dedup
      decisions must be final before the manifest gather): blocked
      window = staging complete, and NO storage I/O is dispatched while
      staging can still proceed — concurrent write-path work (checksums,
      bounce copies, syscalls) steals core time from the staging pass
      and stretches the blocked window several-fold (measured 2.8s vs a
      0.5s pure clone pass on the 1-core dev host). I/O IS dispatched
      mid-staging when staging is budget-starved (writes must complete
      to free budget — deadlock freedom).
    - ``pipelined_staging`` (async takes): the in-flight staging budget
      is clamped to TPUSNAP_ASYNC_STAGE_WINDOW_BYTES and the blocked
      window ends at FIRST-WINDOW-STAGED — the engine has staged one
      window's worth of requests and proven the pipeline flows; the
      drain then clones window N+1 while window N's writes release
      buffers (and budget) back, so blocked time and clone RSS are both
      O(window) instead of O(state). ``stage_eagerly`` selects requests
      that must still stage INSIDE the blocked window (multi-process
      takes: stagers that annotate manifest entries at stage time, whose
      values would otherwise miss the by-value manifest gather). The I/O
      gate stays shut during the blocked window exactly as in
      prioritize mode, and opens permanently once control returns.
    """

    def __init__(
        self,
        write_reqs: List[WriteReq],
        storage: StoragePlugin,
        memory_budget_bytes: int,
        rank: int,
        prioritize_staging: bool = False,
        pipelined_staging: bool = False,
        stage_eagerly: Optional[Callable[[WriteReq], bool]] = None,
        tele: Optional[telemetry.TakeTelemetry] = None,
    ) -> None:
        from .knobs import (
            get_async_stage_window_bytes,
            get_stage_threads,
            is_probe_enabled,
        )

        self.storage = storage
        self.rank = rank
        # In-take roofline probes: only with an enabled recorder (their
        # whole output is telemetry) and the opt-in knob.
        self.probe: Optional[_ProbeRunner] = (
            _ProbeRunner(storage, rank, tele)
            if tele is not None and tele.enabled and is_probe_enabled()
            else None
        )
        self.prioritize_staging = prioritize_staging
        self.pipelined = pipelined_staging
        self.tele = tele
        # TPUSNAP_STAGE_THREADS sizes BOTH the executor and the dispatch
        # cap: staging threads do memory-bandwidth work (memcpy, CRC,
        # deserialize) with the GIL released, and more threads than the
        # memory system feeds only adds cache ping-pong (measured on the
        # 1-vCPU dev host: 4 interleaved clone threads ran ~1 GB/s
        # aggregate vs ~4 GB/s for one).
        self.stage_concurrency = get_stage_threads()
        self.executor = ThreadPoolExecutor(
            max_workers=self.stage_concurrency,
            thread_name_prefix="tpusnap-stage",
        )
        # Deferred write-path hashing gets its own pool so it can never
        # queue ahead of staging tasks (see _WritePipeline.hash_executor).
        self.hash_executor = ThreadPoolExecutor(
            max_workers=_MAX_CPU_CONCURRENCY, thread_name_prefix="tpusnap-hash"
        )
        self.reporter = _Reporter(
            rank=rank, verb="write", total_reqs=len(write_reqs)
        )
        pls = [
            _WritePipeline(wr, storage, self.executor, self.hash_executor, tele)
            for wr in write_reqs
        ]
        cost_key = lambda p: p.staging_cost  # noqa: E731
        if self.pipelined and stage_eagerly is not None:
            # Eager requests lead the queue: they must be staged before
            # the blocked window may close. Within each group, large
            # first — they occupy budget longest and their I/O overlaps
            # the staging of everything behind them.
            eager = sorted(
                (p for p in pls if stage_eagerly(p.write_req)),
                key=cost_key,
                reverse=True,
            )
            rest = sorted(
                (p for p in pls if not stage_eagerly(p.write_req)),
                key=cost_key,
                reverse=True,
            )
            self.pipelines = deque(eager + rest)
            # Identity set, not a count: with TPUSNAP_STAGE_THREADS >= 2
            # an interleaved NON-eager stager can complete first, and a
            # bare countdown would let the blocked window close while an
            # eager (manifest-annotating) stager is still in flight.
            self.eager_pending = {id(p) for p in eager}
        else:
            self.pipelines = deque(sorted(pls, key=cost_key, reverse=True))
            self.eager_pending = set()
        total_cost = sum(p.staging_cost for p in pls)
        if self.pipelined:
            window = get_async_stage_window_bytes()
            if window is not None:
                # The window IS the effective in-flight staging budget:
                # resident clone bytes never exceed it (plus the ≥1
                # over-budget admission), whatever the host-RAM budget
                # would allow.
                memory_budget_bytes = min(memory_budget_bytes, window)
        # The budget governs IN-FLIGHT staging buffers: every dispatch
        # debits staging_cost, every write completion credits buf_size —
        # unconditionally. Buffers the staging pool retains after a
        # write are NOT withheld from the credit (ADVICE r4: withholding
        # re-debited the same resident bytes every reuse cycle, and a
        # budget-capped take whose cumulative clone bytes exceeded the
        # budget degraded to fully serialized stage-then-write) — the
        # pool is its own separately bounded cache: worst-case resident
        # is budget + TPUSNAP_STAGING_POOL_BYTES, and in practice ≈
        # budget, because acquire() reuses parked buffers of recurring
        # sizes (uniform chunk sizes within a take — which is also what
        # lets window N+1's clones recycle window N's released buffers
        # so steady-state windows allocate nothing).
        self.memory_budget_bytes = memory_budget_bytes
        self.budget = memory_budget_bytes
        self.reporter.total_budget = memory_budget_bytes
        # First-window target: the blocked window stages at least this
        # much staging cost (everything, when the state fits the window).
        self.first_window_target = min(memory_budget_bytes, total_cost)
        self.staging_tasks: Set[asyncio.Task] = set()
        self.io_tasks: Set[asyncio.Task] = set()
        self.ready_for_io: List[_WritePipeline] = []
        self.staged_cost_total = 0
        # I/O gate state for pipelined mode: shut during the blocked
        # window, open forever after.
        self.blocked = self.pipelined
        self.staging_complete = False
        self.staging_done_event = threading.Event()
        # Set when THIS RANK's write drain (all writes + COW verifies)
        # finishes — the COW-mode safe-to-mutate boundary, strictly
        # earlier than the cross-rank commit barrier.
        self.drained_event = threading.Event()
        self._stall_start: Optional[float] = None
        self._stage_phase_start = tele.now() if tele is not None else 0.0
        self._window_index = 0
        self._window_start = self._stage_phase_start
        self._window_accum = 0

    # --- dispatch ------------------------------------------------------

    def _dispatch_staging(self) -> None:
        while self.pipelines and len(self.staging_tasks) < self.stage_concurrency:
            head = self.pipelines[0]
            # The ≥1 over-budget admission may only fire when NOTHING
            # can free budget: staged buffers waiting in ready_for_io
            # count in EVERY mode — they hold budget that the write
            # dispatched on the next loop turn will credit back.
            # Admitting over budget past them held every staged buffer
            # resident at once (observed as peak 3/2 budget whenever all
            # in-flight stagings completed in one wait batch before any
            # I/O was dispatched) and unenforced the budget entirely.
            in_flight = self.staging_tasks or self.io_tasks or self.ready_for_io
            if head.staging_cost > self.budget and in_flight:
                break  # wait for memory to free up
            self.pipelines.popleft()
            self.budget -= head.staging_cost
            if self.tele is not None:
                # High-water mark of budget in use (can exceed the
                # budget via the ≥1 over-budget admission).
                self.tele.gauge_max(
                    "scheduler.budget_used_bytes",
                    self.memory_budget_bytes - self.budget,
                )
            self.staging_tasks.add(
                asyncio.ensure_future(head.stage(self.executor))
            )

    def _staging_budget_starved(self) -> bool:
        return (
            bool(self.pipelines)
            and len(self.staging_tasks) < self.stage_concurrency
            and self.pipelines[0].staging_cost > self.budget
        )

    def _io_gate_open(self) -> bool:
        if self.staging_complete:
            return True  # nothing left to prioritize; drain freely
        if self.pipelined:
            if not self.blocked:
                return True
        elif not self.prioritize_staging:
            return True
        # Blocked window (pipelined) / staging-priority mode: open ONLY
        # while staging is budget-starved (requests pending but none
        # runnable) — write completions are the only budget source.
        return bool(self.pipelines and not self.staging_tasks)

    def _probe_may_run(self) -> bool:
        # NEVER inside a pipelined take's blocked window: a probe there
        # would bill its I/O to async_blocked_s — the exact metric
        # async_take exists to minimize and history --check gates.
        # Probes wait for the background drain.
        return self.probe is not None and self.probe.due and not self.blocked

    def _dispatch_io(self) -> None:
        if self._probe_may_run():
            # Park new blob I/O: the in-flight window drains, the loop
            # runs the probe against an idle engine, then reopens.
            return
        if not self._io_gate_open():
            return
        while self.ready_for_io and len(self.io_tasks) < _MAX_IO_CONCURRENCY:
            self.io_tasks.add(
                asyncio.ensure_future(self.ready_for_io.pop(0).write())
            )

    async def _maybe_probe(self) -> None:
        """Run one due probe segment while no blob write is in flight
        (the only moment a probe measures the engine, not contention).
        Called before every I/O dispatch in the pump/drain loops."""
        if self._probe_may_run() and not self.io_tasks:
            await self.probe.run()

    def _update_reporter(self) -> None:
        self.reporter.stage_counts = {
            "ready_for_staging": len(self.pipelines),
            "staging": len(self.staging_tasks),
            "ready_for_io": len(self.ready_for_io),
            "io": len(self.io_tasks),
        }
        self.reporter.budget_remaining = self.budget

    # --- window / stall bookkeeping ------------------------------------

    def _note_stall(self) -> None:
        # Budget-stall EPISODES, not wait iterations: one span + counter
        # per contiguous window in which the head request cannot be
        # admitted, however many task completions the window spans.
        if self._staging_budget_starved():
            if self._stall_start is None:
                self._stall_start = (
                    self.tele.now() if self.tele is not None else 0.0
                )
                telemetry.incr("scheduler.budget_waits", rec=self.tele)
        elif self._stall_start is not None:
            if self.tele is not None:
                self.tele.record_span(
                    "budget_wait",
                    self._stall_start,
                    self.tele.now() - self._stall_start,
                )
            self._stall_start = None

    def _on_staged(self, pipeline: "_WritePipeline") -> None:
        self.staged_cost_total += pipeline.staging_cost
        if not self.pipelined:
            return
        self._window_accum += pipeline.staging_cost
        if self._window_accum >= self.memory_budget_bytes:
            self._close_window()

    def _close_window(self) -> None:
        """Record one per-window ``stage_window`` span (the blocked
        window is window 0 — measurable on its own in the trace)."""
        if self._window_accum <= 0:
            return
        if self.tele is not None:
            now = self.tele.now()
            self.tele.record_span(
                "stage_window",
                self._window_start,
                now - self._window_start,
                window=self._window_index,
                bytes=self._window_accum,
            )
            self._window_start = now
        self._window_index += 1
        self._window_accum = 0

    def _first_window_done(self) -> bool:
        return (
            not self.eager_pending
            and self.staged_cost_total >= self.first_window_target
        )

    def _finish_staging(self) -> None:
        if self.staging_complete:
            return
        self.staging_complete = True
        self.reporter.mark_staging_complete()
        if self._stall_start is not None:
            if self.tele is not None:
                self.tele.record_span(
                    "budget_wait",
                    self._stall_start,
                    self.tele.now() - self._stall_start,
                )
            self._stall_start = None
        if self.pipelined:
            self._close_window()
            self.reporter.stage_windows = max(self._window_index, 1)
        elif self.tele is not None:
            # Interior measurement of the staging window (the "stage"
            # PHASE is recorded by the take around the whole
            # sync_execute call).
            self.tele.record_span(
                "stage_window",
                self._stage_phase_start,
                self.tele.now() - self._stage_phase_start,
                reqs=self.reporter.total_reqs,
            )
        self.staging_done_event.set()

    # --- the loop ------------------------------------------------------

    async def _pump(self, stop_at_first_window: bool) -> None:
        self._dispatch_staging()
        while self.staging_tasks or self.pipelines:
            if stop_at_first_window and self._first_window_done():
                return
            self._note_stall()
            done, _ = await asyncio.wait(
                self.staging_tasks | self.io_tasks,
                return_when=asyncio.FIRST_COMPLETED,
            )
            for task in done:
                if task in self.staging_tasks:
                    self.staging_tasks.discard(task)
                    pipeline = task.result()  # re-raises staging failure
                    # Staged buffer may be smaller than the staging cost
                    # (e.g. cost model overestimates); credit the
                    # difference.
                    self.budget += pipeline.staging_cost - pipeline.buf_size
                    self.eager_pending.discard(id(pipeline))
                    # Heartbeat feed: bytes past the staging stage (the
                    # window async_take blocks training on).
                    telemetry.incr(
                        "scheduler.bytes_staged",
                        pipeline.buf_size,
                        rec=self.tele,
                    )
                    self._on_staged(pipeline)
                    if pipeline.skipped:
                        # Dedup'd against a previous snapshot: no I/O.
                        self.reporter.report_request_done(0)
                    else:
                        self.ready_for_io.append(pipeline)
                elif task in self.io_tasks:
                    self.io_tasks.discard(task)
                    pipeline = task.result()
                    self.budget += pipeline.buf_size
                    if self.probe is not None:
                        self.probe.note_written(pipeline.buf_size)
                    self.reporter.report_request_done(pipeline.buf_size)
            # Staging first: the I/O gate must see the REFILLED staging
            # set, or it opens spuriously in the instant between one
            # stager finishing and the next starting.
            self._dispatch_staging()
            await self._maybe_probe()
            self._dispatch_io()
            self._update_reporter()
        self._finish_staging()

    async def _abort(self) -> None:
        await _cancel_and_drain(self.staging_tasks | self.io_tasks)
        self.executor.shutdown(wait=True)
        self.hash_executor.shutdown(wait=True)

    async def run_blocked_window(self) -> None:
        """Advance to the blocked-window boundary: staging-complete
        (sync / staging-priority modes) or first-window-staged
        (pipelined mode). In-flight tasks stay parked on the event loop
        for ``drain`` to resume."""
        try:
            await self._pump(stop_at_first_window=self.pipelined)
        except BaseException:
            await self._abort()
            raise
        self.reporter.mark_blocked_window_done()
        if self.pipelined:
            self.blocked = False  # I/O gate opens for the drain
            if self.tele is not None:
                self.tele.record_span(
                    "stage_blocked",
                    self._stage_phase_start,
                    self.tele.now() - self._stage_phase_start,
                    reqs=self.reporter.total_reqs,
                    staged_cost=self.staged_cost_total,
                )

    async def drain(self) -> None:
        """Resume to completion: residual staging windows (interleaved
        with their writes), then the storage I/O drain."""
        try:
            await self._pump(stop_at_first_window=False)
            while self.io_tasks or self.ready_for_io:
                await self._maybe_probe()
                self._dispatch_io()
                done, _ = await asyncio.wait(
                    self.io_tasks, return_when=asyncio.FIRST_COMPLETED
                )
                for task in done:
                    self.io_tasks.discard(task)
                    pipeline = task.result()
                    self.budget += pipeline.buf_size
                    if self.probe is not None:
                        self.probe.note_written(pipeline.buf_size)
                    self.reporter.report_request_done(pipeline.buf_size)
                self._update_reporter()
            if (
                self.probe is not None
                and self.probe.ran == 0
                and not self.probe._failed
                and self.reporter.bytes_done > 0
            ):
                # A take smaller than the probe interval still gets ONE
                # sample: "every take self-measures its ceiling" must
                # not silently exclude small takes.
                await self.probe.run()
        except BaseException:
            await self._abort()
            raise
        finally:
            self.executor.shutdown(wait=True)
            self.hash_executor.shutdown(wait=True)
        self.drained_event.set()
        self.reporter.summarize()


async def execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    prioritize_staging: bool = False,
    pipelined_staging: bool = False,
    stage_eagerly: Optional[Callable[[WriteReq], bool]] = None,
) -> PendingIOWork:
    """Run the write engine to its blocked-window boundary and hand the
    rest back as :class:`PendingIOWork` (see :class:`_WriteScheduler`
    for the three modes). ``take`` drains the returned work in the
    foreground; ``async_take`` on a background thread."""
    # Captured once: the drain (PendingIOWork) and late hashing may run
    # on a background thread after a newer take replaced the ambient
    # recorder.
    tele = telemetry.current()
    sched = _WriteScheduler(
        write_reqs,
        storage,
        memory_budget_bytes,
        rank,
        prioritize_staging=prioritize_staging,
        pipelined_staging=pipelined_staging,
        stage_eagerly=stage_eagerly,
        tele=tele,
    )
    await sched.run_blocked_window()
    return PendingIOWork(scheduler=sched)


def sync_execute_write_reqs(
    write_reqs: List[WriteReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
    prioritize_staging: bool = False,
    pipelined_staging: bool = False,
    stage_eagerly: Optional[Callable[[WriteReq], bool]] = None,
) -> PendingIOWork:
    return run_on_loop(
        event_loop,
        execute_write_reqs(
            write_reqs,
            storage,
            memory_budget_bytes,
            rank,
            prioritize_staging=prioritize_staging,
            pipelined_staging=pipelined_staging,
            stage_eagerly=stage_eagerly,
        ),
    )


class _ReadPipeline:
    def __init__(
        self,
        read_req: ReadReq,
        storage: StoragePlugin,
        tele: Optional[telemetry.TakeTelemetry] = None,
        ledger: Optional[access.AccessLedger] = None,
    ) -> None:
        self.read_req = read_req
        self.storage = storage
        self.tele = tele
        self.ledger = ledger
        # In-place reads allocate no full-size scratch buffer (bytes land
        # in the caller-owned restore target), so they are charged only
        # the plugin's transient overhead — the fs engine's per-stream
        # bounce buffers, a cloud plugin's download chunk — instead of
        # the blob size. This is what lets a multi-GB tensor restore in
        # place under a small memory budget without serializing every
        # stream.
        cost = read_req.buffer_consumer.get_consuming_cost_bytes()
        if read_req.into is not None and storage.supports_in_place_reads:
            cost = min(cost, storage.in_place_read_overhead_bytes(cost))
        self.consuming_cost = cost
        self.read_io: Optional[ReadIO] = None
        self.read_nbytes = 0

    def _read_nbytes(self) -> int:
        br = self.read_req.byte_range
        if br is not None:
            return int(br[1] - br[0])
        if self.read_io is not None and self.read_io.buf is not None:
            try:
                return self.read_io.buf.getbuffer().nbytes
            except Exception:
                pass
        return self.consuming_cost

    async def read(self) -> "_ReadPipeline":
        self.read_io = ReadIO(
            path=self.read_req.path,
            byte_range=self.read_req.byte_range,
            into=self.read_req.into,
            want_crc=self.read_req.want_crc,
        )
        start = self.tele.now() if self.tele is not None else 0.0
        token = (
            self.tele.op_enter("storage_read") if self.tele is not None else None
        )
        try:
            await self.storage.read(self.read_io)
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        nbytes = self._read_nbytes()
        self.read_nbytes = nbytes
        if self.tele is not None:
            self.tele.record_span(
                "storage_read",
                start,
                self.tele.now() - start,
                path=self.read_req.path,
                bytes=nbytes,
            )
        telemetry.incr("storage.bytes_read", nbytes, rec=self.tele)
        telemetry.incr("storage.reads", rec=self.tele)
        self._record_access(nbytes)
        return self

    def _record_access(self, nbytes: int) -> None:
        """Attribute this physical read to the manifest leaf (or, for a
        batcher-merged spanning read, each member leaf) in the ambient
        access ledger. Plugins that redirected the read stamped the
        source tier on the ReadIO."""
        ledger = self.ledger
        if ledger is None:
            return
        rr = self.read_req
        source = self.read_io.source if self.read_io is not None else None
        if rr.access_parts:
            for lp, start, end in rr.access_parts:
                ledger.record(
                    lp, rr.path, start, end, end - start, source
                )
            return
        if not rr.logical_path:
            return
        start, end = rr.byte_range if rr.byte_range else (0, nbytes)
        ledger.record(
            rr.logical_path, rr.path, start, end, nbytes, source
        )

    async def consume(self, executor: ThreadPoolExecutor) -> "_ReadPipeline":
        # "consume" covers deserialize + the copy/`device_put` into the
        # restore target (the HtoD leg for jax targets).
        start = self.tele.now() if self.tele is not None else 0.0
        token = self.tele.op_enter("consume") if self.tele is not None else None
        try:
            await self.read_req.buffer_consumer.consume_read_io(
                self.read_io, executor
            )
        finally:
            if self.tele is not None:
                self.tele.op_exit(token)
        if self.tele is not None:
            self.tele.record_span(
                "consume",
                start,
                self.tele.now() - start,
                path=self.read_req.path,
                bytes=self.consuming_cost,
            )
        self.read_io = None  # release
        return self


async def execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
) -> None:
    executor = ThreadPoolExecutor(
        max_workers=_MAX_CPU_CONCURRENCY, thread_name_prefix="tpusnap-consume"
    )
    reporter = _Reporter(rank=rank, verb="read", total_reqs=len(read_reqs))
    # Ambient recorder (the restore path installs one thread-locally);
    # None for uninstrumented callers (verify's own engine, read_object
    # outside a recorder) — spans then skip, counters stay global.
    tele = telemetry.current()
    # Ambient access ledger (same pattern): installed by the restore /
    # read_object scopes; None means attribution is off for this call.
    ledger = access.current()
    pipelines = deque(
        sorted(
            (_ReadPipeline(rr, storage, tele, ledger) for rr in read_reqs),
            key=lambda p: p.consuming_cost,
            reverse=True,
        )
    )
    budget = memory_budget_bytes
    read_tasks: Set[asyncio.Task] = set()
    consume_tasks: Set[asyncio.Task] = set()
    # In-restore roofline probes (TPUSNAP_PROBE=1): the same runner the
    # write scheduler uses — a probe segment writes its own scratch
    # streams under .tpusnap/probe/ and times both legs, so the READ leg
    # measured through this restore's composed plugin stack becomes the
    # ceiling `restore_roofline_fraction` divides by. Cadence counts
    # payload bytes READ; a probe never overlaps blob reads (dispatch
    # parks while one is due) and never consumes memory budget.
    from .knobs import is_probe_enabled

    probe = (
        _ProbeRunner(storage, rank, tele)
        if tele is not None and tele.enabled and is_probe_enabled()
        else None
    )

    # NOTE on destination prefaulting: a background thread first-touching
    # not-yet-dispatched ``into`` buffers (overlapping page faults with
    # the reads) was tried and MEASURED A LOSS on the 1-vCPU dev host
    # (20 GB restore: 88 s with, 55 s without) — the toucher competes for
    # the one core the bounce copies and fused CRCs run on, and its zero
    # writes evict cache the reads want. Multi-core hosts may differ;
    # revisit with real TPU-VM cores.

    def dispatch_reads() -> None:
        nonlocal budget
        while pipelines and len(read_tasks) < _MAX_IO_CONCURRENCY:
            if probe is not None and probe.due:
                # Park new reads until the in-flight window drains and
                # the probe runs: probe traffic sharing the pipe with
                # blob reads would corrupt both the sample and the
                # storage_read spans analyze attributes.
                break
            head = pipelines[0]
            in_flight = read_tasks or consume_tasks
            if head.consuming_cost > budget and in_flight:
                break
            pipelines.popleft()
            budget -= head.consuming_cost
            read_tasks.add(asyncio.ensure_future(head.read()))

    reporter.total_budget = memory_budget_bytes
    try:
        dispatch_reads()
        while read_tasks or consume_tasks or pipelines:
            done, _ = await asyncio.wait(
                read_tasks | consume_tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task in read_tasks:
                    read_tasks.discard(task)
                    pipeline = task.result()
                    if probe is not None:
                        probe.note_written(pipeline.read_nbytes)
                    consume_tasks.add(
                        asyncio.ensure_future(pipeline.consume(executor))
                    )
                elif task in consume_tasks:
                    consume_tasks.discard(task)
                    pipeline = task.result()
                    budget += pipeline.consuming_cost
                    reporter.report_request_done(pipeline.consuming_cost)
            if probe is not None and probe.due and not read_tasks:
                # The read window drained (consumes may still run —
                # they are CPU-side and don't touch the pipe being
                # measured); take the sample, then dispatch resumes.
                await probe.run()
            dispatch_reads()
            reporter.stage_counts = {
                "ready_for_read": len(pipelines),
                "read": len(read_tasks),
                "consume": len(consume_tasks),
            }
            reporter.budget_remaining = budget
        if (
            probe is not None
            and probe.ran == 0
            and not probe._failed
            and reporter.bytes_done > 0
        ):
            # Restore smaller than the probe interval: still measure
            # once, so no probe-enabled restore is fraction-less.
            await probe.run()
    except BaseException:
        # Mirror the write path: a failed request (e.g. checksum
        # mismatch) must not abandon in-flight tasks — orphans would be
        # resumed by the NEXT run_until_complete on a reused event loop
        # and write into a previous call's caller-owned buffers.
        await _cancel_and_drain(read_tasks | consume_tasks)
        # Task cancellation does not interrupt run_in_executor work: a
        # plugin thread may still be mid-write into a caller-owned
        # in-place destination. Wait it out (off-loop) before the error
        # reaches the caller.
        await asyncio.get_running_loop().run_in_executor(
            None, storage.drain_in_flight
        )
        raise
    finally:
        executor.shutdown(wait=True)
    reporter.summarize()


def sync_execute_read_reqs(
    read_reqs: List[ReadReq],
    storage: StoragePlugin,
    memory_budget_bytes: int,
    rank: int,
    event_loop: asyncio.AbstractEventLoop,
) -> None:
    run_on_loop(
        event_loop,
        execute_read_reqs(read_reqs, storage, memory_budget_bytes, rank),
    )
