"""Performance attribution: critical-path bound analysis + doctor logic.

PRs 2/4/5 record everything (spans, heartbeats, histograms, fleet
counters) and interpret nothing: an operator looking at a slow take
still has to eyeball a Chrome trace to learn whether it was
storage-bound, budget-wait-bound or straggler-bound. This module is the
interpreter behind ``python -m tpusnap analyze <path>``:

- **Critical-path bound analysis** (:func:`attribute_spans`): a
  deterministic sweep over one rank's recorded op spans that attributes
  every instant of take/restore wall-clock to exactly one RESOURCE
  (storage write/read, DtoH, stage/clone, checksum, consume,
  ``budget_wait``, barriers) and emits a bound-by verdict with
  percentages. Attribution semantics (documented in docs/design.md
  "Performance attribution"):

  * instants where storage I/O is in flight attribute to the I/O
    category — in an overlapped pipeline, compute that runs UNDER
    in-flight I/O is hidden by it, so shrinking it cannot shrink the
    take;
  * compute categories (DtoH, checksum, stage, consume) attribute only
    the instants they run with no I/O in flight, in a fixed priority
    order (ties are impossible to break per-instant; the order is the
    tiebreak and it is deterministic);
  * pure waits (``budget_wait``, barriers/KV waits) attribute only the
    instants NOTHING else runs — a budget wait while writes drain IS
    storage-bound (writes are the only budget source);
  * instants covered by no op span are ``unattributed`` (Python glue,
    planning) — the acceptance bar is ≥80% attributed on a real take.

- **Tail-latency outliers**: p99/p50 ratios from the log2 latency
  histograms recorded at the storage-plugin boundary
  (:class:`~tpusnap.telemetry.LogHistogram`) — whole-op spans average
  tails away; the histograms are where a 41x p99 write hides.

- **Straggler ranks**: the rollup's per-phase ``phase_skew``.

- **Roofline**: the in-take probe fraction when recorded
  (``TPUSNAP_PROBE=1``) — how much of the self-measured storage ceiling
  the take actually achieved.

Everything here is pure computation over recorded data (no I/O except
the CLI's loaders in ``__main__``), so the attribution math unit-tests
on synthetic spans with a fake clock.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

# ------------------------------------------------------- classification

# Span names that are CONTAINERS over other ops (windows, blocked-window
# markers, probe segments) or phases — excluded from attribution, which
# must never double-count an instant.
EXCLUDED_SPANS = frozenset(
    {"stage_window", "stage_blocked", "async_blocked", "probe_roofline"}
)

# Resource category per op-span name (prefix match for dotted families).
_CATEGORY_EXACT = {
    "storage_write": "storage_write",
    "storage_read": "storage_read",
    "stage_buffer": "stage",
    "dtoh": "dtoh",
    "host_offload.dtoh": "dtoh",
    "checksum": "checksum",
    "checksum_late": "checksum",
    "cow_verify": "checksum",
    "compress": "compress",
    "consume": "consume",
    "restore.decode": "decode",
    "budget_wait": "budget_wait",
}
_CATEGORY_PREFIX = (
    ("comm.", "barrier"),
    ("kv.", "barrier"),
)

# Work categories, highest attribution priority first: I/O wins every
# overlap (see the module docstring), then the device copy, then the
# host compute lanes.
WORK_PRIORITY = (
    "storage_write",
    "storage_read",
    "dtoh",
    # decode outranks consume: restore.decode spans nest inside their
    # containing consume span, and the nested lane must claim the
    # overlap or decode time vanishes into the generic consume bucket.
    "decode",
    "consume",
    "stage",
    "compress",
    "checksum",
)
# Pure waits: attributed only when no work category is active.
WAIT_PRIORITY = ("budget_wait", "barrier")

CATEGORIES = WORK_PRIORITY + WAIT_PRIORITY

# Verdict → the concrete knob to turn. One sentence of operator-ready
# advice per bound; the CLI appends context (percent, tail ratios).
ADVICE = {
    "storage_write": (
        "the storage backend is the limit — raise TPUSNAP_DIRECT_IO_QD / "
        "TPUSNAP_DIRECT_IO_CHUNK_BYTES for deeper device queues, use "
        "async_take (TPUSNAP_ASYNC_STAGE_WINDOW_BYTES) so training "
        "overlaps the drain, let TPUSNAP_COMPRESS=auto compress bf16/f32 "
        "tiles when the codec outruns this pipe, or target a faster tier "
        "(local fs write-back beats writing through to cloud)"
    ),
    "storage_read": (
        "restore is read-bound — raise TPUSNAP_SCRUB_CONCURRENCY-style "
        "read parallelism via a larger memory budget "
        "(TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES) so more tiled reads "
        "stay in flight"
    ),
    "dtoh": (
        "device-to-host copies dominate — batch smaller arrays "
        "(TPUSNAP_SLAB_SIZE_THRESHOLD_BYTES) and keep "
        "TPUSNAP_DISABLE_DEVICE_BATCHING off so slabs pack on-device"
    ),
    "stage": (
        "staging (clone/serialize) dominates — raise TPUSNAP_STAGE_THREADS "
        "only on hosts whose memory system feeds multiple cores (measure "
        "first), or enable TPUSNAP_ASYNC_COW=1 so frozen host-aliasing "
        "arrays clone nothing"
    ),
    "checksum": (
        "checksum passes dominate — raise TPUSNAP_TILE_CHECKSUM_BYTES "
        "(fewer, larger tiles) or TPUSNAP_DISABLE_CHECKSUM=1 for an A/B; "
        "deferred checksums (the default on non-incremental takes) should "
        "already overlap I/O"
    ),
    "consume": (
        "restore consume (deserialize + HtoD) dominates — check that "
        "in-place reads are active (they skip the copy-out) and batch "
        "small objects"
    ),
    "decode": (
        "the fused tile DECOMPRESSOR dominates the restore — the pipe "
        "outruns the codec on the read side, so write the next snapshot "
        "uncompressed for this tier (TPUSNAP_COMPRESS=off forces it; "
        "auto mode decides from the write-side ceiling, which can be "
        "faster than this read pipe); decode threads derive from the "
        "TPUSNAP_STAGE_THREADS budget if you'd rather keep the codec"
    ),
    "compress": (
        "the fused tile codec dominates — the pipe outruns the codec "
        "here, so flip the policy to bypass (TPUSNAP_COMPRESS=auto does "
        "this from the probe ceiling; TPUSNAP_COMPRESS=off forces it); "
        "the codec shares the TPUSNAP_STAGE_THREADS×native copy-thread "
        "budget, so there is no separate codec-thread knob to raise"
    ),
    "budget_wait": (
        "staging starves on the memory budget with no I/O to blame — "
        "raise TPUSNAP_MAX_PER_RANK_MEMORY_BUDGET_BYTES (or lower "
        "TPUSNAP_MAX_CHUNK_SIZE_BYTES so admission granularity shrinks)"
    ),
    "barrier": (
        "blocked on peers (barriers/KV waits) — this rank is NOT the "
        "straggler; find the slowest rank in the stragglers section and "
        "analyze that rank"
    ),
}


def classify_span(name: str) -> Optional[str]:
    """Resource category of an op-span name, or None for spans that do
    not participate in attribution (container spans, unknown names)."""
    if name in EXCLUDED_SPANS:
        return None
    cat = _CATEGORY_EXACT.get(name)
    if cat is not None:
        return cat
    for prefix, c in _CATEGORY_PREFIX:
        if name.startswith(prefix):
            return c
    return None


# ---------------------------------------------------------- attribution


@dataclass
class Attribution:
    """Outcome of one rank's critical-path sweep. ``attributed`` is
    exclusive (sums + unattributed_s == wall_s); ``busy`` is each
    category's raw interval-union time (overlaps allowed), the
    "pressure" view the exclusive walk would otherwise hide."""

    wall_s: float
    attributed: Dict[str, float] = field(default_factory=dict)
    busy: Dict[str, float] = field(default_factory=dict)
    unattributed_s: float = 0.0

    @property
    def coverage(self) -> float:
        if self.wall_s <= 0:
            return 0.0
        return min(sum(self.attributed.values()) / self.wall_s, 1.0)

    def verdict(self) -> Optional[Tuple[str, float]]:
        """(category, fraction-of-wall) of the dominant resource."""
        if not self.attributed or self.wall_s <= 0:
            return None
        cat = max(self.attributed, key=self.attributed.get)
        return cat, self.attributed[cat] / self.wall_s

    def to_json(self) -> Dict[str, Any]:
        return {
            "wall_s": round(self.wall_s, 6),
            "attributed_s": {
                k: round(v, 6) for k, v in sorted(self.attributed.items())
            },
            "attributed_pct": {
                k: round(100.0 * v / self.wall_s, 2)
                for k, v in sorted(self.attributed.items())
                if self.wall_s > 0
            },
            "busy_s": {k: round(v, 6) for k, v in sorted(self.busy.items())},
            "unattributed_s": round(self.unattributed_s, 6),
            "coverage": round(self.coverage, 4),
        }


def _union_seconds(intervals: List[Tuple[float, float]]) -> float:
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_start, cur_end = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_end:
            total += cur_end - cur_start
            cur_start, cur_end = s, e
        else:
            cur_end = max(cur_end, e)
    return total + (cur_end - cur_start)


def attribute_spans(
    spans: Sequence[Tuple[str, float, float]], wall_s: float
) -> Attribution:
    """Deterministic critical-path sweep over op spans of ONE rank.

    ``spans`` are ``(name, start_s, dur_s)`` tuples on the recorder's
    monotonic timeline (phase spans and container spans are ignored via
    :func:`classify_span`). The timeline [0, wall_s] is cut at every
    span boundary; each elementary slice is attributed to the
    highest-priority ACTIVE category (work before waits — see the
    module docstring), or to ``unattributed`` when nothing is in
    flight. Slices beyond ``wall_s`` are clipped; zero/negative
    durations are dropped."""
    by_cat: Dict[str, List[Tuple[float, float]]] = {}
    for name, start, dur in spans:
        cat = classify_span(name)
        if cat is None or dur <= 0:
            continue
        s = max(0.0, float(start))
        e = min(float(start) + float(dur), wall_s) if wall_s > 0 else (
            float(start) + float(dur)
        )
        if e <= s:
            continue
        by_cat.setdefault(cat, []).append((s, e))

    att = Attribution(wall_s=max(wall_s, 0.0))
    for cat, ivs in by_cat.items():
        att.busy[cat] = _union_seconds(list(ivs))

    # Sweep: +1/-1 events per category, slice between consecutive cuts.
    events: List[Tuple[float, int, str]] = []
    for cat, ivs in by_cat.items():
        for s, e in ivs:
            events.append((s, 1, cat))
            events.append((e, -1, cat))
    if not events:
        att.unattributed_s = att.wall_s
        return att
    events.sort(key=lambda t: (t[0], t[1]))
    active: Dict[str, int] = {}
    prev_t = 0.0
    attributed: Dict[str, float] = {}
    unattributed = 0.0

    def _account(span_len: float) -> None:
        nonlocal unattributed
        if span_len <= 0:
            return
        for cat in WORK_PRIORITY:
            if active.get(cat, 0) > 0:
                attributed[cat] = attributed.get(cat, 0.0) + span_len
                return
        for cat in WAIT_PRIORITY:
            if active.get(cat, 0) > 0:
                attributed[cat] = attributed.get(cat, 0.0) + span_len
                return
        unattributed += span_len

    for t, delta, cat in events:
        _account(t - prev_t)
        prev_t = t
        active[cat] = active.get(cat, 0) + delta
    if att.wall_s > prev_t:
        unattributed += att.wall_s - prev_t
    att.attributed = attributed
    att.unattributed_s = max(
        att.wall_s - sum(attributed.values()), 0.0
    ) if att.wall_s > 0 else unattributed
    return att


def spans_of_trace_doc(doc: Dict[str, Any]) -> List[Tuple[str, float, float]]:
    """(name, start_s, dur_s) op spans from one persisted rank trace
    (``rank_<k>.json``): Chrome trace events with ``ph == "X"`` and
    category ``op`` (phases tile the same timeline and would
    double-count)."""
    out = []
    for ev in doc.get("traceEvents") or []:
        if ev.get("ph") != "X" or ev.get("cat") == "phase":
            continue
        out.append(
            (
                ev.get("name", ""),
                float(ev.get("ts", 0.0)) / 1e6,
                float(ev.get("dur", 0.0)) / 1e6,
            )
        )
    return out


# -------------------------------------------------------------- findings


@dataclass
class Finding:
    """One actionable observation. ``severity`` is ``warn`` (fails
    ``--check``) or ``info`` (reported, never gates)."""

    severity: str
    kind: str
    message: str

    def to_json(self) -> Dict[str, str]:
        return {
            "severity": self.severity,
            "kind": self.kind,
            "message": self.message,
        }


@dataclass
class Thresholds:
    """``--check`` gates, all overridable at the CLI."""

    p99_ratio: float = 20.0  # write/read p99 over p50 beyond this → warn
    min_roofline: float = 0.4  # roofline_fraction below this → warn
    min_read_roofline: float = 0.4  # restore_roofline_fraction gate
    max_skew: float = 2.0  # per-phase straggler skew beyond this → warn
    min_coverage: float = 0.5  # attribution coverage below this → info
    # Access-ledger coverage (bytes ever read ÷ stored) below this →
    # the fleet reads a sliver of the snapshot; advise the lazy path.
    min_access_coverage: float = 0.3


def tail_latency_findings(
    io_histograms: Dict[str, Dict[str, Any]],
    thresholds: Thresholds,
    min_count: int = 8,
    min_p99_s: float = 0.005,
) -> List[Finding]:
    """p99/p50 outliers from the storage-boundary latency histograms.
    Only the payload ops (write/read) gate: delete/list run at
    microsecond scale where a single ordinary fs hiccup is a routine
    20x ratio, not a finding. Keys under ``min_count`` samples are
    skipped (a 3-sample p99 is noise, not a tail), as are tails whose
    absolute p99 is below ``min_p99_s`` (a fast op with a fast tail is
    healthy whatever the ratio says)."""
    out = []
    for key, st in sorted((io_histograms or {}).items()):
        if not key.startswith(("write.", "read.")):
            continue
        count = st.get("count") or 0
        p50, p99 = st.get("p50_s"), st.get("p99_s")
        if count < min_count or not p50 or not p99 or p50 <= 0:
            continue
        if p99 < min_p99_s:
            continue
        ratio = p99 / p50
        if ratio > thresholds.p99_ratio:
            op = key.split(".", 1)[0]
            out.append(
                Finding(
                    "warn",
                    "tail_latency",
                    f"{key}: p99 latency {p99 * 1e3:.1f}ms is "
                    f"{ratio:.0f}x the p50 ({p50 * 1e3:.1f}ms) over "
                    f"{count} ops — a fat {op} tail; check for "
                    "device/host contention, throttling, or a failing "
                    "disk (history --check gates storage_write_p99_s)",
                )
            )
    return out


def straggler_findings(
    rollup: Dict[str, Any], thresholds: Thresholds
) -> List[Finding]:
    out = []
    if (rollup or {}).get("ranks", 1) <= 1:
        return out
    for name, agg in sorted((rollup.get("phase_skew") or {}).items()):
        skew = agg.get("skew")
        if skew and skew > thresholds.max_skew and agg.get("max_s", 0) > 0.05:
            out.append(
                Finding(
                    "warn",
                    "straggler",
                    f"phase {name!r}: rank {agg.get('max_rank')} took "
                    f"{agg.get('max_s'):.2f}s, {skew:.2f}x the p50 — "
                    "a straggler rank; analyze that rank's trace "
                    "(trace --rank) and its host",
                )
            )
    return out


def roofline_findings(
    summary_like: Dict[str, Any], thresholds: Thresholds
) -> List[Finding]:
    out: List[Finding] = []
    frac = (summary_like or {}).get("roofline_fraction")
    if isinstance(frac, (int, float)) and frac < thresholds.min_roofline:
        ceiling = ((summary_like.get("probe") or {}).get("write_gbps_p50"))
        out.append(
            Finding(
                "warn",
                "roofline",
                f"take achieved only {frac:.0%} of the in-take probe "
                "ceiling"
                + (f" ({ceiling:.2f} GB/s)" if ceiling else "")
                + " — the pipeline, not the disk, is leaving throughput "
                "on the table; see the bound verdict",
            )
        )
    rfrac = (summary_like or {}).get("restore_roofline_fraction")
    if (
        isinstance(rfrac, (int, float))
        and rfrac < thresholds.min_read_roofline
    ):
        ceiling = ((summary_like.get("probe") or {}).get("read_gbps_p50"))
        out.append(
            Finding(
                "warn",
                "read_roofline",
                f"restore achieved only {rfrac:.0%} of the in-restore "
                "probe READ ceiling"
                + (f" ({ceiling:.2f} GB/s)" if ceiling else "")
                + " — the restore pipeline, not the disk, is leaving "
                "read throughput on the table; see the bound verdict "
                "(decode-bound restores overlap away under a pipelined "
                "engine)",
            )
        )
    return out


def access_findings(
    heatmap: Dict[str, Any], thresholds: Thresholds
) -> List[Finding]:
    """Serving advice from the merged access heatmap (see
    :func:`tpusnap.access.compute_heatmap`). ``info`` severity: partial
    access is an optimization opportunity, not a failure — the gateable
    side lives in ``heatmap --check`` / ``fleet --check``."""
    out: List[Finding] = []
    cov = (heatmap or {}).get("coverage")
    if not (heatmap or {}).get("bytes_read"):
        return out
    if (
        isinstance(cov, (int, float))
        and cov < thresholds.min_access_coverage
    ):
        hot = ", ".join(
            f"{h['path']}[{h['range'][0]}:{h['range'][1]})"
            for h in (heatmap.get("hot_ranges") or [])[:5]
        )
        out.append(
            Finding(
                "info",
                "partial_access",
                f"{heatmap.get('n_readers', 0)} reader(s) ever touched "
                f"only {cov:.0%} of this snapshot's stored bytes — "
                "serve it through read_object / the lazy path instead "
                "of full restores, and keep just the hot tiles on the "
                "fast tier"
                + (f"; hottest: {hot}" if hot else ""),
            )
        )
    return out


# ---------------------------------------------------------- the report


def analyze(
    rollup: Optional[Dict[str, Any]],
    rank_docs: Dict[int, Dict[str, Any]],
    kind: str = "take",
    thresholds: Optional[Thresholds] = None,
    history_events: Optional[List[Dict[str, Any]]] = None,
    heatmap: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The doctor report: bound verdict + attribution for the SLOWEST
    traced rank (the take ends when it does), per-rank attributions,
    tail/straggler/roofline findings, and optional history trend
    context. Pure; the CLI loads and renders."""
    thresholds = thresholds or Thresholds()
    rollup = rollup or {}
    attributions: Dict[int, Attribution] = {}
    for rank, doc in rank_docs.items():
        summary = doc.get("summary") or {}
        wall = float(summary.get("take_wall_s") or 0.0)
        spans = spans_of_trace_doc(doc)
        if wall > 0 and spans:
            attributions[rank] = attribute_spans(spans, wall)

    report: Dict[str, Any] = {"kind": kind, "findings": []}
    findings: List[Finding] = []

    slowest_rank: Optional[int] = None
    if attributions:
        slowest_rank = max(
            attributions, key=lambda r: attributions[r].wall_s
        )
        att = attributions[slowest_rank]
        report["rank"] = slowest_rank
        report["attribution"] = att.to_json()
        report["attribution_by_rank"] = {
            str(r): a.to_json() for r, a in sorted(attributions.items())
        }
        v = att.verdict()
        if v is not None:
            cat, frac = v
            report["bound_by"] = cat
            report["bound_pct"] = round(100.0 * frac, 1)
            report["advice"] = ADVICE.get(cat, "")
        if att.coverage < thresholds.min_coverage:
            findings.append(
                Finding(
                    "info",
                    "coverage",
                    f"only {att.coverage:.0%} of rank {slowest_rank}'s "
                    "wall-clock is covered by op spans — the verdict "
                    "reflects the instrumented part; the rest is Python "
                    "glue/planning",
                )
            )

    # Histograms: prefer the cross-rank rollup merge; fall back to the
    # slowest rank's own.
    io_hist = rollup.get("io_histograms")
    if not io_hist and slowest_rank is not None:
        io_hist = (
            rank_docs[slowest_rank].get("summary") or {}
        ).get("io_histograms")
    if io_hist:
        report["io_histograms"] = io_hist
        findings.extend(tail_latency_findings(io_hist, thresholds))

    findings.extend(straggler_findings(rollup, thresholds))

    # Roofline: rollup first (multi-rank p50), else the slowest rank.
    # Takes carry roofline_fraction (write lane); restores carry
    # restore_roofline_fraction (read lane) — same source selection.
    roofline_src: Dict[str, Any] = {}
    _FRACS = ("roofline_fraction", "restore_roofline_fraction")
    if any(isinstance(rollup.get(f), (int, float)) for f in _FRACS):
        roofline_src = rollup
    elif slowest_rank is not None:
        s = rank_docs[slowest_rank].get("summary") or {}
        if any(isinstance(s.get(f), (int, float)) for f in _FRACS):
            roofline_src = s
    if roofline_src:
        for f in _FRACS:
            if isinstance(roofline_src.get(f), (int, float)):
                report[f] = roofline_src[f]
        if roofline_src.get("probe"):
            report["probe"] = roofline_src["probe"]
        findings.extend(roofline_findings(roofline_src, thresholds))

    if heatmap:
        report["access"] = {
            k: heatmap.get(k)
            for k in (
                "snapshot_bytes",
                "bytes_read",
                "coverage",
                "amplification",
                "n_readers",
            )
        }
        findings.extend(access_findings(heatmap, thresholds))

    if history_events:
        report["history"] = history_context(history_events, kind)

    report["findings"] = [f.to_json() for f in findings]
    report["check_failed"] = any(f.severity == "warn" for f in findings)
    return report


def history_context(
    events: List[Dict[str, Any]], kind: str, window: int = 20
) -> Dict[str, Any]:
    """Trend context for the report: latest vs trailing-median
    throughput (and p99 write latency when recorded) over the last
    ``window`` events of ``kind``."""
    cand = [e for e in events if e.get("kind") == kind][-window:]
    out: Dict[str, Any] = {"events": len(cand)}
    if not cand:
        return out
    for metric in (
        "throughput_gbps",
        "storage_write_p99_s",
        "roofline_fraction",
        "storage_read_p99_s",
        "restore_roofline_fraction",
    ):
        vals = sorted(
            float(e[metric])
            for e in cand
            if isinstance(e.get(metric), (int, float))
        )
        if vals:
            latest = next(
                (
                    float(e[metric])
                    for e in reversed(cand)
                    if isinstance(e.get(metric), (int, float))
                ),
                None,
            )
            out[metric] = {
                "latest": latest,
                "median": round(vals[len(vals) // 2], 6),
                "n": len(vals),
            }
    return out
