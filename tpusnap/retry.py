"""Unified storage retry middleware: collective-progress deadlines,
transient-vs-fatal classification, and a ``StoragePlugin`` wrapper.

Extracted from the GCS plugin's battle-tested retry strategy so that EVERY
storage backend survives transient failures the same way (previously only
gcs.py retried; fs/s3/fsspec failed hard on the first error):

- ``RetryPolicy`` — the knobs: deadline, backoff shape, optional custom
  transient classifier. Constructible from ``storage_options`` so users
  tune retries per snapshot call without code changes.
- ``ProgressDeadline`` — the collective-progress deadline (reference
  gcs.py:216-272): one shared deadline per plugin instance, refreshed
  whenever ANY concurrent transfer completes — a pod-wide slowdown does
  not abort the snapshot while the backend is merely saturated, but a
  genuinely wedged backend still times out.
- ``RetryingStoragePlugin`` — wraps any ``StoragePlugin``; each
  write/write_atomic/read/delete is retried at whole-op granularity with
  exponential backoff + jitter. Whole-op granularity is what makes torn
  writes safe to retry: a partially-persisted blob is simply rewritten
  from byte 0 (fs ``write_atomic`` additionally never exposes the torn
  state thanks to temp+rename), and a partially-delivered read is re-run
  against a fresh ``ReadIO`` so no torn buffer ever reaches a consumer.

Transient classification is per-plugin: ``StoragePlugin.classify_transient``
(overridable) decides; the default covers connection-level failures,
timeouts, HTTP-ish status carriers and retriable OS errnos, and the fault
injection layer's ``InjectedFaultError`` subclasses ``ConnectionError`` so
chaos runs exercise exactly this path.
"""

from __future__ import annotations

import asyncio
import errno
import logging
import random
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from . import flight, telemetry
from .io_types import SIDECAR_PREFIX, ReadIO, StoragePlugin, WriteIO

logger = logging.getLogger(__name__)

_DEFAULT_DEADLINE_SEC = 600.0
_DEFAULT_BACKOFF_BASE_SEC = 0.5
_DEFAULT_BACKOFF_CAP_SEC = 30.0

# HTTP statuses that signal "try again" on any cloud/object backend.
TRANSIENT_HTTP_STATUS = frozenset({408, 429, 500, 502, 503, 504})

# OS errnos worth retrying: interruptions, contention, and network-ish
# filesystem hiccups. Deliberately excludes EIO/ENOSPC/EACCES/EROFS —
# those are real faults a retry loop would only delay surfacing.
TRANSIENT_ERRNOS = frozenset(
    e
    for e in (
        errno.EAGAIN,
        errno.EINTR,
        errno.EBUSY,
        errno.ETIMEDOUT,
        errno.ECONNRESET,
        errno.ECONNABORTED,
        errno.ECONNREFUSED,
        errno.ENETRESET,
        errno.ENETDOWN,
        errno.ENETUNREACH,
        getattr(errno, "ESTALE", None),
        getattr(errno, "EREMOTEIO", None),
    )
    if e is not None
)


def http_status_of(exc: BaseException) -> Optional[int]:
    """Best-effort HTTP status extraction without importing any client
    library: requests-style ``exc.response.status_code`` and
    botocore-style ``exc.response["ResponseMetadata"]["HTTPStatusCode"]``."""
    response = getattr(exc, "response", None)
    if response is None:
        return None
    status = getattr(response, "status_code", None)
    if isinstance(status, int):
        return status
    if isinstance(response, dict):
        meta = response.get("ResponseMetadata")
        if isinstance(meta, dict):
            status = meta.get("HTTPStatusCode")
            if isinstance(status, int):
                return status
    return None


def default_classify_transient(exc: BaseException) -> bool:
    """The classification shared by every plugin unless overridden:
    connection-level failures and timeouts are transient; OSErrors only
    for retriable errnos; HTTP-ish carriers by status code."""
    if isinstance(exc, (ConnectionError, TimeoutError, asyncio.TimeoutError)):
        return True
    if http_status_of(exc) in TRANSIENT_HTTP_STATUS:
        return True
    if isinstance(exc, OSError):
        return exc.errno in TRANSIENT_ERRNOS
    return False


@dataclass(frozen=True)
class RetryPolicy:
    """Retry knobs, overridable per call via ``storage_options``:
    ``retry_deadline_sec``, ``retry_backoff_base_sec``,
    ``retry_backoff_cap_sec``, and ``retry=False`` to disable the
    middleware entirely."""

    deadline_sec: float = _DEFAULT_DEADLINE_SEC
    backoff_base_sec: float = _DEFAULT_BACKOFF_BASE_SEC
    backoff_cap_sec: float = _DEFAULT_BACKOFF_CAP_SEC
    classify_transient: Optional[Callable[[BaseException], bool]] = None

    @classmethod
    def from_storage_options(
        cls, storage_options: Optional[Dict[str, Any]]
    ) -> "RetryPolicy":
        opts = storage_options or {}
        return cls(
            deadline_sec=float(
                opts.get("retry_deadline_sec", _DEFAULT_DEADLINE_SEC)
            ),
            backoff_base_sec=float(
                opts.get("retry_backoff_base_sec", _DEFAULT_BACKOFF_BASE_SEC)
            ),
            backoff_cap_sec=float(
                opts.get("retry_backoff_cap_sec", _DEFAULT_BACKOFF_CAP_SEC)
            ),
            classify_transient=opts.get("retry_classify_transient"),
        )

    def backoff_sec(self, attempt: int) -> float:
        """Exponential backoff with multiplicative jitter in [0.5, 1.5)
        (the GCS plugin's shape, generalized to a configurable base)."""
        raw = min(
            self.backoff_base_sec * (2 ** max(attempt - 1, 0)),
            self.backoff_cap_sec,
        )
        return raw * (0.5 + random.random())


class ProgressDeadline:
    """Collective-progress deadline shared by every concurrent op of one
    plugin instance: refreshed whenever ANY transfer completes, so only a
    backend making no progress at all expires it.

    Armed lazily at the first consult, NOT at construction: a plugin may
    be built long before its first op runs (async takes hold the plugin
    through the whole staging pass before any storage I/O) — counting
    that idle time against the deadline would deny the first failing op
    any retries at all."""

    def __init__(self, deadline_sec: float = _DEFAULT_DEADLINE_SEC) -> None:
        self._deadline_sec = deadline_sec
        self._deadline: Optional[float] = None

    def report_progress(self) -> None:
        self._deadline = time.monotonic() + self._deadline_sec

    def expired(self) -> bool:
        if self._deadline is None:
            self.report_progress()
            return False
        return time.monotonic() > self._deadline


class RetryingStoragePlugin(StoragePlugin):
    """Transparent retry wrapper around any ``StoragePlugin``.

    Each op retries at whole-op granularity while the failure classifies
    transient and the instance's collective-progress deadline has not
    expired. The wrapper is scheduling-transparent: in-place read
    support, overhead accounting, dir flushing and in-flight draining
    all delegate to the inner plugin."""

    def __init__(
        self,
        inner: StoragePlugin,
        policy: Optional[RetryPolicy] = None,
    ) -> None:
        self.inner = inner
        self.policy = policy or RetryPolicy()
        self._deadline = ProgressDeadline(self.policy.deadline_sec)
        self._classify = self.policy.classify_transient or getattr(
            inner, "classify_transient", default_classify_transient
        )

    # --- scheduling transparency -----------------------------------------

    @property
    def supports_in_place_reads(self) -> bool:  # type: ignore[override]
        return self.inner.supports_in_place_reads

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        return self.inner.in_place_read_overhead_bytes(nbytes)

    def drain_in_flight(self) -> None:
        self.inner.drain_in_flight()

    # --- retry core -------------------------------------------------------

    async def _gate(self, exc: Exception, attempt: int, op: str, path: str) -> None:
        """Re-raise fatal/expired failures; otherwise back off.
        Per-classification counters (op kind x exception type) record
        every retried failure whether or not the op eventually
        succeeds — the telemetry trace is how a chaos run proves its
        injected faults actually exercised this path."""
        transient = self._classify(exc)
        if not transient or self._deadline.expired():
            # Sidecar-namespace ops are expected-miss probes, not
            # payload failures: the journal read at every take start
            # 404s on a fresh path, and a ``retry.fatal.read`` counter
            # for it reads as a payload-blob retry gone fatal in every
            # stage_breakdown (the BENCH_r06 stray). Label them under
            # their own family so the payload counters stay clean.
            sidecar = path.startswith(SIDECAR_PREFIX)
            if transient and not sidecar:
                # Retry-budget EXHAUSTION is its own failure mode: the
                # error was retriable, the backend just never came back
                # within the progress deadline. One structured flight
                # breadcrumb + counter NAME the op that gave up — the
                # give-up instant used to be indistinguishable from a
                # hard-fatal classification in every post-mortem.
                telemetry.incr(f"retry.exhausted.{op}")
                flight.record(
                    "retry_exhausted",
                    op=op,
                    path=path,
                    attempts=attempt,
                    deadline_sec=self.policy.deadline_sec,
                    error=type(exc).__name__,
                )
                logger.warning(
                    "Retry budget exhausted in %s(%r) after %d attempt(s) "
                    "(no collective progress for %.0fs): %s",
                    op,
                    path,
                    attempt,
                    self.policy.deadline_sec,
                    exc,
                )
            else:
                family = "retry.fatal.sidecar" if sidecar else "retry.fatal"
                telemetry.incr(f"{family}.{op}")
                if not sidecar:
                    # Sidecar misses stay out of the black box too — a
                    # 404'd journal probe at take start is not forensic
                    # signal.
                    flight.record(
                        "retry_fatal",
                        op=op,
                        path=path,
                        error=type(exc).__name__,
                    )
            raise exc
        telemetry.incr("retry.attempts")
        telemetry.incr(f"retry.transient.{op}.{type(exc).__name__}")
        telemetry.event(
            "retry", op=op, path=path, attempt=attempt, error=type(exc).__name__
        )
        flight.record(
            "retry",
            op=op,
            path=path,
            attempt=attempt,
            error=type(exc).__name__,
        )
        logger.warning(
            "Transient storage error in %s(%r) (attempt %d): %s; retrying",
            op,
            path,
            attempt,
            exc,
        )
        await asyncio.sleep(self.policy.backoff_sec(attempt))

    async def _with_retry(self, op: str, path: str, attempt_coro_factory):
        attempt = 0
        while True:
            try:
                result = await attempt_coro_factory()
            except Exception as e:
                attempt += 1
                await self._gate(e, attempt, op, path)
                continue
            self._deadline.report_progress()
            if attempt > 0:
                # Success-after-retry was previously invisible (only
                # terminal failures logged); the INFO line + counter
                # make transient-burst recovery auditable.
                telemetry.incr("retry.recovered")
                logger.info(
                    "%s(%r) succeeded after %d retr%s (%d attempts total)",
                    op,
                    path,
                    attempt,
                    "y" if attempt == 1 else "ies",
                    attempt + 1,
                )
            return result

    # --- plugin interface -------------------------------------------------

    async def write(self, write_io: WriteIO) -> None:
        await self._with_retry(
            "write", write_io.path, lambda: self.inner.write(write_io)
        )

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        await self._with_retry(
            "write_atomic",
            write_io.path,
            lambda: self.inner.write_atomic(write_io, durable=durable),
        )

    async def read(self, read_io: ReadIO) -> None:
        async def attempt() -> ReadIO:
            # A fresh ReadIO per attempt: a failed inner read may have
            # partially filled buf/into or set crc fields — results are
            # copied back only from a fully successful attempt, so no
            # torn read state ever reaches a consumer.
            trial = ReadIO(
                path=read_io.path,
                byte_range=read_io.byte_range,
                into=read_io.into,
                want_crc=read_io.want_crc,
            )
            await self.inner.read(trial)
            return trial

        trial = await self._with_retry("read", read_io.path, attempt)
        read_io.buf = trial.buf
        read_io.in_place = trial.in_place
        read_io.crc32c = trial.crc32c
        read_io.crc_algo = trial.crc_algo

    async def delete(self, path: str) -> None:
        await self._with_retry("delete", path, lambda: self.inner.delete(path))

    async def list_with_sizes(self):
        return await self._with_retry(
            "list", "", lambda: self.inner.list_with_sizes()
        )

    async def flush_created_dirs(self) -> None:
        await self.inner.flush_created_dirs()

    async def close(self) -> None:
        await self.inner.close()
