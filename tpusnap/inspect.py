"""Offline snapshot inspection and integrity scrub.

The manifest records a CRC for every persisted blob (and tile-grain
checksums for blobs large enough to be read under a memory budget) — see
``manifest.TensorEntry``. This module turns that metadata into an
operational tool: ``verify_snapshot`` re-reads every byte of a snapshot
and checks it against the recorded checksums WITHOUT materializing any
arrays — streaming, tile-by-tile, with the CRC fused into the storage
plugin's read path where supported (fs), so a scrub runs at disk speed
with a small-constant memory footprint.

No reference counterpart: torchsnapshot has no integrity checking at all
(a flipped bit in storage restores silently). The closest operational
analog is a filesystem scrub (zfs/btrfs), applied at checkpoint
granularity. Exposed to operators as ``python -m tpusnap verify``
(see __main__.py) and programmatically as ``Snapshot.verify()``.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .manifest import (
    ChunkedTensorEntry,
    Entry,
    Manifest,
    ObjectEntry,
    PrimitiveEntry,
    ShardedEntry,
    SnapshotMetadata,
    TensorEntry,
    is_container_entry,
)
from .io_types import ReadIO, StoragePlugin
from .serialization import tensor_nbytes

__all__ = [
    "BlobCheck",
    "ScrubReport",
    "SnapshotDiff",
    "base_root_of_location",
    "diff_snapshots",
    "entry_nbytes",
    "entry_verifiable",
    "iter_blobs",
    "materialize_snapshot",
    "verify_snapshot",
]


def base_root_of_location(
    location: str, known_roots: Optional[List[str]] = None
) -> str:
    """Base-snapshot root (relative to the referencing snapshot) of an
    external blob location.

    ``known_roots`` — the referencing snapshot's recorded
    ``metadata.base_roots`` — is authoritative: the longest root that
    prefixes ``location`` wins, with no guessing. Locations matching no
    known root (older-format snapshots) fall back to grammar parsing:
    everything before the storage-layout segment (``<rank>/``,
    ``replicated/``, ``sharded/``, ``batched/``) that starts the blob's
    path within its own snapshot. The first segment after the leading
    ``..`` run always belongs to the base path (a relative reference
    descends into the base's directory name), so a base named by a bare
    step number ("../1000/0/app/w") parses correctly — but a MULTI-level
    base path with an interior numeric directory ("../exp/1000/final" in
    "../exp/1000/final/0/w") is ambiguous to the grammar, which is why
    writers record base_roots (ADVICE r3)."""
    if known_roots:
        best = None
        for r in known_roots:
            if (location == r or location.startswith(r + "/")) and (
                best is None or len(r) > len(best)
            ):
                best = r
        if best is not None:
            return best
    segs = location.split("/")
    i = 0
    while i < len(segs) and segs[i] == "..":
        i += 1
    j = i + 1
    while j < len(segs) and not (
        segs[j].isdigit() or segs[j] in ("replicated", "sharded", "batched")
    ):
        j += 1
    return "/".join(segs[:j]) if j < len(segs) else location


def entry_verifiable(entry: Entry) -> bool:
    """True when every stored byte of ``entry`` has a recorded checksum
    (so a scrub can verify it; False for snapshots written with
    TPUSNAP_DISABLE_CHECKSUM=1). Primitives and containers live inline in
    the metadata and count as verifiable."""
    if isinstance(entry, TensorEntry):
        return entry.checksum is not None
    if isinstance(entry, ChunkedTensorEntry):
        return all(c.tensor.checksum is not None for c in entry.chunks)
    if isinstance(entry, ShardedEntry):
        return all(s.tensor.checksum is not None for s in entry.shards)
    if isinstance(entry, ObjectEntry):
        return entry.checksum is not None
    return True


@dataclass
class _Blob:
    """One physical byte range to verify: a dense blob, a chunk, a shard,
    a slab member, or a single checksum tile of any of those."""

    manifest_path: str
    location: str
    byte_range: Optional[Tuple[int, int]]  # None = whole object
    checksum: Optional[str]  # "<algo>:<hex>" or None (unverifiable)
    detail: str = ""  # human context, e.g. "rows 0:4096" or "chunk 2"


@dataclass
class BlobCheck:
    """Outcome of verifying one physical blob range."""

    manifest_path: str
    location: str
    nbytes: int
    status: str  # "ok" | "corrupt" | "unverified"
    detail: str = ""


@dataclass
class ScrubReport:
    ok: int = 0
    corrupt: int = 0
    unverified: int = 0
    bytes_verified: int = 0
    failures: List[BlobCheck] = field(default_factory=list)
    unverified_blobs: List[BlobCheck] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return self.corrupt == 0

    def summary(self) -> str:
        gb = self.bytes_verified / 1e9
        s = (
            f"{self.ok} blob range(s) ok ({gb:.2f} GB verified), "
            f"{self.corrupt} corrupt, {self.unverified} unverified"
        )
        return s


def entry_nbytes(entry: Entry) -> int:
    """Persisted payload bytes of a leaf entry (0 for containers and
    primitives, whose values live inline in the metadata)."""
    if isinstance(entry, TensorEntry):
        return tensor_nbytes(entry.dtype, entry.shape)
    if isinstance(entry, ChunkedTensorEntry):
        return sum(entry_nbytes(c.tensor) for c in entry.chunks)
    if isinstance(entry, ShardedEntry):
        return sum(entry_nbytes(s.tensor) for s in entry.shards)
    if isinstance(entry, ObjectEntry):
        return entry.nbytes or 0
    return 0


def rank_payload_nbytes(metadata, rank: int) -> int:
    """Total payload bytes of one rank's RESTORE VIEW — what a recovery
    of this snapshot actually reads. The one definition both SLO
    surfaces share (the tracker's commit anchor and the CLI's estimated
    restore time), so they cannot silently diverge."""
    from .manifest_ops import get_manifest_for_rank

    view = get_manifest_for_rank(metadata, rank)
    return sum(
        entry_nbytes(e) for e in view.values() if not is_container_entry(e)
    )


def _tensor_blobs(path: str, entry: TensorEntry, detail: str = "") -> Iterator[_Blob]:
    """Expand one TensorEntry into its verifiable ranges. Entries carrying
    tile-grain checksums are emitted per tile (so a scrub pinpoints the
    corrupted tile and its memory footprint stays at tile size); plain
    entries are one range."""
    base = entry.byte_range[0] if entry.byte_range is not None else 0
    if entry.codec:
        # Compressed entry: the STORED blob is the concatenation of
        # independently compressed tiles, and every recorded checksum is
        # over the stored bytes — so a scrub reads compressed ranges
        # (tile i at sum(comp_tile_sizes[:i])) and verifies them exactly
        # like raw tiles. Bit-rot in a compressed tile is named per tile.
        sizes = [int(s) for s in (entry.comp_tile_sizes or [])]
        if (
            entry.tile_checksums
            and entry.tile_rows
            and len(sizes) == len(entry.tile_checksums)
        ):
            off = base
            for i, tile_crc in enumerate(entry.tile_checksums):
                yield _Blob(
                    manifest_path=path,
                    location=entry.location,
                    byte_range=(off, off + sizes[i]),
                    checksum=tile_crc,
                    detail=(detail + " " if detail else "")
                    + f"comp tile {i} ({entry.codec})",
                )
                off += sizes[i]
            return
        yield _Blob(
            manifest_path=path,
            location=entry.location,
            byte_range=(base, base + sum(sizes)),
            checksum=entry.checksum,
            detail=(detail + " " if detail else "")
            + f"compressed ({entry.codec})",
        )
        return
    nbytes = tensor_nbytes(entry.dtype, entry.shape)
    if entry.tile_checksums and entry.tile_rows:
        n_rows = entry.shape[0]
        row_nbytes = nbytes // n_rows if n_rows else 0
        t = entry.tile_rows
        for i, tile_crc in enumerate(entry.tile_checksums):
            r0 = i * t
            r1 = min(r0 + t, n_rows)
            yield _Blob(
                manifest_path=path,
                location=entry.location,
                byte_range=(base + r0 * row_nbytes, base + r1 * row_nbytes),
                checksum=tile_crc,
                detail=(detail + " " if detail else "") + f"rows {r0}:{r1}",
            )
        return
    yield _Blob(
        manifest_path=path,
        location=entry.location,
        byte_range=(base, base + nbytes),
        checksum=entry.checksum,
        detail=detail,
    )


def iter_blobs(manifest: Manifest) -> Iterator[_Blob]:
    """Every physical byte range a snapshot's manifest references, with
    its expected checksum. Walks the RAW global manifest (keys are
    ``rank/logical_path``), where replicated entries are already
    consolidated onto rank 0 and each rank's sharded entry holds only the
    shards that rank wrote — so every stored byte is yielded exactly once.
    """
    seen: set = set()
    for path, entry in manifest.items():
        if is_container_entry(entry) or isinstance(entry, PrimitiveEntry):
            continue
        blobs: Iterator[_Blob]
        if isinstance(entry, TensorEntry):
            blobs = _tensor_blobs(path, entry)
        elif isinstance(entry, ChunkedTensorEntry):
            blobs = (
                b
                for i, c in enumerate(entry.chunks)
                for b in _tensor_blobs(path, c.tensor, detail=f"chunk {i}")
            )
        elif isinstance(entry, ShardedEntry):
            blobs = (
                b
                for s in entry.shards
                for b in _tensor_blobs(
                    path, s.tensor, detail=f"shard @{s.offsets}"
                )
            )
        elif isinstance(entry, ObjectEntry):
            br = (0, entry.nbytes) if entry.nbytes is not None else None
            blobs = iter(
                [_Blob(path, entry.location, br, entry.checksum)]
            )
        else:
            continue
        for b in blobs:
            key = (b.location, b.byte_range)
            if key in seen:
                continue
            seen.add(key)
            yield b


def _entry_tensors(entry: Entry):
    """Every TensorEntry/ObjectEntry carrying a ``location`` in ``entry``."""
    if isinstance(entry, (TensorEntry, ObjectEntry)):
        yield entry
    elif isinstance(entry, ChunkedTensorEntry):
        for c in entry.chunks:
            yield c.tensor
    elif isinstance(entry, ShardedEntry):
        for s in entry.shards:
            yield s.tensor


def materialize_snapshot(
    path: str,
    storage_options: Optional[Dict[str, Any]] = None,
    resources: Optional[
        Tuple[asyncio.AbstractEventLoop, StoragePlugin]
    ] = None,
) -> Dict[str, int]:
    """Make an incremental snapshot self-contained: copy every blob it
    references from base snapshots (``../`` locations) into this
    snapshot, rewrite the manifest, and re-commit ``.snapshot_metadata``.
    Afterwards the base snapshot(s) may be deleted.

    Blobs are copied whole (slab references keep their byte ranges),
    two in flight so one blob's read overlaps another's write. Before
    the manifest is committed, every copied range is verified against
    its recorded checksum — bit-rot in a base is caught HERE, while the
    base still exists, not after the user deleted it. Peak memory: the
    copy phase holds up to 2 whole blobs, the verification phase up to
    4 scratch buffers of the largest copied blob (all bounded by the
    max-chunk/max-shard knobs, 512 MB class each). The metadata rewrite itself is
    atomic (temp + rename on fs; single PUT on object stores), so a
    failure at any point leaves the snapshot valid and base-referencing.

    ``resources`` lets a caller pass an existing (loop, storage) pair
    (``Snapshot.materialize`` reuses its cached ones); they are left
    open. Returns ``{"blobs_copied": N, "bytes_copied": N}``.
    """
    from .io_types import WriteIO
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    owns_resources = resources is None
    if owns_resources:
        event_loop = asyncio.new_event_loop()
        storage = None
    else:
        event_loop, storage = resources
    local_for: Dict[str, str] = {}
    bytes_copied = 0
    try:
        if storage is None:
            storage = url_to_storage_plugin_in_event_loop(
                path, event_loop, storage_options
            )
        try:
            metadata = _read_metadata(storage, event_loop, path)

            # Map each distinct external location to its local home: the
            # blob's path within its base snapshot (unique — locations
            # embed logical paths or slab uuids).
            for entry in metadata.manifest.values():
                for t in _entry_tensors(entry):
                    if not t.location.startswith("../"):
                        continue
                    base = base_root_of_location(
                        t.location, metadata.base_roots
                    )
                    local = t.location[len(base) + 1 :]
                    prior = local_for.setdefault(t.location, local)
                    if prior != local:  # pragma: no cover - defensive
                        raise RuntimeError(
                            f"conflicting local paths for {t.location!r}"
                        )
            if not local_for:
                return {"blobs_copied": 0, "bytes_copied": 0}
            collisions: Dict[str, str] = {}
            for ext, local in local_for.items():
                if collisions.setdefault(local, ext) != ext:
                    raise RuntimeError(
                        f"two base blobs ({collisions[local]!r}, {ext!r}) "
                        f"map to the same local path {local!r}; cannot "
                        "materialize"
                    )

            # Two copies in flight: one blob's read overlaps another's
            # write. Not more — each in-flight copy holds a whole blob
            # (512 MB class) in memory.
            async def _copy_one(pair, _ctx) -> int:
                ext, local = pair
                blob_io = ReadIO(path=ext)  # whole object
                await storage.read(blob_io)
                data = blob_io.buf.getbuffer()
                await storage.write(WriteIO(path=local, buf=data))
                return data.nbytes

            bytes_copied = sum(
                _bounded_run(
                    event_loop, sorted(local_for.items()), _copy_one, 2
                )
            )

            for entry in metadata.manifest.values():
                for t in _entry_tensors(entry):
                    if t.location in local_for:
                        t.location = local_for[t.location]

            # Verify the copied bytes against the manifest checksums
            # BEFORE committing: corruption in a base must surface while
            # the base still exists, not after the user retires it.
            copied_locations = set(local_for.values())
            to_check = [
                b
                for b in iter_blobs(metadata.manifest)
                if b.location in copied_locations
            ]
            bad = [
                c
                for c in _run_verifications(storage, event_loop, to_check)
                if c.status == "corrupt"
            ]
            if bad:
                detail = "; ".join(
                    f"{c.manifest_path} ({c.detail})" for c in bad[:5]
                )
                raise RuntimeError(
                    f"{len(bad)} copied blob range(s) failed checksum "
                    f"verification — the BASE snapshot is corrupt; the "
                    f"manifest was NOT rewritten and still references the "
                    f"base: {detail}"
                )

            from .manifest import encode_metadata
            from .snapshot import SNAPSHOT_METADATA_FNAME

            metadata.base_roots = None  # self-contained now
            # durable=True: this REWRITES an already-committed snapshot's
            # metadata — power loss must never tear or lose it (fsync is
            # cheap here; no multi-GB take preceded it).
            storage.sync_write_atomic(
                WriteIO(
                    path=SNAPSHOT_METADATA_FNAME,
                    buf=encode_metadata(metadata),
                ),
                event_loop,
                durable=True,
            )
        finally:
            if owns_resources:
                storage.sync_close(event_loop)
    finally:
        if owns_resources:
            event_loop.close()
    return {"blobs_copied": len(local_for), "bytes_copied": bytes_copied}


@dataclass
class SnapshotDiff:
    """Manifest-level diff of two snapshots (by recorded checksums — no
    data is read). Paths are logical (``rank/...``)."""

    added: List[str] = field(default_factory=list)
    removed: List[str] = field(default_factory=list)
    changed: List[str] = field(default_factory=list)  # provably different
    identical: List[str] = field(default_factory=list)
    # Undecidable without reading data: a side lacks checksums, or the
    # two snapshots stored the same-typed value in incomparable layouts
    # (different chunk/shard geometry, dense vs chunked).
    unknown: List[str] = field(default_factory=list)

    @property
    def same(self) -> bool:
        """Provably identical: every path matched by checksum."""
        return not (self.added or self.removed or self.changed or self.unknown)

    @property
    def differs(self) -> bool:
        """Provably different (unknown entries do not count)."""
        return bool(self.added or self.removed or self.changed)

    def summary(self) -> str:
        return (
            f"{len(self.identical)} identical, {len(self.changed)} changed, "
            f"{len(self.added)} added, {len(self.removed)} removed"
            + (f", {len(self.unknown)} undecidable" if self.unknown else "")
        )


def _rowwise_fold(entry) -> Optional[str]:
    """Whole-array checksum of a dense or chunked tensor entry, derived
    by CRC combine over in-order row chunks when necessary — so the same
    content stored in DIFFERENT row-chunk geometries (a tile-grain
    incremental take re-chunks an array on the base's checksum-tile
    grid) still compares equal. None when not derivable (missing
    checksums, non-row chunking, or a checksum algorithm this build
    cannot combine)."""
    from . import _native

    algo = _native.checksum_algorithm()
    if isinstance(entry, TensorEntry):
        if entry.codec:
            # Compressed: the checksum is over STORED bytes — only
            # comparable against another entry of the same codec/layout
            # (the fingerprint's geometry carries the codec).
            return None
        if entry.checksum and entry.checksum.startswith(algo + ":"):
            return entry.checksum
        return None
    if not isinstance(entry, ChunkedTensorEntry) or not entry.chunks:
        return None
    if any(c.tensor.codec for c in entry.chunks):
        # Compressed chunks: per-chunk checksums are over stored bytes
        # at compressed offsets; a row-length CRC combine would be
        # meaningless. Compared chunk-by-chunk with codec-aware
        # geometry instead.
        return None
    row_nbytes = (
        tensor_nbytes(entry.dtype, entry.shape[1:])
        if len(entry.shape) > 1
        else tensor_nbytes(entry.dtype, [1])
    )
    chunks = sorted(entry.chunks, key=lambda c: c.offsets[0])
    expect = 0
    folded: Optional[int] = None
    for c in chunks:
        if (
            c.offsets[0] != expect
            or any(o != 0 for o in c.offsets[1:])
            or list(c.sizes[1:]) != list(entry.shape[1:])
            or not c.tensor.checksum
            or not c.tensor.checksum.startswith(algo + ":")
        ):
            return None
        val = int(c.tensor.checksum.partition(":")[2], 16)
        n = c.sizes[0] * row_nbytes
        folded = val if folded is None else _native.crc_combine(folded, val, n)
        expect += c.sizes[0]
    if expect != entry.shape[0] or folded is None:
        return None
    return f"{algo}:{folded & 0xFFFFFFFF:08x}"


def _entry_fingerprint(entry: Entry):
    """(identity, geometry, content) of a leaf entry.

    - ``identity``: what the value IS (dtype/shape or object type) — an
      identity mismatch is a real change regardless of layout.
    - ``geometry``: how it was stored (dense/chunked/sharded + boxes) —
      checksums are only comparable between equal geometries. Dense and
      row-chunked entries whose checksums fold to a whole-array value
      normalize to the SAME ("rows",) geometry, so a tile-grain
      incremental take (which re-chunks on the tile grid) diffs as
      identical/changed against its dense base instead of undecidable.
    - ``content``: the recorded checksums, or None when absent.

    Locations are excluded throughout — a blob that moved (slab
    repacking, incremental reference) but hashes identically is the
    same content."""
    if isinstance(entry, PrimitiveEntry):
        return (("prim", entry.dtype), (), entry.serialized_value)
    if isinstance(entry, (TensorEntry, ChunkedTensorEntry)):
        folded = _rowwise_fold(entry)
        if folded is not None:
            return (
                ("tensor", entry.dtype, tuple(entry.shape)),
                ("rows",),
                folded,
            )
    if isinstance(entry, TensorEntry):
        # Compressed entries' checksums are over STORED bytes, so they
        # only compare against entries of the same codec: raw-vs-
        # compressed of identical content must read undecidable (a
        # geometry mismatch), never falsely "changed".
        geom = ("dense", entry.codec) if entry.codec else ("dense",)
        return (
            ("tensor", entry.dtype, tuple(entry.shape)),
            geom,
            entry.checksum,
        )
    if isinstance(entry, ChunkedTensorEntry):
        parts = tuple(c.tensor.checksum for c in entry.chunks)
        return (
            ("tensor", entry.dtype, tuple(entry.shape)),
            (
                "chunked",
                tuple(
                    (tuple(c.offsets), tuple(c.sizes), c.tensor.codec)
                    if c.tensor.codec
                    else (tuple(c.offsets), tuple(c.sizes))
                    for c in entry.chunks
                ),
            ),
            None if any(p is None for p in parts) else parts,
        )
    if isinstance(entry, ShardedEntry):
        shards = sorted(
            entry.shards, key=lambda s: (tuple(s.offsets), tuple(s.sizes))
        )
        parts = tuple(s.tensor.checksum for s in shards)
        return (
            ("tensor", entry.dtype, tuple(entry.shape)),
            ("sharded", tuple((tuple(s.offsets), tuple(s.sizes)) for s in shards)),
            None if any(p is None for p in parts) else parts,
        )
    if isinstance(entry, ObjectEntry):
        return (("object", entry.obj_type), (), entry.checksum)
    return (("?", type(entry).__name__), (), None)


def _read_metadata(
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
    path: str,
) -> SnapshotMetadata:
    """Read + parse ``.snapshot_metadata`` through an existing plugin
    (the one shared metadata-loading block for scrub/materialize/diff)."""
    from .snapshot import SNAPSHOT_METADATA_FNAME

    from .manifest import decode_metadata

    read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
    try:
        storage.sync_read(read_io, event_loop)
    except Exception as e:
        raise RuntimeError(
            f"Failed to read snapshot metadata at {path} — not a "
            "snapshot, or an aborted/incomplete one (run "
            f"`python -m tpusnap fsck` to classify)"
        ) from e
    return decode_metadata(read_io.buf.getvalue())


def load_snapshot_metadata(
    path: str,
    storage_options: Optional[Dict[str, Any]] = None,
) -> SnapshotMetadata:
    """Read and parse a snapshot's ``.snapshot_metadata`` standalone
    (own short-lived event loop + plugin)."""
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(
            path, loop, storage_options
        )
        try:
            return _read_metadata(storage, loop, path)
        finally:
            storage.sync_close(loop)
    finally:
        loop.close()


def diff_snapshots(
    path_a: str,
    path_b: str,
    storage_options: Optional[Dict[str, Any]] = None,
) -> SnapshotDiff:
    """Compare two snapshots entry-by-entry using only their manifests'
    recorded checksums — O(metadata), no blob reads. ``changed`` means
    the content provably differs (identity mismatch, or equal layouts
    with different checksums); ``unknown`` means equality cannot be
    decided cheaply (missing checksums, or same-typed values stored in
    incomparable chunk/shard geometries)."""
    ma = load_snapshot_metadata(path_a, storage_options).manifest
    mb = load_snapshot_metadata(path_b, storage_options).manifest
    leaves_a = {p: e for p, e in ma.items() if not is_container_entry(e)}
    leaves_b = {p: e for p, e in mb.items() if not is_container_entry(e)}
    out = SnapshotDiff()
    for p in sorted(set(leaves_a) | set(leaves_b)):
        if p not in leaves_b:
            out.removed.append(p)
        elif p not in leaves_a:
            out.added.append(p)
        else:
            ia, ga, ca = _entry_fingerprint(leaves_a[p])
            ib, gb, cb = _entry_fingerprint(leaves_b[p])
            if ia != ib:
                out.changed.append(p)  # different dtype/shape/type
            elif ca is None or cb is None or ga != gb:
                out.unknown.append(p)
            elif ca == cb:
                out.identical.append(p)
            else:
                out.changed.append(p)
    return out


async def _verify_one(
    storage: StoragePlugin,
    blob: _Blob,
    scratch: Dict[str, Any],
) -> BlobCheck:
    """Read + verify one blob range. ``scratch`` is a per-slot buffer
    holder reused across the ranges a scrub slot processes."""
    from . import _native

    n = blob.byte_range[1] - blob.byte_range[0] if blob.byte_range else None
    mk = lambda status, detail="": BlobCheck(  # noqa: E731
        manifest_path=blob.manifest_path,
        location=blob.location,
        nbytes=n or 0,
        status=status,
        detail=" ".join(x for x in (blob.detail, detail) if x),
    )
    into = None
    if n is not None and n > 0:
        buf = scratch.get("buf")
        if buf is None or buf.nbytes < n:
            buf = _native.aligned_empty(max(n, 1 << 20))
            scratch["buf"] = buf
        into = memoryview(buf)[:n]
    read_io = ReadIO(
        path=blob.location,
        byte_range=blob.byte_range,
        into=into,
        want_crc=blob.checksum is not None,
    )
    try:
        await storage.read(read_io)
    except Exception as e:
        return mk("corrupt", f"read failed: {e}")
    if blob.checksum is None:
        return mk("unverified", "no checksum recorded")
    algo, _, _ = blob.checksum.partition(":")
    try:
        if read_io.in_place and read_io.crc32c is not None:
            # Fused read-time CRC (fs plugin): verify the 4-byte value.
            _native.verify_checksum_value(
                read_io.crc32c,
                read_io.crc_algo,
                blob.checksum,
                blob.manifest_path,
            )
            if read_io.crc_algo != algo:
                return mk("unverified", f"algorithm mismatch ({algo})")
        else:
            data = read_io.buf.getbuffer()
            if n is not None and data.nbytes != n:
                return mk(
                    "corrupt", f"short read: got {data.nbytes} of {n} bytes"
                )
            if _native.checksum_algorithm() != algo:
                return mk("unverified", f"algorithm mismatch ({algo})")
            _native.verify_checksum(data, blob.checksum, blob.manifest_path)
    except _native.ChecksumError as e:
        return mk("corrupt", str(e))
    return mk("ok")


def _bounded_run(
    event_loop: asyncio.AbstractEventLoop,
    items,
    worker,
    concurrency: int,
    slot_ctx=dict,
):
    """Run ``await worker(item, ctx)`` over ``items`` with ``concurrency``
    slots; each slot owns one reusable ``slot_ctx()`` (e.g. a scratch
    buffer holder). Results come back in input order. On any failure the
    sibling slot tasks are cancelled AND drained — gather alone would
    strand them on the caller's (possibly cached-Snapshot, reused) loop,
    where the next run_until_complete resumes them mid-close. The one
    bounded-concurrency engine for the scrub and materialize copies."""

    async def run():
        work = enumerate(items)  # shared: each slot pulls the next, O(n)
        results = []

        async def slot() -> None:
            ctx = slot_ctx()
            for i, item in work:
                results.append((i, await worker(item, ctx)))

        tasks = [
            asyncio.ensure_future(slot())
            for _ in range(max(1, concurrency))
        ]
        try:
            await asyncio.gather(*tasks)
        except BaseException:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise
        return [r for _, r in sorted(results, key=lambda ir: ir[0])]

    from .io_types import run_on_loop

    return run_on_loop(event_loop, run())


def _run_verifications(
    storage: StoragePlugin,
    event_loop: asyncio.AbstractEventLoop,
    blobs: List[_Blob],
    concurrency: Optional[int] = None,
) -> List[BlobCheck]:
    """Verify blob ranges with ``concurrency`` reads in flight — the scrub
    is latency-bound on serial tile reads otherwise. Each slot owns one
    reusable scratch buffer, so peak memory is concurrency x the largest
    range a slot sees (TPUSNAP_SCRUB_CONCURRENCY, default 4)."""
    import logging
    import time

    if concurrency is None:
        from .knobs import get_scrub_concurrency

        concurrency = get_scrub_concurrency()
    logger = logging.getLogger(__name__)
    progress = {"count": 0, "bytes": 0, "last_log": time.monotonic()}

    async def verify_one(blob, scratch) -> BlobCheck:
        check = await _verify_one(storage, blob, scratch)
        progress["count"] += 1
        progress["bytes"] += check.nbytes
        now = time.monotonic()
        if now - progress["last_log"] >= 10.0:
            progress["last_log"] = now
            logger.info(
                "scrub progress: %d/%d ranges, %.2f GB verified",
                progress["count"],
                len(blobs),
                progress["bytes"] / 1e9,
            )
        return check

    # Results return in manifest order, not completion order: scrub
    # output must be deterministic across runs (operators diff it).
    return _bounded_run(event_loop, blobs, verify_one, concurrency)


def verify_snapshot(
    path: str,
    storage_options: Optional[Dict[str, Any]] = None,
    metadata: Optional[SnapshotMetadata] = None,
    resources: Optional[
        Tuple[asyncio.AbstractEventLoop, StoragePlugin]
    ] = None,
) -> ScrubReport:
    """Stream-verify every blob of the snapshot at ``path`` against the
    checksums recorded in its manifest.

    Returns a :class:`ScrubReport`; ``report.clean`` is False when any
    range failed (bit-rot, truncation, or a missing blob). Ranges are
    verified with 4 reads in flight; peak memory is 4 scratch buffers of
    the largest range each slot sees — tile-sized (16 MiB class) for
    large arrays carrying tile checksums, up to the blob size (512 MB
    class) otherwise. ``resources`` lets a caller
    that already holds a (loop, storage) pair — ``Snapshot.verify`` reuses
    its cached ones — skip plugin construction; they are left open.
    """
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    report = ScrubReport()
    owns_resources = resources is None
    if owns_resources:
        event_loop = asyncio.new_event_loop()
        storage = None
    else:
        event_loop, storage = resources
    try:
        if storage is None:
            storage = url_to_storage_plugin_in_event_loop(
                path, event_loop, storage_options
            )
        try:
            if metadata is None:
                metadata = _read_metadata(storage, event_loop, path)
            checks = _run_verifications(
                storage, event_loop, list(iter_blobs(metadata.manifest))
            )
            for check in checks:
                if check.status == "ok":
                    report.ok += 1
                    report.bytes_verified += check.nbytes
                elif check.status == "corrupt":
                    report.corrupt += 1
                    report.failures.append(check)
                else:
                    report.unverified += 1
                    report.unverified_blobs.append(check)
        finally:
            if owns_resources:
                storage.sync_close(event_loop)
    finally:
        if owns_resources:
            event_loop.close()
    return report
