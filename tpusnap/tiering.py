"""Write-back storage tiering: durable-local commit, background cloud drain.

A ``tier+local=<fs-base>+remote=<scheme>://<path>`` URL composes two
storage tiers around one snapshot path:

- the **local tier** (a filesystem mirror of the remote path under
  ``<fs-base>``) is the commit-of-record: every blob write and the
  metadata-written-last commit land there at disk speed — a take through
  the tier never waits on, and never fails because of, the remote;
- the **remote tier** (any registered scheme, chaos-composable:
  ``remote=chaos+s3``) receives the blobs from a background **uploader
  state machine** that is crash-safe and outage-tolerant.

Durability is a two-state ladder, first-class in ``fsck``/``info``/
``timeline``:

    local-committed   metadata committed in the local tier; the upload
                      journal (``.tpusnap/upload_journal``) names the
                      remote target and the blobs already proven remote
    remote-durable    every payload blob uploaded, the remote metadata
                      written LAST and verified by read-back, and the
                      journal's state marker rewritten to ``durable``
                      strictly after that verify

The upload journal rides the PR 3 evidence rule: after each successful
remote write the uploader records the blob's ``(nbytes, CRC32C, XXH64)``
triple (of the bytes it read locally and shipped) and atomically
rewrites the journal — so a SIGKILLed uploader, restarted by
``python -m tpusnap drain`` or the next process's background drain,
re-hashes each local blob and SKIPS every one whose fresh dual hash
matches its journal record: nothing already proven remote is uploaded
twice. Chain-aware ordering: a snapshot's external bases (incremental
takes, delta-stream parents) drain to their remote siblings BEFORE the
snapshot itself, so the remote tier is restorable the instant its
metadata lands.

Outage tolerance: each remote op runs under the ordinary retry
middleware but with a SHORT deadline (``TPUSNAP_TIER_OP_DEADLINE_S``);
once ``TPUSNAP_TIER_OUTAGE_THRESHOLD`` consecutive uploads exhaust it,
the circuit opens — one edge-triggered ``tier_degraded`` flight event,
``tier.degraded_episodes`` counter, ``tpusnap_tier_degraded`` gauge —
and the drain backs off exponentially (jittered, capped at
``TPUSNAP_TIER_BACKOFF_CAP_S``) while takes keep committing locally.
``tpusnap_upload_lag_bytes`` / ``tpusnap_upload_lag_seconds`` quantify
the at-risk window the whole time; recovery emits ``tier_recovered``
and the drain resumes where the journal left off.

GC safety rule (:func:`tpusnap.lifecycle.gc_snapshot`): local payload
blobs may be reclaimed (``gc --evict-local``) only past
``remote-durable``, and only once the durable marker is older than the
``TPUSNAP_TIER_LOCAL_RETENTION_S`` hot-cache window; reads through the
tier URL then fall back to the remote transparently.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import flight, telemetry
from .io_types import (
    SIDECAR_PREFIX,
    UPLOAD_JOURNAL_PATH,
    ReadIO,
    StoragePlugin,
    WriteIO,
)

logger = logging.getLogger(__name__)

# Wall-clock seam (timestamps in the journal/status records; injectable
# for tests). Durations/backoff run on the monotonic clock.
_wall = time.time

#: Subdirectory of TPUSNAP_TELEMETRY_DIR holding the uploader's live
#: status sidecar (read by `tpusnap slo` / `drain --status`).
TIER_STATUS_DIRNAME = "tier"

_TIER_PREFIX = "tier+"
_LOCAL_KEY = "local="
_REMOTE_SEP = "+remote="


# ------------------------------------------------------------------- URL


@dataclass(frozen=True)
class TierSpec:
    """A parsed ``tier+local=<base>+remote=<scheme>://<path>`` URL."""

    local_base: str  # the fs cache base directory (from local=)
    remote_scheme: str  # e.g. "s3", "gs", "chaos+fs", "fsspec+memory"
    remote_path: str  # the path after ://
    url: str  # the original tier URL

    @property
    def remote_url(self) -> str:
        return f"{self.remote_scheme}://{self.remote_path}"

    @property
    def local_dir(self) -> str:
        """The local mirror directory of this snapshot path: the remote
        path re-rooted under the local base — so appending ``/member``
        to the tier URL extends BOTH tiers consistently (delta streams,
        retention roots)."""
        rel = self.remote_path.lstrip("/")
        return os.path.join(self.local_base, rel) if rel else self.local_base


def parse_tier_url(url_path: str) -> Optional[TierSpec]:
    """Parse a tier URL, or return None when ``url_path`` is not one.
    Raises ``ValueError`` on a malformed tier scheme (it IS a tier URL,
    but the local/remote parts don't parse)."""
    if "://" not in url_path:
        return None
    scheme, path = url_path.split("://", 1)
    if not scheme.lower().startswith(_TIER_PREFIX):
        return None
    spec = scheme[len(_TIER_PREFIX):]
    # rpartition on "+remote=": the local fs path may contain "+"; the
    # remote scheme may itself be composed ("chaos+fs", "fsspec+memory").
    local_part, sep, remote_scheme = spec.rpartition(_REMOTE_SEP)
    if not sep or not local_part.startswith(_LOCAL_KEY):
        raise ValueError(
            f"malformed tier URL {url_path!r}: expected "
            "tier+local=<fs-path>+remote=<scheme>://<path>"
        )
    local_base = local_part[len(_LOCAL_KEY):]
    if not local_base:
        raise ValueError(f"tier URL {url_path!r} has an empty local= path")
    return TierSpec(
        local_base=local_base,
        remote_scheme=remote_scheme or "fs",
        remote_path=path,
        url=url_path,
    )


#: Remote scheme → storage-plugin class label (the innermost class name
#: the I/O histograms and restore history events use). Static so the
#: SLO estimator can price a tier without instantiating cloud clients.
_SCHEME_LABELS = {
    "": "FSStoragePlugin",
    "fs": "FSStoragePlugin",
    "file": "FSStoragePlugin",
    "s3": "S3StoragePlugin",
    "gs": "GCSStoragePlugin",
    "gcs": "GCSStoragePlugin",
}


def scheme_plugin_label(scheme: str) -> Optional[str]:
    s = scheme.lower()
    if s.startswith("chaos+"):
        s = s[len("chaos+"):]
    if s.startswith("fsspec+"):
        return "FsspecStoragePlugin"
    return _SCHEME_LABELS.get(s)


# -------------------------------------------------------- upload journal


def _journal_from_json(data: bytes) -> Optional[Dict[str, Any]]:
    try:
        d = json.loads(data.decode("utf-8"))
    except Exception:
        return None
    if not isinstance(d, dict) or not isinstance(d.get("blobs", {}), dict):
        return None
    d.setdefault("version", 1)
    d.setdefault("state", "pending")
    # Sanitize per-blob evidence at the parse boundary: the journal is
    # advisory, never load-bearing — a malformed entry (hand edit,
    # partial corruption that still decodes) must read as absent
    # evidence (re-upload), not crash the drain or the status readers.
    d["blobs"] = {
        str(k): [int(v[0]), str(v[1]), str(v[2])]
        for k, v in (d.get("blobs") or {}).items()
        if isinstance(v, (list, tuple))
        and len(v) == 3
        and isinstance(v[0], int)
    }
    return d


def read_upload_journal(
    storage: StoragePlugin, event_loop: asyncio.AbstractEventLoop
) -> Optional[Dict[str, Any]]:
    """The upload journal at this plugin's root, or None (absent or
    unparseable — unparseable is logged and treated as absent: like the
    take journal, it is advisory for resume efficiency, never
    load-bearing for restore correctness)."""
    read_io = ReadIO(path=UPLOAD_JOURNAL_PATH)
    try:
        storage.sync_read(read_io, event_loop)
    except Exception:
        return None
    j = _journal_from_json(read_io.buf.getvalue())
    if j is None:
        logger.warning(
            "Unparseable upload journal at %r; ignoring", UPLOAD_JOURNAL_PATH
        )
    return j


def read_upload_journal_dir(local_dir: str) -> Optional[Dict[str, Any]]:
    """Direct-file read of a LOCAL tier directory's upload journal (the
    local tier is a filesystem by construction; CLI/status readers use
    this to avoid building a plugin)."""
    try:
        with open(os.path.join(local_dir, UPLOAD_JOURNAL_PATH), "rb") as f:
            return _journal_from_json(f.read())
    except OSError:
        return None


def durability_of_journal(journal: Optional[Dict[str, Any]]) -> Optional[str]:
    """The two-state durability ladder from a journal record: None when
    the snapshot is not tiered at all."""
    if journal is None:
        return None
    return (
        "remote-durable" if journal.get("state") == "durable"
        else "local-committed"
    )


# ------------------------------------------------------------ the plugin


class TieredStoragePlugin(StoragePlugin):
    """The composed two-tier plugin a tier URL resolves to.

    Writes (blobs, sidecars, the metadata commit) go to the LOCAL tier
    only — the remote is never on the take's critical path. Reads
    prefer local and fall back to the remote on a local miss (the
    evicted-hot-cache case). Deletes propagate to both tiers
    best-effort (a failed remote delete is logged and counted; running
    ``gc`` against the remote URL reclaims any stragglers). Listings
    merge both tiers with local precedence, so ``fsck`` through the
    tier URL sees the union.

    The metadata commit additionally seeds/updates the upload journal
    (state ``pending``) and — when ``TPUSNAP_TIER_DRAIN`` is on —
    enqueues this snapshot with the process-global background uploader.
    Each sub-plugin composes its own middleware (retry, histograms,
    chaos via the remote sub-scheme), so the tier itself is returned
    bare by the registry (``handles_own_retries``)."""

    # Retry/instrumentation compose on the sub-plugins, not the tier.
    handles_own_retries = True

    def __init__(
        self,
        spec: TierSpec,
        storage_options: Optional[Dict[str, Any]] = None,
    ) -> None:
        from .storage_plugin import url_to_storage_plugin

        self.spec = spec
        self._storage_options = storage_options
        # The local tier never draws the chaos plan — faults target the
        # remote via its own scheme (remote=chaos+...); a faulty LOCAL
        # commit tier would break the "commits at disk speed, never
        # fails" contract this layer exists for.
        local_opts = dict(storage_options or {})
        local_opts.pop("fault_plan", None)
        self.local = url_to_storage_plugin(spec.local_dir, local_opts or None)
        self._remote: Optional[StoragePlugin] = None
        self._journal_seeded = False

    # --- sub-plugin access ------------------------------------------------

    @property
    def local_dir(self) -> str:
        return self.spec.local_dir

    @property
    def remote_url(self) -> str:
        return self.spec.remote_url

    def _remote_plugin(self) -> StoragePlugin:
        if self._remote is None:
            from .knobs import get_tier_op_deadline_s
            from .storage_plugin import url_to_storage_plugin

            opts = dict(self._storage_options or {})
            # Short per-op deadline: a fallback read/delete against a
            # wedged remote must fail fast enough for callers to act,
            # not park for the 600 s payload default.
            opts.setdefault("retry_deadline_sec", get_tier_op_deadline_s())
            self._remote = url_to_storage_plugin(self.spec.remote_url, opts)
        return self._remote

    # --- scheduling transparency -----------------------------------------

    @property
    def supports_in_place_reads(self) -> bool:  # type: ignore[override]
        return self.local.supports_in_place_reads

    def in_place_read_overhead_bytes(self, nbytes: int) -> int:
        return self.local.in_place_read_overhead_bytes(nbytes)

    def drain_in_flight(self) -> None:
        self.local.drain_in_flight()
        if self._remote is not None:
            self._remote.drain_in_flight()

    def classify_transient(self, exc: BaseException) -> bool:
        from .retry import default_classify_transient

        return getattr(
            self.local, "classify_transient", default_classify_transient
        )(exc)

    # --- journal seeding / commit hand-off --------------------------------

    async def _seed_journal(self) -> None:
        """First write of a take: make the tier intent durable in the
        local dir — the journal names the remote target (what lets a
        bare ``drain <local-dir>`` resume after any crash) and resets
        the durability state to ``pending`` (a retake's new bytes are
        not remote yet). Prior blob evidence is PRESERVED: the drain
        re-verifies every entry against the local bytes' fresh dual
        hash, so stale evidence can only cause a re-upload, never a
        wrong skip."""
        if self._journal_seeded:
            return
        self._journal_seeded = True
        prior = None
        read_io = ReadIO(path=UPLOAD_JOURNAL_PATH)
        try:
            await self.local.read(read_io)
            prior = _journal_from_json(read_io.buf.getvalue())
        except Exception:
            prior = None
        journal = prior or {"version": 1, "blobs": {}}
        journal["remote"] = self.spec.remote_url
        journal["state"] = "pending"
        journal.pop("durable_at", None)
        # The PREVIOUS take's commit stamp must go too: an in-flight
        # drain of that take checks the stamp before writing its
        # durable marker, and a stale stamp surviving the seed would
        # let it mark the dir durable while THIS take is mid-overwrite
        # of the payload (the window between first blob write and
        # metadata commit).
        journal.pop("committed_at", None)
        await self.local.write_atomic(
            WriteIO(
                path=UPLOAD_JOURNAL_PATH,
                buf=json.dumps(journal).encode("utf-8"),
            )
        )

    async def _on_local_commit(self) -> None:
        """The local metadata just committed: stamp the journal and
        hand the snapshot to the background uploader. Best-effort — the
        take is already durable locally and a failure here only delays
        cloud convergence (the next drain picks it up)."""
        try:
            await self._seed_journal()
            read_io = ReadIO(path=UPLOAD_JOURNAL_PATH)
            await self.local.read(read_io)
            journal = _journal_from_json(read_io.buf.getvalue()) or {
                "version": 1,
                "blobs": {},
            }
            journal["remote"] = self.spec.remote_url
            journal["state"] = "pending"
            journal.pop("durable_at", None)
            journal["committed_at"] = _wall()
            await self.local.write_atomic(
                WriteIO(
                    path=UPLOAD_JOURNAL_PATH,
                    buf=json.dumps(journal).encode("utf-8"),
                )
            )
        except Exception:
            logger.warning(
                "upload journal commit stamp failed (non-fatal; the next "
                "drain will still converge)",
                exc_info=True,
            )
        from .knobs import is_tier_drain_enabled

        if is_tier_drain_enabled():
            drain_manager().enqueue(
                self.spec.local_dir,
                self.spec.remote_url,
                self._storage_options,
            )

    # --- plugin interface -------------------------------------------------

    async def write(self, write_io: WriteIO) -> None:
        await self._seed_journal()
        await self.local.write(write_io)

    async def write_atomic(self, write_io: WriteIO, durable: bool = False) -> None:
        await self._seed_journal()
        await self.local.write_atomic(write_io, durable=durable)
        from .snapshot import SNAPSHOT_METADATA_FNAME

        if write_io.path == SNAPSHOT_METADATA_FNAME:
            await self._on_local_commit()

    async def read(self, read_io: ReadIO) -> None:
        try:
            await self.local.read(read_io)
            return
        except FileNotFoundError:
            # Sidecars (journal probes, salvage records, heartbeats)
            # live ONLY in the local tier: a miss is a miss, and
            # falling through would put the remote — possibly mid-
            # outage — on the take's critical path, the exact thing
            # this layer exists to prevent.
            if read_io.path.startswith(SIDECAR_PREFIX):
                raise
            # Evicted (or never-local) blob: read through to the remote
            # tier. A fresh ReadIO per tier, retry-middleware style, so
            # a partially-filled local attempt never leaks upward.
            pass
        trial = ReadIO(
            path=read_io.path,
            byte_range=read_io.byte_range,
            into=read_io.into,
            want_crc=read_io.want_crc,
        )
        await self._remote_plugin().read(trial)
        telemetry.incr("tier.remote_fallback_reads")
        read_io.buf = trial.buf
        read_io.in_place = trial.in_place
        read_io.crc32c = trial.crc32c
        read_io.crc_algo = trial.crc_algo
        # Access-ledger provenance: the bytes came through the remote
        # tier because the local copy was evicted (or never landed).
        read_io.source = "evicted-read-through"

    async def delete(self, path: str) -> None:
        if path.startswith(SIDECAR_PREFIX):
            # Sidecars never drain to the remote; their cleanup (journal
            # clears at commit, abort cleanup) must stay local-speed.
            await self.local.delete(path)
            return
        local_exc: Optional[Exception] = None
        try:
            await self.local.delete(path)
        except Exception as e:
            local_exc = e
        try:
            await self._remote_plugin().delete(path)
        except Exception:
            if local_exc is not None:
                raise local_exc
            # Local copy gone, remote delete failed (outage, or the
            # blob never drained): not fatal — `gc` against the remote
            # URL reclaims stragglers.
            telemetry.incr("tier.remote_delete_failures")
            logger.debug(
                "remote tier delete failed for %r (non-fatal)",
                path,
                exc_info=True,
            )
            return
        # Only an evicted blob (local miss) may ride on the remote
        # delete's success: a REAL local failure (EACCES, EIO) leaving
        # the local copy behind must surface, or gc/retention report
        # bytes reclaimed that still occupy the local disk.
        if local_exc is not None and not isinstance(
            local_exc, FileNotFoundError
        ):
            raise local_exc

    async def list_with_sizes(self) -> Optional[dict]:
        # LOCAL tier only, deliberately: the take path lists at start
        # (salvage probe, metadata-existence check) and a remote walk —
        # possibly mid-outage — must never sit on it. Offline tooling
        # stays correct without the union: fsck reads durability from
        # the upload journal and classifies locally-absent referenced
        # blobs of a remote-durable snapshot as evicted, not missing;
        # the remote tier is fsck-able directly at its own URL.
        return await self.local.list_with_sizes()

    async def flush_created_dirs(self) -> None:
        await self.local.flush_created_dirs()

    async def close(self) -> None:
        # The background uploader is process-global and deliberately
        # survives this plugin: durability converges across takes.
        await self.local.close()
        if self._remote is not None:
            await self._remote.close()


def build_tiered_plugin(
    url_path: str, storage_options: Optional[Dict[str, Any]] = None
) -> TieredStoragePlugin:
    spec = parse_tier_url(url_path)
    if spec is None:
        raise ValueError(f"not a tier URL: {url_path!r}")
    return TieredStoragePlugin(spec, storage_options)


# --------------------------------------------------------- status surface

_status_lock = threading.Lock()
_status: Dict[str, Any] = {"state": "idle"}


def tier_status_path(base: Optional[str] = None) -> str:
    from .knobs import get_telemetry_dir

    return os.path.join(
        base or get_telemetry_dir(), TIER_STATUS_DIRNAME, "status.json"
    )


def _publish_status(**fields: Any) -> None:
    """Update the process-global uploader status, rewrite the local
    status sidecar atomically, and fan the record out to the metrics
    sinks (``tpusnap_upload_lag_bytes``/``_seconds``,
    ``tpusnap_tier_degraded``). Never raises.

    ``lag_bytes`` in the published record is the TOTAL at-risk figure:
    the actively-draining snapshot's remainder (callers pass it as
    ``lag_bytes``) plus the queued backlog the DrainManager maintains
    (``queued_lag_bytes``) — during an outage with micro-commits piling
    up, the queue IS most of the exposure."""
    with _status_lock:
        if "lag_bytes" in fields:
            _status["active_lag_bytes"] = int(fields.pop("lag_bytes") or 0)
        _status.update(fields)
        _status["lag_bytes"] = int(
            _status.get("active_lag_bytes") or 0
        ) + int(_status.get("queued_lag_bytes") or 0)
        _status["ts"] = _wall()
        committed = _status.get("oldest_commit_ts")
        _status["lag_seconds"] = (
            round(max(_status["ts"] - committed, 0.0), 3)
            if isinstance(committed, (int, float))
            else 0.0
        )
        state = dict(_status)
    try:
        path = tier_status_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, path)
    except Exception:
        logger.debug("tier status sidecar write failed", exc_info=True)
    try:
        telemetry.notify_tier_update(state)
    except Exception:
        logger.debug("tier status sink notify failed", exc_info=True)


def read_tier_status(base: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """The last published uploader status on this host, or None."""
    try:
        with open(tier_status_path(base), "r") as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else None
    except Exception:
        return None


def current_status() -> Dict[str, Any]:
    with _status_lock:
        return dict(_status)


# ----------------------------------------------------------- the drainer


@dataclass
class DrainReport:
    """Outcome of draining ONE snapshot directory to its remote."""

    local_dir: str
    remote_url: str
    # "durable" | "degraded" | "superseded" | "missing-blobs" | "no-metadata"
    state: str
    blobs_total: int = 0
    blobs_uploaded: int = 0
    blobs_skipped: int = 0
    bytes_uploaded: int = 0
    bytes_skipped: int = 0
    lag_bytes: int = 0
    degraded_episodes: int = 0
    error: str = ""
    # Content-addressed refs (tpusnap.cas): blobs this snapshot holds
    # as shared-store refs drain at STORE level — each unique blob
    # uploads once store-wide (store journal keyed by hash), to the
    # STORE's remote, never as per-snapshot private copies.
    cas_refs: int = 0
    cas_blobs_uploaded: int = 0
    cas_blobs_skipped: int = 0
    bases: List["DrainReport"] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        d = {k: v for k, v in self.__dict__.items() if k != "bases"}
        d["bases"] = [b.to_json() for b in self.bases]
        return d

    def summary(self) -> str:
        s = (
            f"{self.local_dir} -> {self.remote_url}: {self.state} — "
            f"{self.blobs_uploaded}/{self.blobs_total} blob(s) uploaded "
            f"({self.bytes_uploaded} bytes), {self.blobs_skipped} skipped "
            f"via journal evidence ({self.bytes_skipped} bytes)"
        )
        if self.cas_refs:
            s += (
                f"; {self.cas_refs} CAS ref(s) drained store-level "
                f"({self.cas_blobs_uploaded} blob(s) uploaded, "
                f"{self.cas_blobs_skipped} already proven remote)"
            )
        if self.lag_bytes:
            s += f"; {self.lag_bytes} bytes still local-only"
        if self.error:
            s += f" [{self.error}]"
        return s


class _Circuit:
    """The uploader's sustained-outage circuit breaker: consecutive
    op failures past the threshold open it (one edge-triggered
    ``tier_degraded`` flight event + counter per episode); any success
    closes it (``tier_recovered``). While open, callers back off with
    capped exponential + jitter instead of hammering the endpoint."""

    def __init__(self, remote_url: str) -> None:
        from .knobs import get_tier_backoff_cap_s, get_tier_outage_threshold

        self.remote_url = remote_url
        self.threshold = get_tier_outage_threshold()
        self.backoff_cap_s = get_tier_backoff_cap_s()
        self.failures = 0
        self.open = False
        self.episodes = 0

    def record_failure(self, exc: Exception) -> None:
        self.failures += 1
        if not self.open and self.failures >= self.threshold:
            self.open = True
            self.episodes += 1
            telemetry.incr("tier.degraded_episodes")
            flight.record(
                "tier_degraded",
                op="circuit_open",
                remote=self.remote_url,
                failures=self.failures,
                error=type(exc).__name__,
            )
            logger.warning(
                "write-back tier DEGRADED: %d consecutive upload failures "
                "against %s (%s) — takes keep committing locally; the "
                "drain keeps probing with capped backoff",
                self.failures,
                self.remote_url,
                exc,
            )

    def record_success(self) -> None:
        if self.open:
            self.open = False
            flight.record(
                "tier_recovered", op="circuit_close", remote=self.remote_url
            )
            logger.info(
                "write-back tier recovered: %s reachable again; drain "
                "resuming",
                self.remote_url,
            )
        self.failures = 0

    def backoff_s(self) -> float:
        raw = min(0.1 * (2 ** min(self.failures, 16)), self.backoff_cap_s)
        return raw * (0.5 + random.random())


def _remote_sibling(remote_url: str, rel: str) -> str:
    """Apply a relative base reference (``../B`` style root, as recorded
    in ``metadata.base_roots``) to a remote URL textually."""
    scheme, _, path = remote_url.partition("://")
    segs = [s for s in path.split("/") if s not in ("", ".")]
    lead = "/" if path.startswith("/") else ""
    for part in rel.split("/"):
        if part in ("", "."):
            continue
        if part == "..":
            if segs:
                segs.pop()
        else:
            segs.append(part)
    return f"{scheme}://{lead}{'/'.join(segs)}"


def _external_base_roots(metadata) -> List[str]:
    """The relative base roots this snapshot's manifest references —
    drained FIRST so the remote tier restores the instant this
    snapshot's metadata lands (delta-stream parents reference their
    chain the same way, which is what makes the drain chain-aware:
    bases before deltas)."""
    from .inspect import base_root_of_location, iter_blobs

    roots = set()
    for b in iter_blobs(metadata.manifest):
        if b.location.startswith("../"):
            roots.add(base_root_of_location(b.location, metadata.base_roots))
    return sorted(roots)


def drain_snapshot(
    path: str,
    remote_url: Optional[str] = None,
    storage_options: Optional[Dict[str, Any]] = None,
    *,
    deadline_s: Optional[float] = None,
    should_abort: Optional[Callable[[], bool]] = None,
) -> DrainReport:
    """Drain one snapshot to remote-durable (synchronously; the unit of
    work both the background uploader and the ``drain`` CLI run).

    ``path`` may be a tier URL or a bare local tier directory (the
    upload journal then names the remote unless ``remote_url``
    overrides it). ``deadline_s`` bounds how long a sustained outage is
    tolerated before returning a ``degraded`` report (None = keep
    probing until it converges or ``should_abort`` fires)."""
    spec = parse_tier_url(path)
    if spec is not None:
        local_dir = spec.local_dir
        remote_url = remote_url or spec.remote_url
    else:
        local_dir = path
    if remote_url is None:
        journal = read_upload_journal_dir(local_dir)
        remote_url = (journal or {}).get("remote")
        if not remote_url:
            return DrainReport(
                local_dir=local_dir,
                remote_url="",
                state="no-metadata",
                error=(
                    "no remote tier recorded: pass a tier URL, or a local "
                    "dir whose upload journal names the remote"
                ),
            )
    deadline = (
        time.monotonic() + deadline_s if deadline_s is not None else None
    )

    def give_up() -> bool:
        if should_abort is not None and should_abort():
            return True
        return deadline is not None and time.monotonic() > deadline

    return _drain_one(
        local_dir, remote_url, storage_options, give_up, visited=set()
    )


def _drain_one(
    local_dir: str,
    remote_url: str,
    storage_options: Optional[Dict[str, Any]],
    give_up: Callable[[], bool],
    visited: set,
    as_base: bool = False,
) -> DrainReport:
    from .knobs import get_tier_op_deadline_s
    from .manifest import decode_metadata
    from .snapshot import SNAPSHOT_METADATA_FNAME
    from .storage_plugin import url_to_storage_plugin

    report = DrainReport(
        local_dir=local_dir, remote_url=remote_url, state="degraded"
    )
    key = os.path.abspath(local_dir)
    if key in visited:
        report.state = "durable"  # cycle guard; parent already handles it
        return report
    visited.add(key)

    if as_base:
        # Base recursion short-circuit: an already-durable base needs no
        # work — without this, EVERY delta micro-commit's drain would
        # re-read and re-hash its whole (multi-GB, long-durable) base
        # chain on the training host. An explicit top-level drain still
        # runs the full re-verify pass.
        journal0 = read_upload_journal_dir(local_dir)
        if journal0 is not None and journal0.get("state") == "durable":
            report.state = "durable"
            return report

    event_loop = asyncio.new_event_loop()
    local = remote = None
    try:
        local_opts = dict(storage_options or {})
        local_opts.pop("fault_plan", None)
        # The drain reads the RAW local dir: a CAS-composed view would
        # synthesize ref'd locations into the listing and resolve their
        # reads through the store — the drain would then upload shared
        # blobs as per-snapshot private copies, the exact N× the store
        # exists to kill. Refs drain at store level below instead.
        local_opts["cas"] = False
        local = url_to_storage_plugin(local_dir, local_opts or None)

        # 1. Local metadata: without a local commit there is nothing to
        # make durable (a torn take's blobs are salvage fuel, not a
        # drain unit).
        read_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
        try:
            local.sync_read(read_io, event_loop)
            meta_bytes = read_io.buf.getvalue()
            metadata = decode_metadata(meta_bytes)
        except Exception as e:
            report.state = "no-metadata"
            report.error = f"local metadata unreadable: {e}"
            return report

        # 2. Chain-aware: drain external bases (incremental bases,
        # delta-stream parents) to their remote siblings FIRST.
        for rel in _external_base_roots(metadata):
            base_local = os.path.normpath(os.path.join(local_dir, rel))
            # The base's own upload journal is the authoritative remote
            # target (a base taken through the tier recorded it; the
            # recorded relative root may walk arbitrarily far up the
            # tree, so textual sibling math is only the fallback for
            # hand-mirrored layouts).
            base_remote = (read_upload_journal_dir(base_local) or {}).get(
                "remote"
            ) or _remote_sibling(remote_url, rel)
            base_report = _drain_one(
                base_local,
                base_remote,
                storage_options,
                give_up,
                visited,
                as_base=True,
            )
            report.bases.append(base_report)
            if base_report.state != "durable":
                # A child must never outrun its chain: the remote can
                # only restore this snapshot once every base it
                # references is remote-durable.
                report.state = (
                    "degraded"
                    if base_report.state == "degraded"
                    else base_report.state
                )
                report.error = (
                    f"base {rel!r} did not converge "
                    f"({base_report.state}): {base_report.error}"
                )
                report.lag_bytes = base_report.lag_bytes
                return report

        # 3. Journal + pending set.
        journal = read_upload_journal(local, event_loop) or {
            "version": 1,
            "blobs": {},
        }
        journal["remote"] = remote_url
        evidence: Dict[str, list] = dict(journal.get("blobs") or {})
        files = local.sync_list_with_sizes(event_loop) or {}
        # Drain what a restore can reach: the manifest's referenced
        # LOCAL locations. Orphans, superseded-take leftovers and
        # ``.tmp.<pid>`` debris are gc's business — uploading them
        # would pay cloud bandwidth/storage for unreachable bytes and
        # inflate the lag gauge forever.
        from .lifecycle import _referenced_locations

        referenced = _referenced_locations(metadata)
        pending = sorted(p for p in referenced if p in files)
        # Content-addressed refs: locations this snapshot holds as
        # shared-store refs have no local file by design — they are
        # neither "pending" (the store drains them, below) nor
        # "unreachable" (the ref record IS their reachability).
        from .cas import read_refs as _read_cas_refs
        from .cas import resolve_store_url as _resolve_cas_store

        cas_ref_map, cas_store_url = _read_cas_refs(local, event_loop)
        cas_store_url = cas_store_url or _resolve_cas_store()
        ref_locs = {
            p for p in referenced if p in cas_ref_map and p not in files
        }
        report.cas_refs = len(ref_locs)
        # Referenced blobs neither present locally NOR carried in the
        # evidence map cannot reach the remote: refusing the durable
        # marker beats blessing a snapshot the remote cannot restore.
        # (Absent-but-evidenced = evicted past a previous durable
        # marker: the remote already holds them.)
        unreachable = sorted(
            p
            for p in referenced
            if p not in files and p not in evidence and p not in ref_locs
        )
        if unreachable:
            report.state = "missing-blobs"
            report.error = (
                f"{len(unreachable)} referenced blob(s) neither present "
                "locally nor proven remote (e.g. "
                f"{unreachable[0]!r}) — run fsck; refusing to mark "
                "remote-durable"
            )
            return report
        report.blobs_total = len(pending)
        already_durable = journal.get("state") == "durable"
        # The commit stamp THIS drain is making durable: a retake that
        # commits to the same dir while the drain runs re-stamps the
        # journal, and the durable marker must never be written over a
        # newer stamp (it would falsely bless bytes the remote does not
        # hold — and license `gc --evict-local` to delete their only
        # copy).
        drain_stamp = journal.get("committed_at")

        remote_opts = dict(storage_options or {})
        remote_opts.setdefault("retry_deadline_sec", get_tier_op_deadline_s())
        remote = url_to_storage_plugin(remote_url, remote_opts)
        circuit = _Circuit(remote_url)

        def flush_journal(mark_durable: bool = False) -> bool:
            """Merge this drain's evidence into the CURRENT on-disk
            journal (read-modify-write, never blind overwrite): a
            concurrent retake's pending stamp survives every flush.
            ``mark_durable`` writes the durable marker ONLY when the
            on-disk commit stamp is still the one this drain read at
            start; returns False (superseded) otherwise."""
            current = read_upload_journal(local, event_loop) or {
                "version": 1,
                "blobs": {},
            }
            current["remote"] = remote_url
            blobs = dict(current.get("blobs") or {})
            blobs.update(evidence)
            current["blobs"] = blobs
            superseded = current.get("committed_at") != drain_stamp
            if mark_durable and not superseded:
                current["state"] = "durable"
                current["durable_at"] = _wall()
            local.sync_write_atomic(
                WriteIO(
                    path=UPLOAD_JOURNAL_PATH,
                    buf=json.dumps(current).encode("utf-8"),
                ),
                event_loop,
            )
            journal.clear()
            journal.update(current)
            return not superseded

        lag = _pending_bytes(files, pending, evidence)
        _publish_status(
            state="draining",
            snapshot=local_dir,
            remote=remote_url,
            lag_bytes=lag,
            oldest_commit_ts=journal.get("committed_at"),
            degraded=False,
        )

        from .lifecycle import dual_hash_evidence

        # 4. Blob loop: hash local bytes; journal evidence matching the
        # fresh dual hash licenses a skip (the bytes are already proven
        # remote); everything else uploads, then records evidence and
        # flushes the journal BEFORE the next blob — the crash-safety
        # granularity a resumed drain skips on.
        for p in pending:
            read_io = ReadIO(path=p)
            local.sync_read(read_io, event_loop)
            buf = read_io.buf.getbuffer()
            triple = list(dual_hash_evidence(buf))
            prior = evidence.get(p)
            # Zero-byte blobs skip like any other: the evidence is the
            # (0, crc-of-empty, xxh-of-empty) triple, and re-uploading
            # them would re-fire tier_durable on every re-drain.
            if prior is not None and list(prior) == triple:
                report.blobs_skipped += 1
                report.bytes_skipped += triple[0]
                telemetry.incr("tier.blobs_skipped")
                telemetry.incr("tier.bytes_skipped", triple[0])
                continue
            while True:
                if give_up():
                    report.lag_bytes = _pending_bytes(files, pending, evidence)
                    report.degraded_episodes = circuit.episodes
                    report.error = report.error or (
                        "drain deadline reached while the remote is "
                        "unavailable"
                    )
                    _publish_status(
                        state="degraded", lag_bytes=report.lag_bytes,
                        degraded=True,
                    )
                    return report
                try:
                    remote.sync_write(WriteIO(path=p, buf=buf), event_loop)
                    circuit.record_success()
                    break
                except Exception as e:
                    circuit.record_failure(e)
                    report.error = f"{type(e).__name__}: {e}"
                    _publish_status(
                        state="degraded" if circuit.open else "draining",
                        lag_bytes=_pending_bytes(files, pending, evidence),
                        degraded=circuit.open,
                    )
                    _interruptible_sleep(circuit.backoff_s(), give_up)
            evidence[p] = triple
            report.blobs_uploaded += 1
            report.bytes_uploaded += triple[0]
            telemetry.incr("tier.blobs_uploaded")
            telemetry.incr("tier.bytes_uploaded", triple[0])
            flush_journal()
            _publish_status(
                state="draining",
                lag_bytes=_pending_bytes(files, pending, evidence),
                degraded=False,
            )

        # 4b. CAS refs drain at STORE level: each unique blob uploads
        # once store-wide to the STORE's remote, with the store journal
        # (keyed by hash) as the skip evidence — N branched snapshots
        # referencing one base pay one upload, not N. The durable
        # marker below requires store-journal proof for EVERY ref'd
        # key: this snapshot's own journal proves nothing about shared
        # blobs.
        if ref_locs:
            from .cas import blob_key as _cas_key
            from .cas import drain_store, store_remote_evidence
            from .io_types import CAS_REFS_DIR

            keys = {_cas_key(tuple(cas_ref_map[p])) for p in ref_locs}
            if not cas_store_url:
                report.state = "missing-blobs"
                report.error = (
                    f"{len(ref_locs)} CAS ref(s) but no store is "
                    "configured (TPUSNAP_CAS_DIR unset and no ref "
                    "record names one) — refusing the durable marker"
                )
                return report
            store_report = drain_store(
                cas_store_url, keys=keys, storage_options=storage_options
            )
            report.cas_blobs_uploaded = store_report.uploaded
            report.cas_blobs_skipped = store_report.skipped
            proven, _ = store_remote_evidence(cas_store_url, keys)
            unproven = sorted(keys - proven)
            if unproven:
                report.state = (
                    "missing-blobs"
                    if store_report.state == "no-remote"
                    else "degraded"
                )
                report.error = (
                    f"store drain left {len(unproven)} ref'd blob(s) "
                    f"unproven remote ({store_report.summary()}) — "
                    "refusing the durable marker"
                )
                return report
            # Ref records ride to the remote dir before the metadata:
            # a restore from the bare remote can then resolve every
            # ref against the store's remote mirror.
            for p in sorted(files):
                if not p.startswith(CAS_REFS_DIR + "/") or ".tmp." in p:
                    continue
                ref_io = ReadIO(path=p)
                local.sync_read(ref_io, event_loop)
                remote.sync_write_atomic(
                    WriteIO(path=p, buf=ref_io.buf.getvalue()), event_loop
                )

        # 5. Remote metadata LAST (the remote tier becomes a committed
        # snapshot only now), then verify by read-back before the
        # durable marker — the marker must never promise what the
        # remote cannot prove it holds.
        while True:
            if give_up():
                report.lag_bytes = len(meta_bytes)
                report.degraded_episodes = circuit.episodes
                report.error = report.error or (
                    "remote metadata commit did not converge"
                )
                _publish_status(state="degraded", degraded=True,
                                lag_bytes=report.lag_bytes)
                return report
            try:
                remote.sync_write_atomic(
                    WriteIO(path=SNAPSHOT_METADATA_FNAME, buf=meta_bytes),
                    event_loop,
                )
                verify_io = ReadIO(path=SNAPSHOT_METADATA_FNAME)
                remote.sync_read(verify_io, event_loop)
                if verify_io.buf.getvalue() != meta_bytes:
                    raise IOError(
                        "remote metadata read-back does not match the "
                        "committed local bytes"
                    )
                decode_metadata(verify_io.buf.getvalue())
                circuit.record_success()
                break
            except Exception as e:
                circuit.record_failure(e)
                report.error = f"{type(e).__name__}: {e}"
                _interruptible_sleep(circuit.backoff_s(), give_up)

        # 6. The durable marker, strictly after the verify — and only
        # if no newer local commit landed while this drain ran (the
        # remote then holds a SUPERSEDED snapshot; the caller/manager
        # re-drains to converge).
        if not flush_journal(mark_durable=True):
            report.state = "superseded"
            report.error = (
                "a newer local commit landed during this drain; "
                "re-drain to converge the remote"
            )
            report.lag_bytes = 0
            report.degraded_episodes = circuit.episodes
            _publish_status(
                state="draining", degraded=False,
                snapshot=local_dir, remote=remote_url,
            )
            return report
        report.state = "durable"
        report.error = ""
        report.lag_bytes = 0
        report.degraded_episodes = circuit.episodes
        if not already_durable or report.blobs_uploaded:
            telemetry.incr("tier.drains_completed")
            flight.record(
                "tier_durable",
                op=local_dir,
                remote=remote_url,
                uploaded=report.blobs_uploaded,
                skipped=report.blobs_skipped,
            )
        _publish_status(
            state="durable", lag_bytes=0, degraded=False,
            oldest_commit_ts=None,  # nothing awaits durability anymore
            snapshot=local_dir, remote=remote_url,
        )
        return report
    finally:
        try:
            for plugin in (remote, local):
                if plugin is None:
                    continue
                try:
                    plugin.sync_close(event_loop)
                except Exception:
                    logger.debug("drain plugin close failed", exc_info=True)
        finally:
            event_loop.close()


def _pending_bytes(
    files: Dict[str, int], pending: List[str], evidence: Dict[str, list]
) -> int:
    return sum(
        files[p]
        for p in pending
        if evidence.get(p) is None or evidence[p][0] != files[p]
    )


def _interruptible_sleep(seconds: float, give_up: Callable[[], bool]) -> None:
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        if give_up():
            return
        time.sleep(min(0.05, max(end - time.monotonic(), 0.0)))


# ------------------------------------------------------ background drain


class DrainManager:
    """Process-global background uploader: one daemon thread draining a
    deduplicated queue of (local_dir, remote_url) jobs. Deliberately
    survives plugin close — durability converges across takes — and
    deliberately owns NO shutdown blocking: a process exit mid-drain is
    exactly the crash the upload journal makes cheap to resume."""

    def __init__(self) -> None:
        self._cv = threading.Condition()
        self._backlog_lock = threading.Lock()
        self._queue: List[Tuple[str, str, Optional[Dict[str, Any]]]] = []
        self._active: Optional[str] = None
        # Jobs re-enqueued WHILE active (a retake committing to the dir
        # the drain is currently working): remembered and re-queued when
        # the active job finishes — dropping them would leave the
        # retake's bytes local-committed forever despite auto-drain.
        self._dirty: Dict[str, Tuple[str, str, Optional[Dict[str, Any]]]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = False

    def enqueue(
        self,
        local_dir: str,
        remote_url: str,
        storage_options: Optional[Dict[str, Any]] = None,
    ) -> None:
        key = os.path.abspath(local_dir)
        with self._cv:
            if self._stop:
                return
            if key == self._active:
                self._dirty[key] = (local_dir, remote_url, storage_options)
            elif all(os.path.abspath(j[0]) != key for j in self._queue):
                self._queue.append((local_dir, remote_url, storage_options))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run,
                    name="tpusnap-tier-drain",
                    daemon=True,
                )
                self._thread.start()
            self._cv.notify_all()
        self._publish_backlog()

    def _publish_backlog(self) -> None:
        """Fold the QUEUED (not-yet-active) snapshots' local-only bytes
        into the published lag: during a sustained outage micro-commits
        pile up behind the one stuck job, and a gauge that only counted
        the active drain would understate the exposure by the whole
        queue. Each queued dir is one journal read + payload walk —
        queues are short (deduplicated per dir). Snapshot-compute-
        publish runs atomically under one lock: without it, an
        enqueue-time publisher that computed from the pre-pop queue
        could land AFTER the dequeue's fresh zero and stick a stale
        backlog in the gauge forever."""
        with self._backlog_lock:
            with self._cv:
                queued = [j[0] for j in self._queue]
            backlog = 0
            for d in queued:
                try:
                    st = tier_state_of_dir(d)
                    backlog += int((st or {}).get("lag_bytes") or 0)
                except Exception:
                    continue
            _publish_status(queued_lag_bytes=backlog)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
                local_dir, remote_url, opts = self._queue.pop(0)
                self._active = os.path.abspath(local_dir)
            self._publish_backlog()
            rerun = False
            try:
                report = drain_snapshot(
                    local_dir,
                    remote_url,
                    opts,
                    should_abort=lambda: self._stop,
                )
                # A drain superseded by a concurrent retake must run
                # again even if no enqueue raced the active window.
                rerun = report.state == "superseded"
            except Exception:
                logger.warning(
                    "background drain of %r failed (will not retry until "
                    "the next take or an explicit `tpusnap drain`)",
                    local_dir,
                    exc_info=True,
                )
            finally:
                with self._cv:
                    key, self._active = self._active, None
                    dirty = self._dirty.pop(key, None)
                    if dirty is not None:
                        self._queue.append(dirty)
                    elif rerun and not self._stop:
                        self._queue.append((local_dir, remote_url, opts))
                    self._cv.notify_all()

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until the queue is empty and no job is active (tests;
        True when idle was reached within ``timeout``)."""
        end = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._cv:
            while self._queue or self._active is not None:
                remaining = None
                if end is not None:
                    remaining = end - time.monotonic()
                    if remaining <= 0:
                        return False
                self._cv.wait(timeout=remaining if remaining else 0.1)
            return True

    def stop(self) -> None:
        """Test aid: abort the current job at its next blob/backoff
        boundary and park the thread. The journal keeps everything
        resumable."""
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=10)
        with self._cv:
            self._stop = False
            self._thread = None
            self._queue.clear()
            self._active = None


_manager: Optional[DrainManager] = None
_manager_lock = threading.Lock()


def drain_manager() -> DrainManager:
    global _manager
    with _manager_lock:
        if _manager is None:
            _manager = DrainManager()
        return _manager


def reset_manager_for_tests() -> None:
    global _manager
    with _manager_lock:
        m, _manager = _manager, None
    if m is not None:
        m.stop()


# ------------------------------------------------- tier-aware SLO seams


def restore_source_label(path: str) -> Optional[str]:
    """The storage-plugin class label a restore of ``path`` would
    actually read its bytes from — the tier-aware input to the SLO RTO
    estimator. None for non-tiered snapshots (no filter: today's
    single-backend behavior).

    For a tiered snapshot (tier URL, or a local tier dir carrying an
    upload journal): the LOCAL tier's label while every referenced blob
    is still cached locally, the REMOTE tier's once any has been
    evicted — a restore falls back per blob, and the evicted bytes
    dominate its wall-clock."""
    try:
        spec = parse_tier_url(path)
    except ValueError:
        return None
    if spec is not None:
        local_dir = spec.local_dir
        remote_scheme = spec.remote_scheme
    else:
        if "://" in path:
            scheme = path.split("://", 1)[0].lower()
            if scheme.startswith("chaos+"):
                scheme = scheme[len("chaos+"):]
            if scheme not in ("", "fs", "file"):
                return None
            local_dir = path.split("://", 1)[1]
        else:
            local_dir = path
        remote_scheme = None
    journal = read_upload_journal_dir(local_dir)
    if journal is None:
        return None
    if remote_scheme is None:
        remote = str(journal.get("remote") or "")
        remote_scheme = remote.split("://", 1)[0] if "://" in remote else "fs"
    try:
        from .lifecycle import _referenced_locations
        from .manifest import decode_metadata
        from .snapshot import SNAPSHOT_METADATA_FNAME

        with open(os.path.join(local_dir, SNAPSHOT_METADATA_FNAME), "rb") as f:
            metadata = decode_metadata(f.read())
        referenced = _referenced_locations(metadata)
        all_local = all(
            os.path.exists(os.path.join(local_dir, loc)) for loc in referenced
        )
    except Exception:
        all_local = False
    if all_local:
        return scheme_plugin_label("fs")
    return scheme_plugin_label(remote_scheme)


def tier_state_of_dir(local_dir: str) -> Optional[Dict[str, Any]]:
    """Compact per-snapshot tier state for CLI surfaces (``info``,
    ``watch``, ``drain --status``): durability, remote target, and the
    local-only lag derived from the journal evidence vs the blobs on
    disk. None when the directory is not a tiered snapshot."""
    from .snapshot import SNAPSHOT_METADATA_FNAME

    journal = read_upload_journal_dir(local_dir)
    if journal is None:
        return None
    evidence = journal.get("blobs") or {}
    # Referenced locations only, matching what the drain will actually
    # ship (orphans/debris are gc's business, not upload lag). Falls
    # back to a whole-tree walk when the metadata is unreadable (torn
    # local state — everything non-sidecar counts as exposed).
    referenced = None
    try:
        from .lifecycle import _referenced_locations
        from .manifest import decode_metadata

        with open(os.path.join(local_dir, SNAPSHOT_METADATA_FNAME), "rb") as f:
            referenced = _referenced_locations(decode_metadata(f.read()))
    except Exception:
        referenced = None
    lag = 0
    pending = 0
    try:
        for dirpath, _dirnames, filenames in os.walk(local_dir):
            rel_dir = os.path.relpath(dirpath, local_dir).replace(os.sep, "/")
            if rel_dir == SIDECAR_PREFIX.rstrip("/") or rel_dir.startswith(
                SIDECAR_PREFIX
            ):
                continue
            for name in filenames:
                rel = name if rel_dir == "." else f"{rel_dir}/{name}"
                if rel.startswith(SIDECAR_PREFIX) or rel == SNAPSHOT_METADATA_FNAME:
                    continue
                if referenced is not None and rel not in referenced:
                    continue
                try:
                    size = os.path.getsize(os.path.join(dirpath, name))
                except OSError:
                    continue
                rec = evidence.get(rel)
                if rec is None or rec[0] != size:
                    lag += size
                    pending += 1
    except OSError:
        pass
    return {
        "durability": durability_of_journal(journal),
        "remote": journal.get("remote"),
        "state": journal.get("state"),
        "committed_at": journal.get("committed_at"),
        "durable_at": journal.get("durable_at"),
        "lag_bytes": lag,
        "pending_blobs": pending,
        "evidenced_blobs": len(evidence),
    }
