"""PytreeState — wrap any JAX pytree (flax params, optax optimizer state,
a TrainState) as a Stateful.

tpusnap extension with no reference counterpart: the reference leans on
torch modules implementing state_dict() themselves; JAX state is plain
pytrees. ``state_dict`` exposes the tree as nested containers (dict/list/
tuple — NamedTuples and custom pytree nodes flatten through
``jax.tree_util``), and ``load_state_dict`` restores values while
preserving the ORIGINAL tree structure, so NamedTuple/custom-node types
survive the round-trip even though the snapshot stores generic containers.
"""

from typing import Any, Dict

import jax


class PytreeState:
    def __init__(self, tree: Any) -> None:
        self._tree = tree

    @property
    def tree(self) -> Any:
        return self._tree

    def state_dict(self) -> Dict[str, Any]:
        leaves = jax.tree_util.tree_leaves(self._tree)
        return {"leaves": leaves}

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        treedef = jax.tree_util.tree_structure(self._tree)
        leaves = state_dict["leaves"]
        if treedef.num_leaves != len(leaves):
            raise ValueError(
                f"Snapshot holds {len(leaves)} leaves but the target pytree "
                f"has {treedef.num_leaves}"
            )
        self._tree = jax.tree_util.tree_unflatten(treedef, leaves)
