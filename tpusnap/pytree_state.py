"""PytreeState — wrap any JAX pytree (flax params, optax optimizer state,
a TrainState) as a Stateful.

tpusnap extension with no reference counterpart: the reference leans on
torch modules implementing state_dict() themselves; JAX state is plain
pytrees. ``state_dict`` exposes the tree as nested dicts keyed by the
pytree *key path* (``jax.tree_util.tree_flatten_with_path``), so every
leaf has a stable human-readable logical path in the snapshot manifest —
``emb/tables/t0`` — addressable by ``Snapshot.read_object`` exactly like
the reference's named state-dict entries. ``load_state_dict`` restores
values by the same paths while preserving the ORIGINAL tree structure, so
NamedTuple/custom-node types survive the round-trip even though the
snapshot stores generic containers.

(Snapshots written by the pre-named-path format — index-keyed
``leaves/N`` entries — are not loadable by this class: the in-place
restore machinery matches snapshot entries to target leaves by path, so
an index-keyed snapshot would silently lose sharding/placement. The
format changed before any release.)
"""

from typing import Any, Dict, List, Tuple

import jax


def _segments(path: Tuple[Any, ...]) -> List[str]:
    segs: List[str] = []
    for k in path:
        if isinstance(k, jax.tree_util.DictKey):
            segs.append(str(k.key))
        elif isinstance(k, jax.tree_util.SequenceKey):
            segs.append(str(k.idx))
        elif isinstance(k, jax.tree_util.GetAttrKey):
            segs.append(k.name)
        elif isinstance(k, jax.tree_util.FlattenedIndexKey):
            segs.append(str(k.key))
        else:  # future key types: fall back to their repr
            segs.append(str(k))
    return segs


class PytreeState:
    def __init__(self, tree: Any) -> None:
        self._tree = tree

    @property
    def tree(self) -> Any:
        return self._tree

    @tree.setter
    def tree(self, new_tree: Any) -> None:
        self._tree = new_tree

    def state_dict(self) -> Dict[str, Any]:
        paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(
            self._tree
        )
        if treedef.num_leaves == 1 and not paths_and_leaves[0][0]:
            # Bare-leaf tree: store under a sentinel key unlikely to
            # collide with a real pytree dict key.
            return {"__value__": paths_and_leaves[0][1]}
        out: Dict[str, Any] = {}
        for path, leaf in paths_and_leaves:
            segs = _segments(path)
            node = out
            for seg in segs[:-1]:
                node = node.setdefault(seg, {})
                if not isinstance(node, dict):
                    raise ValueError(
                        f"pytree key path collision at {'/'.join(segs)!r}"
                    )
            if segs[-1] in node:
                raise ValueError(
                    f"pytree key paths collide after string conversion: "
                    f"{'/'.join(segs)!r}"
                )
            node[segs[-1]] = leaf
        return out

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(
            self._tree
        )

        def lookup(path):
            if not path:
                return state_dict["__value__"]
            node: Any = state_dict
            segs = _segments(path)
            for seg in segs:
                if not isinstance(node, dict) or seg not in node:
                    raise KeyError(
                        f"snapshot is missing pytree path {'/'.join(segs)!r}"
                    )
                node = node[seg]
            if isinstance(node, dict):
                # The snapshot's tree is deeper here than the target's —
                # installing a container as a leaf would surface as a
                # confusing failure far from the cause.
                raise ValueError(
                    f"snapshot holds a subtree at {'/'.join(segs)!r} where "
                    "the target pytree has a leaf"
                )
            return node

        leaves = [lookup(path) for path, _ in paths_and_leaves]
        self._tree = jax.tree_util.tree_unflatten(treedef, leaves)
