"""Continuous delta checkpointing: streaming micro-commits for
seconds-scale RPO with crash-replay restore (ROADMAP 4).

A classic take is a periodic stop-the-world event: a crash loses
everything since the last one — minutes of work at fleet cadences, and
the PR 10 SLO tracker can only *measure* that exposure. This module
composes primitives the system already owns — incremental dedup's
dual-hash (CRC32C+XXH64) change detection, strict-staging incremental
``async_take``, the crash-safe journal, salvage-resume and fsck's
torn-tail classification — into a **streaming delta mode** with a
tunable recovery-point objective:

- :meth:`tpusnap.Snapshot.stream` opens a :class:`DeltaStream` under a
  root directory: one full **base** snapshot now (with per-tile dedup
  hashes recorded, so every blob has tile grain from the first
  increment), then one **micro-commit** per cadence interval — a real,
  journaled, metadata-written-last incremental snapshot referencing the
  previous committed member, shipping only tiles/blobs whose fresh
  dual-hash pair changed. An unchanged model streams ~zero payload
  bytes; one mutated row of a multi-GB array streams ~one checksum
  tile.
- Because incremental writers **collapse chained references** (each
  member's external locations point at the member that physically holds
  the bytes — never through an intermediate), the chain never deepens
  lookups: ``Snapshot(head).restore`` / ``read_object`` work
  transparently on any member, reading base + changed blobs flat.
- Every micro-commit runs the unchanged crash machinery: a SIGKILL
  mid-commit leaves a **torn tail** the journal classifies (fsck names
  it "torn delta micro-commit seq N over member X"), gc'd or salvaged
  like any torn take — and recovery lands on the last committed
  increment via :func:`resolve_chain`. Each commit also anchors the SLO
  tracker, turning ``tpusnap_rpo_seconds`` from take-interval minutes
  into stream-cadence seconds.
- Chains stay bounded: past ``TPUSNAP_DELTA_MAX_CHAIN`` members the
  stream **compacts** — ``materialize`` copies the head's referenced
  blobs in (checksum-verified, committed atomically), making it the new
  self-contained base, and the superseded members are retired.

Step-consistency contract (the ``staged()``/mutate-after-return
contract, streamed):

- **Functional JAX updates** (the normal case) never need coordination:
  the capture stages from the array objects it was handed; new arrays
  produced by a later step are different objects.
- **In-place mutators** (raw numpy buffers, donated pinned_host) call
  :meth:`DeltaStream.mark_step` once per training step. The stream then
  defers each due capture to the next ``mark_step`` call and performs
  it inline there — on the training thread, at a step boundary — so no
  capture ever overlaps a mutation. The capture cost is the strict
  incremental staging window (the dual-hash pass; writes and the
  two-phase commit drain on the background thread). Free-running
  captures (no ``mark_step`` caller) run entirely on the stream's
  worker thread and guarantee blob-grain consistency only.
- :meth:`DeltaStream.commit_now` forces a synchronous micro-commit and
  returns the committed :class:`~tpusnap.Snapshot`;
  :meth:`DeltaStream.close` stops the stream (with a final commit by
  default).

Multi-process streams are **elastic**: the minimum joined rank — the
*driver* — announces each capture epoch over the jax.distributed
coordination KV; every member polls for the announcement and joins the
epoch's collective micro-commit over a fresh per-epoch
:class:`~tpusnap.comm.SubsetComm`, so each micro-commit is a real
multi-rank incremental take riding the unchanged journal /
metadata-written-last machinery, with the participating world recorded
in ``extras["delta"]["world"]`` (and in the take journal, so a torn
epoch still names its world). Death and resize are stream events, not
wedges:

- a rank dying mid-epoch (lease expiry → ``RankFailedError``) lets the
  survivors complete the epoch DEGRADED when every leaf is replicated
  (the PR 15 degraded-commit protocol, extended to the stream's
  force-clone-staged incremental async takes via ``stream_capture``);
  the dead rank is expired from the membership and streaming continues;
- sharded state refuses adoption: the torn epoch aborts (its salvage
  substrate kept) and the stream **pauses** —
  :attr:`DeltaStream.paused` / ``pause_info`` name the event; reopening
  ``Snapshot.stream`` on the root resumes the committed chain and the
  retake of the torn member salvages its journal-proven blobs;
- ranks leave gracefully via :meth:`DeltaStream.leave` (a terminal
  ``left`` member/lease state — watchers render LEFT, never DEAD) and
  join a LIVE stream by calling ``Snapshot.stream`` on the same root;
  either way the next capture boundary re-plans the world through the
  take's own partitioner/resharding machinery.

Reopening a stream root after full shutdown resumes the committed
chain in place (single- and multi-process alike): the new stream
adopts the head's stream id and sequence, takes no new base, and its
first micro-commit retakes — and salvages — any torn tail.
"""

from __future__ import annotations

import json
import logging
import posixpath
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional
from urllib.parse import urlsplit

from . import flight, telemetry
from .comm import Communicator, get_communicator
from .knobs import get_delta_cadence_s, get_delta_max_chain

logger = logging.getLogger(__name__)

# Coordination-KV namespace of the elastic-stream control plane:
# `tpusnap_stream/<stream_id>/members/<rank>` membership records,
# `tpusnap_stream/<stream_id>/ann/<inc>/<epoch>` capture-epoch
# announcements, `tpusnap_stream_root/<digest(root)>` the root
# registration a joiner reads.
_STREAM_KV_ROOT = "tpusnap_stream"
# Follower poll interval for the next epoch announcement.
_ANN_POLL_S = 0.1

__all__ = [
    "DeltaStream",
    "DeltaChainReport",
    "ChainMember",
    "resolve_chain",
    "delta_payload_bytes",
]


def member_name(seq: int) -> str:
    """Canonical member directory name: ``base-000000`` for the stream's
    first full snapshot, ``delta-%06d`` for micro-commits. Chain
    structure is read from metadata (``extras["delta"]``), never parsed
    from names — a compacted head keeps its ``delta-*`` name while
    being fully self-contained."""
    return f"base-{seq:06d}" if seq == 0 else f"delta-{seq:06d}"


def delta_fields(metadata) -> Optional[Dict[str, Any]]:
    """The validated delta-chain fields of a committed snapshot's
    metadata — delegates to :func:`tpusnap.manifest_ops.
    delta_chain_fields`, the one place chain membership is decoded."""
    from .manifest_ops import delta_chain_fields

    return delta_chain_fields(metadata)


def delta_payload_bytes(metadata) -> int:
    """Bytes PHYSICALLY stored in this member's own directory — i.e.
    excluding external (``../``) references into earlier chain members.
    The numerator of delta write amplification: for an unchanged model
    this is ~zero; for one changed row of a tiled array it is ~one
    checksum tile."""
    from .inspect import iter_blobs

    total = 0
    for blob in iter_blobs(metadata.manifest):
        if blob.location.startswith("../"):
            continue
        if blob.byte_range is not None:
            total += blob.byte_range[1] - blob.byte_range[0]
    return total


# -------------------------------------------------------- chain resolution


@dataclass
class ChainMember:
    """One directory under a stream root, classified."""

    name: str
    state: str  # "committed" | "torn" | "debris"
    seq: Optional[int] = None
    parent: Optional[str] = None
    stream_id: Optional[str] = None
    created_at: Optional[float] = None
    payload_bytes: int = 0
    # Elastic-stream forensics (multi-process epochs). ``world`` is the
    # participating world recorded at capture time
    # (``{"size", "ranks", "joined"?, "left"?, "expired"?}`` with
    # GLOBAL process ids); ``degraded`` is the ``extras["degraded"]``
    # record of an epoch the survivors completed without a dead rank;
    # ``missing_ranks`` (torn members only) names the GLOBAL ranks
    # whose per-rank journal evidence never landed — the write the tear
    # interrupted.
    world: Optional[Dict[str, Any]] = None
    degraded: Optional[Dict[str, Any]] = None
    missing_ranks: Optional[List[int]] = None


@dataclass
class DeltaChainReport:
    """What :func:`resolve_chain` finds under a stream root.

    ``head`` is the RECOVERY POINT: the committed member with the
    highest sequence number — ``Snapshot(<root>/<head>).restore``
    replays base + committed deltas transparently. ``torn_tail`` names
    a member whose micro-commit was interrupted (journal present, no
    metadata): recovery IGNORES it (gc or the next stream's
    salvage-resume reclaims it). ``chain`` is the set of members the
    head's blob references actually span (head first) — what retention
    must keep alive for the head to stay restorable. ``superseded`` are
    committed members outside every live chain (compaction leftovers) —
    reclaimable. ``debris`` are half-deleted/foreign subdirectories
    (e.g. a compaction retire interrupted mid-rmtree)."""

    root: str
    members: List[ChainMember] = field(default_factory=list)
    head: Optional[str] = None  # member name
    torn_tail: Optional[str] = None
    chain: List[str] = field(default_factory=list)  # head first
    superseded: List[str] = field(default_factory=list)
    debris: List[str] = field(default_factory=list)

    @property
    def head_path(self) -> Optional[str]:
        return f"{self.root.rstrip('/')}/{self.head}" if self.head else None

    def summary(self) -> str:
        if not self.members:
            return f"{self.root}: no delta-stream members"
        s = (
            f"{self.root}: {len(self.members)} member(s), "
            f"head={self.head or 'NONE'}"
        )
        if self.chain:
            s += f", chain depth {len(self.chain)}"
        degraded = [m for m in self.members if m.degraded]
        if degraded:
            s += f", {len(degraded)} DEGRADED epoch(s)"
        if self.torn_tail:
            s += f", TORN TAIL {self.torn_tail} (recovery ignores it)"
            torn_m = next(
                (m for m in self.members if m.name == self.torn_tail), None
            )
            if torn_m is not None and torn_m.missing_ranks:
                s += (
                    f" — missing journal evidence from rank(s) "
                    f"{torn_m.missing_ranks}"
                )
        if self.superseded:
            s += f", {len(self.superseded)} superseded"
        if self.debris:
            s += f", {len(self.debris)} debris dir(s)"
        return s


def resolve_chain(
    root: str, storage_options: Optional[Dict[str, Any]] = None
) -> DeltaChainReport:
    """Scan a stream root and name the recovery head, the torn tail (if
    a crash interrupted a micro-commit) and the live chain. Read-only;
    works on any backend that can list. Exposed through
    ``python -m tpusnap info|fsck <root>`` when the root itself holds no
    ``.snapshot_metadata`` but contains chain members."""
    import asyncio

    from .io_types import ReadIO
    from .lifecycle import JOURNAL_FNAME, JOURNAL_RECORDS_DIR
    from .manifest import decode_metadata
    from .snapshot import SNAPSHOT_METADATA_FNAME
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    report = DeltaChainReport(root=root)
    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(
            root, event_loop, storage_options
        )
        try:
            files = storage.sync_list_with_sizes(event_loop)
            if not files:
                return report
            # Group by first path component: each member is a subdir.
            by_member: Dict[str, Dict[str, int]] = {}
            for path, size in files.items():
                member, sep, rest = path.partition("/")
                if sep:
                    by_member.setdefault(member, {})[rest] = size
            for name in sorted(by_member):
                sub = by_member[name]
                m = ChainMember(name=name, state="debris")
                if SNAPSHOT_METADATA_FNAME in sub:
                    read_io = ReadIO(
                        path=f"{name}/{SNAPSHOT_METADATA_FNAME}"
                    )
                    try:
                        storage.sync_read(read_io, event_loop)
                        md = decode_metadata(read_io.buf.getvalue())
                    except Exception:
                        report.members.append(m)
                        report.debris.append(name)
                        continue
                    m.state = "committed"
                    m.created_at = md.created_at
                    d = delta_fields(md)
                    if d is not None:
                        m.seq = d.get("seq")
                        m.parent = d.get("parent")
                        m.stream_id = d.get("stream")
                        w = d.get("world")
                        if isinstance(w, dict):
                            m.world = w
                    deg = (md.extras or {}).get("degraded")
                    if isinstance(deg, dict):
                        m.degraded = deg
                    try:
                        m.payload_bytes = delta_payload_bytes(md)
                    except Exception:
                        pass
                elif JOURNAL_FNAME in sub or any(
                    p.startswith(JOURNAL_RECORDS_DIR + "/") for p in sub
                ):
                    m.state = "torn"
                    read_io = ReadIO(path=f"{name}/{JOURNAL_FNAME}")
                    try:
                        from .lifecycle import TakeJournal

                        storage.sync_read(read_io, event_loop)
                        j = TakeJournal.from_json(
                            read_io.buf.getvalue().decode("utf-8")
                        )
                        if j.stream:
                            m.seq = j.stream.get("seq")
                            m.parent = j.stream.get("parent")
                            m.stream_id = j.stream.get("stream")
                            w = j.stream.get("world")
                            if isinstance(w, dict):
                                m.world = w
                                ranks = w.get("ranks")
                                if isinstance(ranks, list) and ranks:
                                    # Per-rank journal evidence present
                                    # under the torn member: a VIRTUAL
                                    # rank with no record file never
                                    # proved a single blob — name it by
                                    # its GLOBAL id.
                                    have = set()
                                    rec_pfx = JOURNAL_RECORDS_DIR + "/rank_"
                                    for p in sub:
                                        if p.startswith(rec_pfx):
                                            try:
                                                have.add(
                                                    int(p.rsplit("_", 1)[-1])
                                                )
                                            except ValueError:
                                                pass
                                    missing = [
                                        int(ranks[v])
                                        for v in range(len(ranks))
                                        if v not in have
                                    ]
                                    m.missing_ranks = missing or None
                    except Exception:
                        pass
                else:
                    report.debris.append(name)
                report.members.append(m)
        finally:
            storage.sync_close(event_loop)
    finally:
        event_loop.close()

    committed = [m for m in report.members if m.state == "committed"]
    chain_members = [m for m in committed if m.seq is not None]
    if chain_members:
        head = max(
            chain_members, key=lambda m: (m.seq, m.created_at or 0.0)
        )
        report.head = head.name
    elif committed:
        # Non-stream snapshots under the root (or pre-field members):
        # newest committed by created_at is still the best recovery
        # point resolve can offer.
        report.head = max(
            committed, key=lambda m: m.created_at or 0.0
        ).name
    torn = [m for m in report.members if m.state == "torn"]
    if torn:
        report.torn_tail = max(
            torn, key=lambda m: (m.seq is not None, m.seq or 0)
        ).name
    if report.head:
        report.chain = _chain_of(root, report.head, storage_options)
        live = set(report.chain)
        report.superseded = [
            m.name for m in committed if m.name not in live
        ]
    return report


def _chain_of(
    root: str,
    head_name: str,
    storage_options: Optional[Dict[str, Any]] = None,
) -> List[str]:
    """The member names the head's blob references actually span (head
    first) — the base_roots recorded at take time, resolved back to
    member names. Because writers collapse chained references, this IS
    the complete keep-alive set for the head; no transitive walk is
    needed (retention still walks transitively as defense in depth)."""
    from .inspect import load_snapshot_metadata

    head_path = f"{root.rstrip('/')}/{head_name}"
    try:
        md = load_snapshot_metadata(head_path, storage_options)
    except Exception:
        return [head_name]
    out = [head_name]
    for r in md.base_roots or []:
        # Base roots are relative to the member ("../base-000000").
        name = posixpath.normpath(posixpath.join(head_name, r))
        if "/" not in name and name not in out and name != head_name:
            out.append(name)
    return out


# --------------------------------------------------------------- the stream


class DeltaStream:
    """A live continuous-checkpointing session. Construct via
    :meth:`tpusnap.Snapshot.stream`. Thread-safe; one capture in flight
    at a time. See the module docstring for semantics."""

    def __init__(
        self,
        root: str,
        app_state,
        cadence_s: Optional[float] = None,
        replicated: Optional[List[str]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
        max_chain: Optional[int] = None,
    ) -> None:
        comm = get_communicator(comm)
        self.root = root
        if cadence_s is not None:
            cadence_s = float(cadence_s)
            if cadence_s <= 0:
                raise ValueError(
                    f"cadence_s must be > 0, got {cadence_s!r} (the "
                    "TPUSNAP_DELTA_CADENCE_S default applies when omitted)"
                )
            # Same floor as the knob: a micro-commit is a real
            # two-phase-committed take.
            self.cadence_s = max(0.1, cadence_s)
        else:
            self.cadence_s = get_delta_cadence_s()
        self.max_chain = int(max_chain or get_delta_max_chain())
        self.stream_id = uuid.uuid4().hex[:16]
        self._app_state = app_state
        self._replicated = replicated
        self._storage_options = storage_options
        self._comm = comm
        self._multi = comm.world_size > 1
        self._rank = comm.rank  # GLOBAL process id
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        self._leaving = False  # graceful departure in progress (multi)
        self._paused = False  # torn epoch on rank failure (multi)
        self._pause_info: Optional[Dict[str, Any]] = None
        self._seq = 0
        self._head: Optional[str] = None  # member NAME
        self._chain: List[str] = []  # oldest first, head last
        self._step_gated = False  # a mark_step caller exists
        self._commit_due = False  # cadence elapsed, capture wanted
        self._capture_busy = False  # a capture/commit is in flight
        self._last_commit_mono: float = 0.0
        self._last_error: Optional[BaseException] = None
        # A staged-but-not-finalized capture handed off by mark_step:
        # the worker waits out its background commit drain so the
        # training thread never blocks past the staging window.
        self._pending_finalize: Optional[Dict[str, Any]] = None
        self._observability_stopped = False
        # Multi-process control plane (all no-ops when world_size == 1).
        self._kv = None
        self._inc = ""  # per-open incarnation token (epoch key scope)
        self._epoch = 1  # next epoch this rank expects to run
        self._members: List[int] = [self._rank]  # last epoch's world
        self._nudge_seen: Optional[bytes] = None
        self.stats: Dict[str, Any] = {
            "commits": 0,
            "bytes_written_total": 0,
            "last_commit_bytes": 0,
            "last_commit_wall_s": None,
            "max_commit_interval_s": None,
            "compactions": 0,
            "steps_marked": 0,
            "epochs": 0,
            "degraded_epochs": 0,
            "joins": 0,
            "leaves": 0,
        }

        if self._multi:
            from .snapshot import _get_kv_store

            self._kv = _get_kv_store(comm)
            reg = self._read_reg()
            if reg and reg.get("live"):
                # A live stream already runs on this root: JOIN it solo
                # (no collectives — the incumbents are mid-cadence, not
                # at our call site).
                self._open_join(reg)
            else:
                self._open_collective()
        else:
            self._open_solo()

        try:
            from . import slo as _slo

            _slo.tracker().note_stream(self.cadence_s)
        except Exception:
            logger.debug("slo note_stream failed", exc_info=True)

        self._worker = threading.Thread(
            target=self._run, name="tpusnap-delta", daemon=True
        )
        self._worker.start()

    # ----------------------------------------------------------- open paths

    def _plan_open(self) -> Dict[str, Any]:
        """Classify the root: FRESH (no committed chain — new stream id,
        base now; a torn base-000000 is retaken in place, salvaging its
        journal-proven blobs) or RESUME (committed chain present — adopt
        its identity and head; the first micro-commit retakes — and
        salvages — any torn tail). Committed members that are NOT chain
        members keep the historical refusal: a fresh base under foreign
        snapshots would silently change what the directory means."""
        existing = resolve_chain(self.root, self._storage_options)
        committed = [m for m in existing.members if m.state == "committed"]
        if not committed:
            return {
                "resume": False,
                "sid": self.stream_id,
                "seq": 0,
                "head": None,
                "chain": [],
                "torn": existing.torn_tail,
            }
        head_m = next(
            (m for m in existing.members if m.name == existing.head), None
        )
        if head_m is None or head_m.seq is None or not head_m.stream_id:
            raise ValueError(
                f"{self.root!r} already holds committed non-stream "
                f"snapshot(s) ({', '.join(m.name for m in committed[:4])}"
                f"{', ...' if len(committed) > 4 else ''}). A delta "
                "stream cannot adopt them: open the stream on a FRESH "
                "root (or gc the old members first)."
            )
        return {
            "resume": True,
            "sid": head_m.stream_id,
            "seq": int(head_m.seq),
            "head": existing.head,
            "chain": list(reversed(existing.chain)),
            "torn": existing.torn_tail,
        }

    def _apply_plan(self, plan: Dict[str, Any]) -> None:
        self.stream_id = plan["sid"]
        self._seq = int(plan["seq"])
        self._head = plan["head"]
        self._chain = list(plan["chain"])
        if plan["resume"]:
            # The caller restored the head before reopening (or is
            # about to diverge from it knowingly); the stream is armed
            # on the EXISTING recovery point — no new base.
            self._last_commit_mono = time.monotonic()
            telemetry.incr("delta.stream_resumes")
            flight.record(
                "delta",
                op="stream_resume",
                stream=self.stream_id,
                head=self._head,
                seq=self._seq,
                torn_tail=plan.get("torn"),
            )
            logger.info(
                "Resuming delta stream %s at %r: head %s (seq %d)%s",
                self.stream_id,
                self.root,
                self._head,
                self._seq,
                (
                    f"; torn tail {plan['torn']} will be salvaged on "
                    "the next micro-commit"
                    if plan.get("torn")
                    else ""
                ),
            )

    def _open_solo(self) -> None:
        plan = self._plan_open()
        self._apply_plan(plan)
        flight.record(
            "delta", op="stream_start", stream=self.stream_id,
            cadence_s=self.cadence_s,
        )
        if not plan["resume"]:
            # The base: a full, committed snapshot with per-tile dedup
            # hashes recorded, so the very first increment already
            # skips at tile grain. Synchronous — the stream is not
            # armed until a recovery point exists.
            self._commit(kind="base")

    def _open_collective(self) -> None:
        """Full-world open: rank 0 resolves the root (fresh vs resume)
        and broadcasts ONE plan — every rank must enter together,
        exactly like any SPMD cold start."""
        plan = None
        if self._rank == 0:
            plan = self._plan_open()
            plan["inc"] = uuid.uuid4().hex[:8]
        plan = self._comm.broadcast_object(plan, src=0)
        self._apply_plan(plan)
        self._inc = plan["inc"]
        self._members = list(range(self._comm.world_size))
        self._epoch = 1
        # Membership + root registration BEFORE the base take, so a
        # joiner arriving mid-base already sees a live stream.
        self._set_member_state("joined")
        if self._rank == 0:
            self._write_reg(live=True)
        flight.record(
            "delta", op="stream_start", stream=self.stream_id,
            cadence_s=self.cadence_s, world=len(self._members),
        )
        if not plan["resume"]:
            self._commit(kind="base")

    def _open_join(self, reg: Dict[str, Any]) -> None:
        """Join a LIVE stream on this root: adopt the advertised
        identity, record membership, and participate from the first
        epoch whose announcement lists this rank. No collectives, no
        base — the chain already has one."""
        self.stream_id = reg["sid"]
        self._inc = reg.get("inc", "")
        if reg.get("cadence_s"):
            self.cadence_s = float(reg["cadence_s"])
        self._seq = int(reg.get("seq", 0))
        self._head = reg.get("head")
        self._chain = [self._head] if self._head else []
        self._epoch = int(reg.get("epoch", 0)) + 1
        self._members = []
        self._last_commit_mono = time.monotonic()
        self._set_member_state("joined")
        self.stats["joins"] += 1
        telemetry.incr("delta.stream_joins")
        flight.record(
            "delta", op="stream_join", stream=self.stream_id,
            rank=self._rank, epoch=self._epoch,
        )
        logger.info(
            "rank %d joining live delta stream %s at %r (next epoch %d)",
            self._rank, self.stream_id, self.root, self._epoch,
        )

    # ------------------------------------------------------------- public

    @property
    def head(self) -> Optional[str]:
        """Path of the last committed member — the recovery point."""
        with self._lock:
            return self._member_path(self._head) if self._head else None

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def chain(self) -> List[str]:
        """Committed member names, oldest first."""
        with self._lock:
            return list(self._chain)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def mark_step(self, bytes_changed: Optional[int] = None) -> None:
        """Declare a training-step boundary (call once per optimizer
        step from the training thread). Arms step-gated capture: each
        due micro-commit's CAPTURE (state_dict + dual-hash staging)
        runs inline HERE, at a boundary, so it can never overlap an
        in-place mutation; the write + two-phase commit still drain in
        the background. ``bytes_changed`` (optional) feeds the SLO
        tracker's exact data-at-risk tier."""
        if bytes_changed:
            try:
                from . import slo as _slo

                _slo.record_step(bytes_changed)
            except Exception:
                pass
        capture = False
        with self._lock:
            self._step_gated = True
            self.stats["steps_marked"] += 1
            if self._commit_due and not self._capture_busy and not self._closed:
                self._commit_due = False
                self._capture_busy = True
                capture = True
        if capture:
            # Capture ONLY on the training thread: async_take returns
            # at staging-complete (incremental takes stage strictly),
            # so the state is frozen — and safe to mutate again — the
            # moment _begin_capture returns. The storage writes and the
            # two-phase commit drain on the take's background thread;
            # the WORKER waits them out and finalizes, so mark_step
            # never blocks on storage or compaction.
            try:
                ctx = self._begin_capture("delta")
            except Exception as e:
                # A failed capture must not take the TRAINING loop down
                # — stop the stream; the last committed increment stays
                # the recovery point and raise_if_failed() surfaces it.
                self._fail(e, where="micro-commit capture in mark_step")
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()
                return
            inline = False
            with self._cv:
                if self._closed:
                    # Teardown race: the worker may already be gone —
                    # finalize here rather than strand the capture.
                    inline = True
                else:
                    self._pending_finalize = ctx
                    self._cv.notify_all()
            if inline:
                try:
                    self._finalize_capture(ctx)
                except Exception:
                    logger.warning(
                        "DeltaStream finalize during close failed "
                        "(the previous head remains the recovery point)",
                        exc_info=True,
                    )
                finally:
                    with self._cv:
                        self._capture_busy = False
                        self._cv.notify_all()

    def commit_now(self):
        """Force a micro-commit and return the committed
        :class:`~tpusnap.Snapshot`. Raises if the stream is closed.
        Single-process: runs synchronously on the calling thread.
        Multi-process: nudges the driver to announce the next epoch
        immediately and blocks until this rank's worker has committed
        it — commits are collective, so they always run on the epoch
        protocol, never inline on one rank."""
        if self._multi:
            return self._commit_now_multi()
        with self._cv:
            if self._closed:
                raise RuntimeError("DeltaStream is closed")
            while self._capture_busy:
                self._cv.wait()
                if self._closed:
                    raise RuntimeError("DeltaStream is closed")
            self._capture_busy = True
            self._commit_due = False
        try:
            return self._commit(kind="delta")
        finally:
            with self._cv:
                self._capture_busy = False
                self._cv.notify_all()

    def _commit_now_multi(self):
        from .snapshot import Snapshot

        with self._cv:
            if self._closed:
                raise RuntimeError("DeltaStream is closed")
            target = self.stats["commits"] + 1
        try:
            self._kv.set(
                f"{self._kv_prefix()}/nudge", uuid.uuid4().hex.encode()
            )
        except Exception:
            logger.warning("commit_now nudge failed", exc_info=True)
        deadline = time.monotonic() + max(60.0, 4.0 * self.cadence_s)
        with self._cv:
            while self.stats["commits"] < target:
                if self._closed:
                    if self._paused:
                        raise RuntimeError(
                            f"DeltaStream is paused: {self._pause_info}"
                        )
                    err = self._last_error
                    if err is not None:
                        raise RuntimeError(
                            "DeltaStream worker failed during commit_now"
                        ) from err
                    raise RuntimeError("DeltaStream is closed")
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        "commit_now timed out waiting for the stream epoch"
                    )
                self._cv.wait(timeout=0.25)
            head = self._member_path(self._head)
        return Snapshot(head, self._storage_options)

    def close(self, final_commit: bool = True) -> Optional[str]:
        """Stop the stream. With ``final_commit`` (the default) a last
        micro-commit captures the state as of close, so nothing since
        the previous cadence tick is lost. Returns the head path.
        Idempotent.

        Multi-process close is a graceful :meth:`leave` — elastic
        membership can't promise every member is at a close() call
        site, so there is no implicit final collective commit; call
        :meth:`commit_now` first for an at-close recovery point. The
        last member out turns the root registration off so a later
        full-world open resumes from storage."""
        if self._multi:
            with self._lock:
                already = self._closed
            if (
                not already
                and final_commit
                and self._last_error is None
                and not self._paused
            ):
                logger.info(
                    "multi-process DeltaStream close takes no implicit "
                    "final commit; call commit_now() first for an "
                    "at-close recovery point"
                )
            head = self.leave()
            try:
                states = self._read_members()
                if not any(s == "joined" for s in states.values()):
                    self._write_reg(live=False)
            except Exception:
                pass
            return head
        with self._cv:
            already = self._closed
            if not already:
                self._closed = True
                self._cv.notify_all()
        if already:
            self._stop_observability()
            return self._member_path(self._head) if self._head else None
        from .io_types import close_may_join

        if close_may_join():
            # Joining is safe only on the explicit-close path: a
            # GC-finalizer close (the lockwatch-caught deadlock class)
            # skips the join — the daemon worker observes _closed and
            # exits on its own.
            # tpusnap: waive=TPS006 join is gated on close_may_join() above
            self._worker.join(timeout=60.0)
        # Drain a capture the worker may have exited without finalizing
        # (mark_step hand-off racing the shutdown).
        with self._cv:
            ctx = self._pending_finalize
            self._pending_finalize = None
        if ctx is not None:
            try:
                self._finalize_capture(ctx)
            except Exception:
                logger.warning(
                    "DeltaStream finalize during close failed (the "
                    "previous head remains the recovery point)",
                    exc_info=True,
                )
            finally:
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()
        if final_commit and self._last_error is None:
            with self._cv:
                while self._capture_busy:
                    self._cv.wait()
                self._capture_busy = True
            try:
                self._commit(kind="delta")
            except Exception:
                logger.warning(
                    "DeltaStream final commit failed (the previous head "
                    "remains the recovery point)",
                    exc_info=True,
                )
            finally:
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()
        self._stop_observability()
        return self._member_path(self._head) if self._head else None

    def __enter__(self) -> "DeltaStream":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # On an exception unwind, skip the final commit: the state may
        # be mid-step garbage; the last committed increment is the
        # honest recovery point.
        self.close(final_commit=exc_type is None)

    def leave(self) -> Optional[str]:
        """Gracefully leave a multi-process stream: finish any epoch
        this rank is already announced into, publish a terminal
        ``left`` membership state (watchers render LEFT, never DEAD —
        no ``RankFailedError``, no degraded epoch), and stop this
        rank's worker. The remaining members re-plan the next capture
        boundary without this rank; it can rejoin later by reopening
        ``Snapshot.stream`` on the same root. On a single-process
        stream this is ``close(final_commit=False)``. Returns the last
        head path this rank observed. Idempotent."""
        if not self._multi:
            return self.close(final_commit=False)
        with self._cv:
            if self._closed:
                return self._member_path(self._head) if self._head else None
            if self._leaving:
                already_leaving = True
            else:
                already_leaving = False
                self._leaving = True
                self._cv.notify_all()
        if not already_leaving:
            # Publish the departure FIRST: the driver re-reads
            # membership immediately before announcing, so no NEW epoch
            # lists this rank after this write. An epoch ALREADY
            # announced with us in its world is honored by the worker
            # before it exits (the _leaving checks in the epoch loop).
            self._set_member_state("left")
            self.stats["leaves"] += 1
            telemetry.incr("delta.stream_leaves")
            flight.record("rank_left", rank=self._rank)
            flight.record(
                "delta", op="stream_leave", stream=self.stream_id,
                rank=self._rank, epoch=self._epoch,
            )
        from .io_types import close_may_join

        if close_may_join():
            # Same join gate as close(): a GC-finalizer leave must not
            # block; the daemon worker observes _leaving and exits.
            # tpusnap: waive=TPS006 join is gated on close_may_join() above
            self._worker.join(timeout=120.0)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._stop_observability()
        logger.info(
            "rank %d left delta stream %s", self._rank, self.stream_id
        )
        return self._member_path(self._head) if self._head else None

    @property
    def paused(self) -> bool:
        """True when a torn epoch paused the stream (rank failure the
        survivors could not degrade). A paused stream is a NAMED,
        policy-handled event, not a worker failure —
        :meth:`raise_if_failed` stays silent; ``pause_info`` carries
        the forensics. Reopen ``Snapshot.stream`` on the root to
        resume (the torn member salvages on the retake)."""
        with self._lock:
            return self._paused

    @property
    def pause_info(self) -> Optional[Dict[str, Any]]:
        """``{"epoch", "member", "dead_ranks", "detail"}`` of the torn
        epoch that paused the stream, or None."""
        with self._lock:
            return dict(self._pause_info) if self._pause_info else None

    @property
    def members(self) -> List[int]:
        """GLOBAL ranks of the last completed epoch's world (this
        process alone for single-process streams)."""
        with self._lock:
            return list(self._members)

    def raise_if_failed(self) -> None:
        """Re-raise the worker's terminal failure, if any (a failed
        micro-commit stops the stream rather than silently shipping
        stale recovery points forever). A PAUSED stream does not raise
        — check :attr:`paused`."""
        with self._lock:
            err = self._last_error
        if err is not None:
            raise RuntimeError(
                "DeltaStream worker failed; the stream is stopped and the "
                f"last committed increment is the recovery point: {err!r}"
            ) from err

    # ------------------------------------------------------------ internals

    def _member_path(self, name: str) -> str:
        return f"{self.root.rstrip('/')}/{name}"

    # --------------------------------------------- multi-process control KV

    def _kv_prefix(self) -> str:
        return f"{_STREAM_KV_ROOT}/{self.stream_id}"

    def _member_key(self, rank: int) -> str:
        return f"{self._kv_prefix()}/members/{rank}"

    def _ann_key(self, epoch: int) -> str:
        return f"{self._kv_prefix()}/ann/{self._inc}/{epoch}"

    def _reg_key(self) -> str:
        import hashlib

        digest = hashlib.sha1(
            self.root.rstrip("/").encode("utf-8")
        ).hexdigest()[:16]
        return f"{_STREAM_KV_ROOT}_root/{digest}"

    def _read_reg(self) -> Optional[Dict[str, Any]]:
        try:
            raw = self._kv.try_get(self._reg_key())
            return None if raw is None else json.loads(raw.decode("utf-8"))
        except Exception:
            return None

    def _write_reg(self, live: bool) -> None:
        """Root registration: what a later ``Snapshot.stream`` on the
        same root reads to decide join-live vs collective open. Updated
        by the driver after every epoch (so a joiner adopts a current
        head), turned off at pause and by the last member out."""
        try:
            self._kv.set(
                self._reg_key(),
                json.dumps(
                    {
                        "sid": self.stream_id,
                        "inc": self._inc,
                        "live": bool(live),
                        "cadence_s": self.cadence_s,
                        "epoch": self._epoch - 1,
                        "seq": self._seq,
                        "head": self._head,
                    }
                ).encode("utf-8"),
            )
        except Exception:
            logger.debug("stream reg write failed", exc_info=True)

    def _set_member_state(self, state: str, rank: Optional[int] = None) -> None:
        try:
            self._kv.set(
                self._member_key(self._rank if rank is None else rank),
                json.dumps({"state": state, "epoch": self._epoch}).encode(
                    "utf-8"
                ),
            )
        except Exception:
            logger.warning(
                "stream membership write (%s) failed", state, exc_info=True
            )

    def _read_members(self) -> Dict[int, str]:
        """GLOBAL rank -> membership state (joined/left/expired)."""
        out: Dict[int, str] = {}
        blobs = None
        try:
            blobs = self._kv.try_get_dir(f"{self._kv_prefix()}/members/")
        except Exception:
            blobs = None
        if blobs is None:
            # Per-rank probe fallback, bounded: the jax world is the
            # superset of every possible member.
            blobs = {}
            for r in range(self._comm.world_size):
                raw = self._kv.try_get(self._member_key(r))
                if raw is not None:
                    blobs[str(r)] = raw
        for key, raw in blobs.items():
            try:
                r = int(key.rsplit("/", 1)[-1])
                out[r] = json.loads(raw.decode("utf-8")).get(
                    "state", "joined"
                )
            except Exception:
                continue
        return out

    def _joined_members(self) -> List[int]:
        membership = self._read_members()
        members = sorted(
            r for r, s in membership.items() if s == "joined"
        )
        if self._rank not in members:
            members = sorted(set(members) | {self._rank})
        return members

    def _nudged(self) -> bool:
        """A commit_now caller (any member) wants the next epoch NOW."""
        try:
            raw = self._kv.try_get(f"{self._kv_prefix()}/nudge")
        except Exception:
            return False
        if raw is not None and raw != self._nudge_seen:
            self._nudge_seen = raw
            return True
        return False

    def _takeover_grace(self) -> float:
        # How long a follower waits past the cadence before presuming
        # the driver dead: several lease TTLs (death detection would
        # have fired inside any in-flight epoch long before), staggered
        # by rank so takeovers don't herd.
        from .knobs import get_liveness_ttl_s

        ttl = get_liveness_ttl_s()
        return max(4.0 * ttl, 10.0) + 0.5 * self._rank

    # ------------------------------------------------- multi-process epochs

    def _run_multi(self) -> None:
        """Elastic epoch loop. The driver — the minimum currently-joined
        global rank — announces each capture epoch over the
        coordination KV; every member polls for the announcement and
        joins the epoch's collective micro-commit over a per-epoch
        :class:`~tpusnap.comm.SubsetComm`. Membership is re-read at
        every announcement, so leaves (graceful or expired) and joins
        re-plan the world at the next capture boundary."""
        while True:
            with self._cv:
                if self._closed:
                    return
            members = self._joined_members()
            try:
                if min(members) == self._rank:
                    alive = self._drive_one_epoch()
                else:
                    alive = self._follow_one_epoch(min(members))
            except Exception as e:  # defensive: never wedge the worker
                self._fail(e, where="elastic epoch loop")
                return
            if not alive:
                return

    def _drive_one_epoch(self) -> bool:
        # Cadence wait, interruptible by close/leave and commit_now
        # nudges (the nudge key is polled, not pushed — the KV has no
        # watch primitive).
        deadline = self._last_commit_mono + self.cadence_s
        while True:
            with self._cv:
                if self._closed:
                    return False
                if self._leaving:
                    return False
            if self._nudged():
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            with self._cv:
                self._cv.wait(timeout=min(remaining, 0.25))
        membership = self._read_members()
        members = sorted(
            r for r, s in membership.items() if s == "joined"
        )
        if self._rank not in members:
            members = sorted(set(members) | {self._rank})
        if min(members) != self._rank:
            # A lower rank (re)joined; it drives from here.
            return True
        prev = set(self._members)
        world: Dict[str, Any] = {"size": len(members), "ranks": members}
        joins = sorted(set(members) - prev)
        leaves = sorted(prev - set(members))
        if joins:
            world["joined"] = joins
        if leaves:
            world["left"] = leaves
            expired = [r for r in leaves if membership.get(r) == "expired"]
            if expired:
                world["expired"] = expired
        ann = {
            "epoch": self._epoch,
            "seq": self._seq + 1,
            "parent": self._head,
            "members": members,
            "world": world,
        }
        self._kv.set(
            self._ann_key(self._epoch),
            json.dumps(ann).encode("utf-8"),
        )
        return self._run_epoch(ann)

    def _follow_one_epoch(self, driver: int) -> bool:
        ann_key = self._ann_key(self._epoch)
        takeover_at = (
            time.monotonic() + self.cadence_s + self._takeover_grace()
        )
        leave_by: Optional[float] = None
        while True:
            with self._cv:
                if self._closed:
                    return False
                leaving = self._leaving
            if leaving and leave_by is None:
                # A leaver must LINGER ~one cadence: the driver may have
                # read membership just before our `left` write landed
                # and announce an epoch that still names us — exiting
                # now would strand it mid-gather. Any membership read
                # after the write excludes us, so at most one such
                # racing announcement exists; serve it if it arrives,
                # then go.
                leave_by = time.monotonic() + self.cadence_s + 2.0
            raw = None
            try:
                raw = self._kv.try_get(ann_key)
            except Exception:
                pass
            if raw is not None:
                break
            if leave_by is not None and time.monotonic() > leave_by:
                # No racing announcement can still list us — done.
                return False
            if not leaving and time.monotonic() > takeover_at:
                # The driver went a full cadence plus several lease
                # TTLs without announcing: presume it dead BETWEEN
                # epochs (an in-flight epoch's liveness would have
                # caught it), expire it and let the next-lowest joined
                # rank (possibly this one) drive.
                self._set_member_state("expired", rank=driver)
                flight.record(
                    "delta", op="driver_takeover", stream=self.stream_id,
                    expired=driver, by=self._rank, epoch=self._epoch,
                )
                logger.warning(
                    "delta stream %s: driver rank %d silent past "
                    "takeover grace; expiring it from the stream",
                    self.stream_id, driver,
                )
                return True
            with self._cv:
                self._cv.wait(timeout=_ANN_POLL_S)
        try:
            ann = json.loads(raw.decode("utf-8"))
        except Exception:
            logger.warning("unparseable epoch announcement; skipping")
            self._epoch += 1
            return True
        if self._rank not in ann.get("members", []):
            # Announced before our join record landed: skip — the next
            # epoch's membership read includes us. seq/head are adopted
            # from the first announcement we DO participate in. A
            # LEAVER seeing itself re-planned out is done for good.
            self._epoch = int(ann["epoch"]) + 1
            return not leaving
        return self._run_epoch(ann)

    def _run_epoch(self, ann: Dict[str, Any]) -> bool:
        """One collective micro-commit over the announced member set.
        Returns False when the stream must stop (close/pause/failure)."""
        from .comm import SubsetComm
        from .dist_store import TakeAbortedError
        from .liveness import RankFailedError

        members = [int(r) for r in ann["members"]]
        epoch = int(ann["epoch"])
        seq = int(ann["seq"])
        with self._cv:
            if self._closed:
                return False
            self._capture_busy = True
        snap = None
        try:
            subset = SubsetComm(
                members,
                namespace=(
                    f"tpusnap/st/{self.stream_id}-{self._inc}-e{epoch}"
                ),
            )
            ctx = self._begin_capture(
                "delta",
                seq=seq,
                parent=ann.get("parent"),
                comm=subset,
                world=ann.get("world")
                or {"size": len(members), "ranks": members},
            )
            snap = self._finalize_capture(ctx)
        except RankFailedError as e:
            self._pause_on_rank_failure(e, ann)
            return False
        except TakeAbortedError as e:
            if "RankFailedError" in str(e):
                # A peer detected the death first and published the
                # abort; same torn-epoch outcome on this rank.
                self._pause_on_rank_failure(e, ann)
            else:
                self._fail(e, where=f"elastic micro-commit (epoch {epoch})")
            return False
        except BaseException as e:
            self._fail(e, where=f"elastic micro-commit (epoch {epoch})")
            return False
        finally:
            with self._cv:
                self._capture_busy = False
                self._cv.notify_all()
        # Commit landed (possibly degraded — metadata says which).
        self._members = members
        self._epoch = epoch + 1
        self.stats["epochs"] += 1
        deg = (snap.metadata.extras or {}).get("degraded")
        if deg:
            dead_global = sorted(
                members[v]
                for v in deg.get("dead_ranks", [])
                if 0 <= v < len(members)
            )
            self.stats["degraded_epochs"] += 1
            telemetry.incr("delta.degraded_epochs")
            for r in dead_global:
                self._set_member_state("expired", rank=r)
            flight.record(
                "delta", op="degraded_epoch", stream=self.stream_id,
                epoch=epoch, seq=seq, dead_ranks=dead_global,
            )
            logger.warning(
                "delta stream %s epoch %d committed DEGRADED without "
                "global rank(s) %s; they are expired from the stream "
                "and the next capture re-plans around them",
                self.stream_id, epoch, dead_global,
            )
        if min(members) == self._rank:
            self._write_reg(live=True)
        return True

    def _pause_on_rank_failure(self, exc: BaseException, ann: Dict[str, Any]) -> None:
        """A rank died mid-epoch and the survivors could not degrade
        (sharded state cannot be adopted): the torn epoch keeps its
        salvage substrate and the stream PAUSES — a named,
        policy-handled event, not a worker failure. The committed chain
        stays the recovery point; reopening ``Snapshot.stream`` on the
        root resumes it and the retake salvages the torn member."""
        members = [int(r) for r in ann["members"]]
        ranks = getattr(exc, "ranks", None) or []
        dead_global = sorted(
            {members[v] for v in ranks if 0 <= v < len(members)}
        )
        member = member_name(int(ann["seq"]))
        for r in dead_global:
            self._set_member_state("expired", rank=r)
        with self._cv:
            self._paused = True
            self._pause_info = {
                "epoch": int(ann["epoch"]),
                "member": member,
                "dead_ranks": dead_global or None,
                "detail": str(exc),
            }
            self._closed = True
            self._cv.notify_all()
        telemetry.incr("delta.stream_pauses")
        flight.record(
            "delta", op="stream_pause", stream=self.stream_id,
            epoch=int(ann["epoch"]), member=member,
            dead_ranks=dead_global or None,
        )
        self._write_reg(live=False)
        logger.error(
            "delta stream %s PAUSED: epoch %d (member %s) tore on rank "
            "failure%s and could not commit degraded. The committed "
            "chain is intact; reopen Snapshot.stream on %r after "
            "recovery — the torn member salvages on the retake.",
            self.stream_id,
            int(ann["epoch"]),
            member,
            f" of global rank(s) {dead_global}" if dead_global else "",
            self.root,
        )
        self._stop_observability()

    def _fail(self, exc: BaseException, where: str) -> None:
        """Stop the stream on a terminal failure (the last committed
        increment remains the recovery point); raise_if_failed()
        surfaces the cause to the caller."""
        logger.error(
            "DeltaStream %s failed; stopping the stream (the last "
            "committed increment remains the recovery point)",
            where,
            exc_info=True,
        )
        with self._cv:
            self._last_error = exc
            self._closed = True
            self._cv.notify_all()
        self._stop_observability()

    def _stop_observability(self) -> None:
        """Idempotent teardown of the stream's observability footprint:
        the SLO tracker's cadence gauge must never advertise a live
        stream after the stream stopped — for ANY reason, including a
        failed micro-commit mid-incident (exactly when a dashboard
        claiming 'delta stream active' would mislead)."""
        with self._lock:
            if self._observability_stopped:
                return
            self._observability_stopped = True
        try:
            from . import slo as _slo

            _slo.tracker().note_stream(None)
        except Exception:
            logger.debug("slo note_stream failed", exc_info=True)
        flight.record(
            "delta", op="stream_close", stream=self.stream_id,
            commits=self.stats["commits"],
        )

    def _run(self) -> None:
        """Worker loop: finalize captures handed off by mark_step (wait
        out their background commit drains), wake at cadence, capture
        here (free-running) or defer to the next mark_step (step-gated,
        with a one-cadence grace so a stalled training loop cannot
        suspend checkpointing forever). Multi-process streams run the
        elastic epoch loop instead — captures are announcement-driven
        and always run here on the worker (the collective rendezvous
        inside the take is the cross-rank step synchronizer; mark_step
        still feeds stats and the SLO tracker)."""
        if self._multi:
            self._run_multi()
            return
        while True:
            with self._cv:
                ctx = self._pending_finalize
                self._pending_finalize = None
            if ctx is not None:
                # A mark_step capture: wait out its background commit
                # drain + bookkeeping/compaction here, off the training
                # thread.
                try:
                    self._finalize_capture(ctx)
                except Exception as e:
                    self._fail(e, where="micro-commit")
                    return
                finally:
                    with self._cv:
                        self._capture_busy = False
                        self._cv.notify_all()
                continue
            with self._cv:
                deadline = self._last_commit_mono + self.cadence_s
                while not self._closed and self._pending_finalize is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=min(remaining, 0.5))
                if self._pending_finalize is not None:
                    continue
                if self._closed:
                    return
                if self._capture_busy:
                    # A commit_now (or an in-flight mark_step capture)
                    # owns the slot; check back shortly rather than
                    # stacking a second commit on top.
                    self._cv.wait(timeout=0.05)
                    continue
                if self._step_gated:
                    # Hand the capture to the training thread: the next
                    # mark_step performs it at a step boundary.
                    self._commit_due = True
                    grace = time.monotonic() + self.cadence_s
                    while (
                        not self._closed
                        and self._commit_due
                        and time.monotonic() < grace
                    ):
                        self._cv.wait(timeout=0.05)
                    if self._closed:
                        return
                    if not self._commit_due:
                        # mark_step took it (or a commit_now raced in);
                        # loop to the top — the hand-off pickup and the
                        # next interval live there.
                        continue
                    # Grace expired: training loop stalled mid-step (or
                    # stopped calling mark_step) — a bounded RPO beats
                    # step consistency; fall through to a free-running
                    # capture.
                    self._commit_due = False
                self._capture_busy = True
            try:
                self._commit(kind="delta")
            except Exception as e:
                self._fail(e, where="micro-commit")
                return
            finally:
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()

    def _commit(self, kind: str):
        """One full micro-commit on THIS thread (capture + commit drain
        + bookkeeping). commit_now/close/base use it; mark_step splits
        it into _begin_capture (training thread) + _finalize_capture
        (worker)."""
        return self._finalize_capture(self._begin_capture(kind))

    def _begin_capture(
        self,
        kind: str,
        *,
        seq: Optional[int] = None,
        parent: Optional[str] = None,
        comm: Optional[Communicator] = None,
        world: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """The capture half: state_dict + strict dual-hash staging.
        When this returns, the content is FROZEN (incremental takes
        stage everything before async_take returns) and the caller may
        mutate state again; the storage writes + two-phase commit drain
        on the take's own background thread. Caller holds the
        _capture_busy slot (or is __init__).

        Elastic epochs pass ``seq``/``parent`` from the announcement
        (authoritative — a joiner's local view may lag), ``comm`` the
        per-epoch :class:`~tpusnap.comm.SubsetComm`, and ``world`` the
        participating world recorded into ``extras["delta"]`` (and
        thus the take journal, so even a torn epoch names it)."""
        from .snapshot import Snapshot

        t0 = time.monotonic()
        if seq is None:
            with self._lock:
                seq = self._seq if kind == "base" else self._seq + 1
                parent = self._head
        take_comm = comm if comm is not None else self._comm
        if world is None and self._multi:
            world = {
                "size": take_comm.world_size,
                "ranks": sorted(self._members),
            }
        name = member_name(seq)
        path = self._member_path(name)
        delta_extras: Dict[str, Any] = {
            "stream": self.stream_id,
            "seq": seq,
            "parent": parent,
        }
        if world:
            delta_extras["world"] = world
        extras = {"delta": delta_extras}
        ctx: Dict[str, Any] = {"kind": kind, "t0": t0, "seq": seq,
                               "name": name}
        if kind == "base":
            # Full base, tile-grain dedup hashes recorded everywhere.
            ctx["snap"] = Snapshot.take(
                path,
                self._app_state,
                replicated=self._replicated,
                storage_options=self._storage_options,
                comm=take_comm,
                _extras=extras,
                _record_dedup_hashes=True,
            )
        else:
            # Micro-commits force DEFENSIVE-CLONE staging (not the
            # process-wide TPUSNAP_ASYNC_COW default): the stream's
            # whole point is that training keeps mutating while the
            # drain runs, with no wait_staged() rendezvous — under COW
            # every free-running capture would fail on the write-time
            # mutation check. Per-take parameter, not an env override:
            # a global flip would race concurrent takes on other
            # threads into silently paying the full clone pass.
            ctx["pending"] = Snapshot.async_take(
                path,
                self._app_state,
                replicated=self._replicated,
                storage_options=self._storage_options,
                comm=take_comm,
                incremental_from=self._member_path(parent),
                _extras=extras,
                _record_dedup_hashes=True,
                _force_clone_staging=True,
                # Arms the degraded-commit context for this incremental
                # async take (see the _take_impl gate): the force-clone
                # staging above is exactly what makes adoption safe.
                _stream_capture=True,
            )
        return ctx

    def _finalize_capture(self, ctx: Dict[str, Any]):
        """The commit half: wait out the background drain (ONE commit in
        flight at a time — the capture slot is held until this returns),
        then head/chain bookkeeping and compaction."""
        kind, t0, seq = ctx["kind"], ctx["t0"], ctx["seq"]
        name = ctx["name"]
        snap = ctx.get("snap")
        if snap is None:
            snap = ctx["pending"].wait()
        wall = time.monotonic() - t0
        written = 0
        try:
            written = delta_payload_bytes(snap.metadata)
        except Exception:
            logger.debug("delta payload accounting failed", exc_info=True)
        telemetry.incr("delta.commits")
        if written:
            telemetry.incr("delta.bytes_written", written)
        with self._lock:
            interval = (
                time.monotonic() - self._last_commit_mono
                if self._last_commit_mono
                else None
            )
            self._last_commit_mono = time.monotonic()
            self._seq = seq
            self._head = name
            self._chain.append(name)
            st = self.stats
            st["commits"] += 1
            st["bytes_written_total"] += written
            st["last_commit_bytes"] = written
            st["last_commit_wall_s"] = round(wall, 4)
            if interval is not None:
                st["max_commit_interval_s"] = max(
                    st["max_commit_interval_s"] or 0.0, round(interval, 4)
                )
            chain_len = len(self._chain)
            # commit_now waiters (multi) watch stats["commits"].
            self._cv.notify_all()
        flight.record(
            "delta",
            op="micro_commit" if kind != "base" else "base_commit",
            stream=self.stream_id,
            seq=seq,
            bytes=written,
            wall_s=round(wall, 4),
        )
        if chain_len > self.max_chain:
            if self._multi:
                # Compaction (materialize + retire) is a single-writer
                # job; with every member holding a handle it would
                # race. Leave long multi-process chains to `tpusnap gc`
                # or an explicit maintenance materialize.
                logger.debug(
                    "multi-process stream chain depth %d exceeds "
                    "max_chain=%d; compaction is single-process only",
                    chain_len, self.max_chain,
                )
            else:
                self._compact(snap)
        return snap

    def _compact(self, head_snap) -> None:
        """Chain compaction via the existing materialize path: the head
        becomes self-contained (referenced base blobs copied in,
        checksum-verified, metadata rewritten atomically — a crash
        mid-copy leaves the old metadata and the chain intact), then
        the superseded members are retired. Local-fs roots delete them;
        other backends leave them for `gc`/bucket lifecycle rules."""
        t0 = time.monotonic()
        stats = head_snap.materialize()
        with self._lock:
            head = self._head
            superseded = [m for m in self._chain if m != head]
            self._chain = [head]
        telemetry.incr("delta.compactions")
        flight.record(
            "delta",
            op="compact",
            stream=self.stream_id,
            head=head,
            bytes_copied=stats.get("bytes_copied", 0),
            retired=len(superseded),
            wall_s=round(time.monotonic() - t0, 4),
        )
        self.stats["compactions"] += 1
        parts = urlsplit(self.root)
        if parts.scheme not in ("", "file"):
            logger.info(
                "Delta chain compacted at %r; %d superseded member(s) left "
                "for bucket lifecycle rules / `tpusnap gc`",
                self.root,
                len(superseded),
            )
            return
        import os
        import shutil

        root = os.path.abspath(parts.path or self.root)
        for name in superseded:
            target = os.path.join(root, name)
            # Metadata first: a retire interrupted mid-delete leaves a
            # directory that can never be mistaken for a committed
            # snapshot (resolve_chain reports it as debris; the
            # crash-matrix covers this window).
            try:
                meta = os.path.join(target, ".snapshot_metadata")
                if os.path.exists(meta):
                    os.unlink(meta)
                shutil.rmtree(target, ignore_errors=True)
            except OSError:
                logger.warning(
                    "Failed to retire superseded member %r (reclaim via "
                    "`tpusnap gc` later)",
                    target,
                    exc_info=True,
                )
