"""Continuous delta checkpointing: streaming micro-commits for
seconds-scale RPO with crash-replay restore (ROADMAP 4).

A classic take is a periodic stop-the-world event: a crash loses
everything since the last one — minutes of work at fleet cadences, and
the PR 10 SLO tracker can only *measure* that exposure. This module
composes primitives the system already owns — incremental dedup's
dual-hash (CRC32C+XXH64) change detection, strict-staging incremental
``async_take``, the crash-safe journal, salvage-resume and fsck's
torn-tail classification — into a **streaming delta mode** with a
tunable recovery-point objective:

- :meth:`tpusnap.Snapshot.stream` opens a :class:`DeltaStream` under a
  root directory: one full **base** snapshot now (with per-tile dedup
  hashes recorded, so every blob has tile grain from the first
  increment), then one **micro-commit** per cadence interval — a real,
  journaled, metadata-written-last incremental snapshot referencing the
  previous committed member, shipping only tiles/blobs whose fresh
  dual-hash pair changed. An unchanged model streams ~zero payload
  bytes; one mutated row of a multi-GB array streams ~one checksum
  tile.
- Because incremental writers **collapse chained references** (each
  member's external locations point at the member that physically holds
  the bytes — never through an intermediate), the chain never deepens
  lookups: ``Snapshot(head).restore`` / ``read_object`` work
  transparently on any member, reading base + changed blobs flat.
- Every micro-commit runs the unchanged crash machinery: a SIGKILL
  mid-commit leaves a **torn tail** the journal classifies (fsck names
  it "torn delta micro-commit seq N over member X"), gc'd or salvaged
  like any torn take — and recovery lands on the last committed
  increment via :func:`resolve_chain`. Each commit also anchors the SLO
  tracker, turning ``tpusnap_rpo_seconds`` from take-interval minutes
  into stream-cadence seconds.
- Chains stay bounded: past ``TPUSNAP_DELTA_MAX_CHAIN`` members the
  stream **compacts** — ``materialize`` copies the head's referenced
  blobs in (checksum-verified, committed atomically), making it the new
  self-contained base, and the superseded members are retired.

Step-consistency contract (the ``staged()``/mutate-after-return
contract, streamed):

- **Functional JAX updates** (the normal case) never need coordination:
  the capture stages from the array objects it was handed; new arrays
  produced by a later step are different objects.
- **In-place mutators** (raw numpy buffers, donated pinned_host) call
  :meth:`DeltaStream.mark_step` once per training step. The stream then
  defers each due capture to the next ``mark_step`` call and performs
  it inline there — on the training thread, at a step boundary — so no
  capture ever overlaps a mutation. The capture cost is the strict
  incremental staging window (the dual-hash pass; writes and the
  two-phase commit drain on the background thread). Free-running
  captures (no ``mark_step`` caller) run entirely on the stream's
  worker thread and guarantee blob-grain consistency only.
- :meth:`DeltaStream.commit_now` forces a synchronous micro-commit and
  returns the committed :class:`~tpusnap.Snapshot`;
  :meth:`DeltaStream.close` stops the stream (with a final commit by
  default).

Multi-process streams are not yet supported (cadence agreement and
background state_dict capture across ranks need their own coordination
protocol); ``world_size > 1`` raises. Single-process covers the
serving/fine-tune fleets this mode targets first; multi-host training
keeps explicit ``take``/``async_take``.
"""

from __future__ import annotations

import logging
import posixpath
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional
from urllib.parse import urlsplit

from . import flight, telemetry
from .comm import Communicator, get_communicator
from .knobs import get_delta_cadence_s, get_delta_max_chain

logger = logging.getLogger(__name__)

__all__ = [
    "DeltaStream",
    "DeltaChainReport",
    "ChainMember",
    "resolve_chain",
    "delta_payload_bytes",
]


def member_name(seq: int) -> str:
    """Canonical member directory name: ``base-000000`` for the stream's
    first full snapshot, ``delta-%06d`` for micro-commits. Chain
    structure is read from metadata (``extras["delta"]``), never parsed
    from names — a compacted head keeps its ``delta-*`` name while
    being fully self-contained."""
    return f"base-{seq:06d}" if seq == 0 else f"delta-{seq:06d}"


def delta_fields(metadata) -> Optional[Dict[str, Any]]:
    """The validated delta-chain fields of a committed snapshot's
    metadata — delegates to :func:`tpusnap.manifest_ops.
    delta_chain_fields`, the one place chain membership is decoded."""
    from .manifest_ops import delta_chain_fields

    return delta_chain_fields(metadata)


def delta_payload_bytes(metadata) -> int:
    """Bytes PHYSICALLY stored in this member's own directory — i.e.
    excluding external (``../``) references into earlier chain members.
    The numerator of delta write amplification: for an unchanged model
    this is ~zero; for one changed row of a tiled array it is ~one
    checksum tile."""
    from .inspect import iter_blobs

    total = 0
    for blob in iter_blobs(metadata.manifest):
        if blob.location.startswith("../"):
            continue
        if blob.byte_range is not None:
            total += blob.byte_range[1] - blob.byte_range[0]
    return total


# -------------------------------------------------------- chain resolution


@dataclass
class ChainMember:
    """One directory under a stream root, classified."""

    name: str
    state: str  # "committed" | "torn" | "debris"
    seq: Optional[int] = None
    parent: Optional[str] = None
    stream_id: Optional[str] = None
    created_at: Optional[float] = None
    payload_bytes: int = 0


@dataclass
class DeltaChainReport:
    """What :func:`resolve_chain` finds under a stream root.

    ``head`` is the RECOVERY POINT: the committed member with the
    highest sequence number — ``Snapshot(<root>/<head>).restore``
    replays base + committed deltas transparently. ``torn_tail`` names
    a member whose micro-commit was interrupted (journal present, no
    metadata): recovery IGNORES it (gc or the next stream's
    salvage-resume reclaims it). ``chain`` is the set of members the
    head's blob references actually span (head first) — what retention
    must keep alive for the head to stay restorable. ``superseded`` are
    committed members outside every live chain (compaction leftovers) —
    reclaimable. ``debris`` are half-deleted/foreign subdirectories
    (e.g. a compaction retire interrupted mid-rmtree)."""

    root: str
    members: List[ChainMember] = field(default_factory=list)
    head: Optional[str] = None  # member name
    torn_tail: Optional[str] = None
    chain: List[str] = field(default_factory=list)  # head first
    superseded: List[str] = field(default_factory=list)
    debris: List[str] = field(default_factory=list)

    @property
    def head_path(self) -> Optional[str]:
        return f"{self.root.rstrip('/')}/{self.head}" if self.head else None

    def summary(self) -> str:
        if not self.members:
            return f"{self.root}: no delta-stream members"
        s = (
            f"{self.root}: {len(self.members)} member(s), "
            f"head={self.head or 'NONE'}"
        )
        if self.chain:
            s += f", chain depth {len(self.chain)}"
        if self.torn_tail:
            s += f", TORN TAIL {self.torn_tail} (recovery ignores it)"
        if self.superseded:
            s += f", {len(self.superseded)} superseded"
        if self.debris:
            s += f", {len(self.debris)} debris dir(s)"
        return s


def resolve_chain(
    root: str, storage_options: Optional[Dict[str, Any]] = None
) -> DeltaChainReport:
    """Scan a stream root and name the recovery head, the torn tail (if
    a crash interrupted a micro-commit) and the live chain. Read-only;
    works on any backend that can list. Exposed through
    ``python -m tpusnap info|fsck <root>`` when the root itself holds no
    ``.snapshot_metadata`` but contains chain members."""
    import asyncio

    from .io_types import ReadIO
    from .lifecycle import JOURNAL_FNAME, JOURNAL_RECORDS_DIR
    from .manifest import decode_metadata
    from .snapshot import SNAPSHOT_METADATA_FNAME
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    report = DeltaChainReport(root=root)
    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(
            root, event_loop, storage_options
        )
        try:
            files = storage.sync_list_with_sizes(event_loop)
            if not files:
                return report
            # Group by first path component: each member is a subdir.
            by_member: Dict[str, Dict[str, int]] = {}
            for path, size in files.items():
                member, sep, rest = path.partition("/")
                if sep:
                    by_member.setdefault(member, {})[rest] = size
            for name in sorted(by_member):
                sub = by_member[name]
                m = ChainMember(name=name, state="debris")
                if SNAPSHOT_METADATA_FNAME in sub:
                    read_io = ReadIO(
                        path=f"{name}/{SNAPSHOT_METADATA_FNAME}"
                    )
                    try:
                        storage.sync_read(read_io, event_loop)
                        md = decode_metadata(read_io.buf.getvalue())
                    except Exception:
                        report.members.append(m)
                        report.debris.append(name)
                        continue
                    m.state = "committed"
                    m.created_at = md.created_at
                    d = delta_fields(md)
                    if d is not None:
                        m.seq = d.get("seq")
                        m.parent = d.get("parent")
                        m.stream_id = d.get("stream")
                    try:
                        m.payload_bytes = delta_payload_bytes(md)
                    except Exception:
                        pass
                elif JOURNAL_FNAME in sub or any(
                    p.startswith(JOURNAL_RECORDS_DIR + "/") for p in sub
                ):
                    m.state = "torn"
                    read_io = ReadIO(path=f"{name}/{JOURNAL_FNAME}")
                    try:
                        from .lifecycle import TakeJournal

                        storage.sync_read(read_io, event_loop)
                        j = TakeJournal.from_json(
                            read_io.buf.getvalue().decode("utf-8")
                        )
                        if j.stream:
                            m.seq = j.stream.get("seq")
                            m.parent = j.stream.get("parent")
                            m.stream_id = j.stream.get("stream")
                    except Exception:
                        pass
                else:
                    report.debris.append(name)
                report.members.append(m)
        finally:
            storage.sync_close(event_loop)
    finally:
        event_loop.close()

    committed = [m for m in report.members if m.state == "committed"]
    chain_members = [m for m in committed if m.seq is not None]
    if chain_members:
        head = max(
            chain_members, key=lambda m: (m.seq, m.created_at or 0.0)
        )
        report.head = head.name
    elif committed:
        # Non-stream snapshots under the root (or pre-field members):
        # newest committed by created_at is still the best recovery
        # point resolve can offer.
        report.head = max(
            committed, key=lambda m: m.created_at or 0.0
        ).name
    torn = [m for m in report.members if m.state == "torn"]
    if torn:
        report.torn_tail = max(
            torn, key=lambda m: (m.seq is not None, m.seq or 0)
        ).name
    if report.head:
        report.chain = _chain_of(root, report.head, storage_options)
        live = set(report.chain)
        report.superseded = [
            m.name for m in committed if m.name not in live
        ]
    return report


def _chain_of(
    root: str,
    head_name: str,
    storage_options: Optional[Dict[str, Any]] = None,
) -> List[str]:
    """The member names the head's blob references actually span (head
    first) — the base_roots recorded at take time, resolved back to
    member names. Because writers collapse chained references, this IS
    the complete keep-alive set for the head; no transitive walk is
    needed (retention still walks transitively as defense in depth)."""
    from .inspect import load_snapshot_metadata

    head_path = f"{root.rstrip('/')}/{head_name}"
    try:
        md = load_snapshot_metadata(head_path, storage_options)
    except Exception:
        return [head_name]
    out = [head_name]
    for r in md.base_roots or []:
        # Base roots are relative to the member ("../base-000000").
        name = posixpath.normpath(posixpath.join(head_name, r))
        if "/" not in name and name not in out and name != head_name:
            out.append(name)
    return out


# --------------------------------------------------------------- the stream


class DeltaStream:
    """A live continuous-checkpointing session. Construct via
    :meth:`tpusnap.Snapshot.stream`. Thread-safe; one capture in flight
    at a time. See the module docstring for semantics."""

    def __init__(
        self,
        root: str,
        app_state,
        cadence_s: Optional[float] = None,
        replicated: Optional[List[str]] = None,
        storage_options: Optional[Dict[str, Any]] = None,
        comm: Optional[Communicator] = None,
        max_chain: Optional[int] = None,
    ) -> None:
        comm = get_communicator(comm)
        if comm.world_size > 1:
            raise NotImplementedError(
                "Snapshot.stream is single-process for now: multi-rank "
                "micro-commit cadence agreement and background state "
                "capture need their own coordination protocol. Use "
                "take/async_take with incremental_from for multi-host "
                "delta checkpointing."
            )
        self.root = root
        if cadence_s is not None:
            cadence_s = float(cadence_s)
            if cadence_s <= 0:
                raise ValueError(
                    f"cadence_s must be > 0, got {cadence_s!r} (the "
                    "TPUSNAP_DELTA_CADENCE_S default applies when omitted)"
                )
            # Same floor as the knob: a micro-commit is a real
            # two-phase-committed take.
            self.cadence_s = max(0.1, cadence_s)
        else:
            self.cadence_s = get_delta_cadence_s()
        self.max_chain = int(max_chain or get_delta_max_chain())
        self.stream_id = uuid.uuid4().hex[:16]
        self._app_state = app_state
        self._replicated = replicated
        self._storage_options = storage_options
        self._comm = comm
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False
        self._seq = 0
        self._head: Optional[str] = None  # member NAME
        self._chain: List[str] = []  # oldest first, head last
        self._step_gated = False  # a mark_step caller exists
        self._commit_due = False  # cadence elapsed, capture wanted
        self._capture_busy = False  # a capture/commit is in flight
        self._last_commit_mono: float = 0.0
        self._last_error: Optional[BaseException] = None
        # A staged-but-not-finalized capture handed off by mark_step:
        # the worker waits out its background commit drain so the
        # training thread never blocks past the staging window.
        self._pending_finalize: Optional[Dict[str, Any]] = None
        self._observability_stopped = False
        self.stats: Dict[str, Any] = {
            "commits": 0,
            "bytes_written_total": 0,
            "last_commit_bytes": 0,
            "last_commit_wall_s": None,
            "max_commit_interval_s": None,
            "compactions": 0,
            "steps_marked": 0,
        }

        # Refuse a root that already holds stream members: a fresh
        # base-000000 under committed deltas that reference the OLD
        # base would silently change the bytes their external
        # references resolve to. Recovery is explicit — restore
        # resolve_chain(root).head, then stream to a fresh root.
        # (Backends that cannot list skip the guard.)
        existing = resolve_chain(root, storage_options)
        if existing.members:
            raise ValueError(
                f"{root!r} already holds delta-stream member(s) "
                f"({', '.join(m.name for m in existing.members[:4])}"
                f"{', ...' if len(existing.members) > 4 else ''}). "
                "Resuming a stream in place is not supported: restore "
                f"the recovery head ({existing.head!r}) into your app "
                "state, then open the stream on a FRESH root (or gc the "
                "old members first)."
            )

        # The base: a full, committed snapshot with per-tile dedup
        # hashes recorded, so the very first increment already skips at
        # tile grain. Synchronous — the stream is not armed until a
        # recovery point exists.
        flight.record(
            "delta", op="stream_start", stream=self.stream_id,
            cadence_s=self.cadence_s,
        )
        self._commit(kind="base")
        try:
            from . import slo as _slo

            _slo.tracker().note_stream(self.cadence_s)
        except Exception:
            logger.debug("slo note_stream failed", exc_info=True)

        self._worker = threading.Thread(
            target=self._run, name="tpusnap-delta", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------- public

    @property
    def head(self) -> Optional[str]:
        """Path of the last committed member — the recovery point."""
        with self._lock:
            return self._member_path(self._head) if self._head else None

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    @property
    def chain(self) -> List[str]:
        """Committed member names, oldest first."""
        with self._lock:
            return list(self._chain)

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def mark_step(self, bytes_changed: Optional[int] = None) -> None:
        """Declare a training-step boundary (call once per optimizer
        step from the training thread). Arms step-gated capture: each
        due micro-commit's CAPTURE (state_dict + dual-hash staging)
        runs inline HERE, at a boundary, so it can never overlap an
        in-place mutation; the write + two-phase commit still drain in
        the background. ``bytes_changed`` (optional) feeds the SLO
        tracker's exact data-at-risk tier."""
        if bytes_changed:
            try:
                from . import slo as _slo

                _slo.record_step(bytes_changed)
            except Exception:
                pass
        capture = False
        with self._lock:
            self._step_gated = True
            self.stats["steps_marked"] += 1
            if self._commit_due and not self._capture_busy and not self._closed:
                self._commit_due = False
                self._capture_busy = True
                capture = True
        if capture:
            # Capture ONLY on the training thread: async_take returns
            # at staging-complete (incremental takes stage strictly),
            # so the state is frozen — and safe to mutate again — the
            # moment _begin_capture returns. The storage writes and the
            # two-phase commit drain on the take's background thread;
            # the WORKER waits them out and finalizes, so mark_step
            # never blocks on storage or compaction.
            try:
                ctx = self._begin_capture("delta")
            except Exception as e:
                # A failed capture must not take the TRAINING loop down
                # — stop the stream; the last committed increment stays
                # the recovery point and raise_if_failed() surfaces it.
                self._fail(e, where="micro-commit capture in mark_step")
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()
                return
            inline = False
            with self._cv:
                if self._closed:
                    # Teardown race: the worker may already be gone —
                    # finalize here rather than strand the capture.
                    inline = True
                else:
                    self._pending_finalize = ctx
                    self._cv.notify_all()
            if inline:
                try:
                    self._finalize_capture(ctx)
                except Exception:
                    logger.warning(
                        "DeltaStream finalize during close failed "
                        "(the previous head remains the recovery point)",
                        exc_info=True,
                    )
                finally:
                    with self._cv:
                        self._capture_busy = False
                        self._cv.notify_all()

    def commit_now(self):
        """Force a synchronous micro-commit on the calling thread and
        return the committed :class:`~tpusnap.Snapshot`. Raises if the
        stream is closed."""
        with self._cv:
            if self._closed:
                raise RuntimeError("DeltaStream is closed")
            while self._capture_busy:
                self._cv.wait()
                if self._closed:
                    raise RuntimeError("DeltaStream is closed")
            self._capture_busy = True
            self._commit_due = False
        try:
            return self._commit(kind="delta")
        finally:
            with self._cv:
                self._capture_busy = False
                self._cv.notify_all()

    def close(self, final_commit: bool = True) -> Optional[str]:
        """Stop the stream. With ``final_commit`` (the default) a last
        micro-commit captures the state as of close, so nothing since
        the previous cadence tick is lost. Returns the head path.
        Idempotent."""
        with self._cv:
            already = self._closed
            if not already:
                self._closed = True
                self._cv.notify_all()
        if already:
            self._stop_observability()
            return self._member_path(self._head) if self._head else None
        from .io_types import close_may_join

        if close_may_join():
            # Joining is safe only on the explicit-close path: a
            # GC-finalizer close (the lockwatch-caught deadlock class)
            # skips the join — the daemon worker observes _closed and
            # exits on its own.
            # tpusnap: waive=TPS006 join is gated on close_may_join() above
            self._worker.join(timeout=60.0)
        # Drain a capture the worker may have exited without finalizing
        # (mark_step hand-off racing the shutdown).
        with self._cv:
            ctx = self._pending_finalize
            self._pending_finalize = None
        if ctx is not None:
            try:
                self._finalize_capture(ctx)
            except Exception:
                logger.warning(
                    "DeltaStream finalize during close failed (the "
                    "previous head remains the recovery point)",
                    exc_info=True,
                )
            finally:
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()
        if final_commit and self._last_error is None:
            with self._cv:
                while self._capture_busy:
                    self._cv.wait()
                self._capture_busy = True
            try:
                self._commit(kind="delta")
            except Exception:
                logger.warning(
                    "DeltaStream final commit failed (the previous head "
                    "remains the recovery point)",
                    exc_info=True,
                )
            finally:
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()
        self._stop_observability()
        return self._member_path(self._head) if self._head else None

    def __enter__(self) -> "DeltaStream":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # On an exception unwind, skip the final commit: the state may
        # be mid-step garbage; the last committed increment is the
        # honest recovery point.
        self.close(final_commit=exc_type is None)

    def raise_if_failed(self) -> None:
        """Re-raise the worker's terminal failure, if any (a failed
        micro-commit stops the stream rather than silently shipping
        stale recovery points forever)."""
        with self._lock:
            err = self._last_error
        if err is not None:
            raise RuntimeError(
                "DeltaStream worker failed; the stream is stopped and the "
                f"last committed increment is the recovery point: {err!r}"
            ) from err

    # ------------------------------------------------------------ internals

    def _member_path(self, name: str) -> str:
        return f"{self.root.rstrip('/')}/{name}"

    def _fail(self, exc: BaseException, where: str) -> None:
        """Stop the stream on a terminal failure (the last committed
        increment remains the recovery point); raise_if_failed()
        surfaces the cause to the caller."""
        logger.error(
            "DeltaStream %s failed; stopping the stream (the last "
            "committed increment remains the recovery point)",
            where,
            exc_info=True,
        )
        with self._cv:
            self._last_error = exc
            self._closed = True
            self._cv.notify_all()
        self._stop_observability()

    def _stop_observability(self) -> None:
        """Idempotent teardown of the stream's observability footprint:
        the SLO tracker's cadence gauge must never advertise a live
        stream after the stream stopped — for ANY reason, including a
        failed micro-commit mid-incident (exactly when a dashboard
        claiming 'delta stream active' would mislead)."""
        with self._lock:
            if self._observability_stopped:
                return
            self._observability_stopped = True
        try:
            from . import slo as _slo

            _slo.tracker().note_stream(None)
        except Exception:
            logger.debug("slo note_stream failed", exc_info=True)
        flight.record(
            "delta", op="stream_close", stream=self.stream_id,
            commits=self.stats["commits"],
        )

    def _run(self) -> None:
        """Worker loop: finalize captures handed off by mark_step (wait
        out their background commit drains), wake at cadence, capture
        here (free-running) or defer to the next mark_step (step-gated,
        with a one-cadence grace so a stalled training loop cannot
        suspend checkpointing forever)."""
        while True:
            with self._cv:
                ctx = self._pending_finalize
                self._pending_finalize = None
            if ctx is not None:
                # A mark_step capture: wait out its background commit
                # drain + bookkeeping/compaction here, off the training
                # thread.
                try:
                    self._finalize_capture(ctx)
                except Exception as e:
                    self._fail(e, where="micro-commit")
                    return
                finally:
                    with self._cv:
                        self._capture_busy = False
                        self._cv.notify_all()
                continue
            with self._cv:
                deadline = self._last_commit_mono + self.cadence_s
                while not self._closed and self._pending_finalize is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=min(remaining, 0.5))
                if self._pending_finalize is not None:
                    continue
                if self._closed:
                    return
                if self._capture_busy:
                    # A commit_now (or an in-flight mark_step capture)
                    # owns the slot; check back shortly rather than
                    # stacking a second commit on top.
                    self._cv.wait(timeout=0.05)
                    continue
                if self._step_gated:
                    # Hand the capture to the training thread: the next
                    # mark_step performs it at a step boundary.
                    self._commit_due = True
                    grace = time.monotonic() + self.cadence_s
                    while (
                        not self._closed
                        and self._commit_due
                        and time.monotonic() < grace
                    ):
                        self._cv.wait(timeout=0.05)
                    if self._closed:
                        return
                    if not self._commit_due:
                        # mark_step took it (or a commit_now raced in);
                        # loop to the top — the hand-off pickup and the
                        # next interval live there.
                        continue
                    # Grace expired: training loop stalled mid-step (or
                    # stopped calling mark_step) — a bounded RPO beats
                    # step consistency; fall through to a free-running
                    # capture.
                    self._commit_due = False
                self._capture_busy = True
            try:
                self._commit(kind="delta")
            except Exception as e:
                self._fail(e, where="micro-commit")
                return
            finally:
                with self._cv:
                    self._capture_busy = False
                    self._cv.notify_all()

    def _commit(self, kind: str):
        """One full micro-commit on THIS thread (capture + commit drain
        + bookkeeping). commit_now/close/base use it; mark_step splits
        it into _begin_capture (training thread) + _finalize_capture
        (worker)."""
        return self._finalize_capture(self._begin_capture(kind))

    def _begin_capture(self, kind: str) -> Dict[str, Any]:
        """The capture half: state_dict + strict dual-hash staging.
        When this returns, the content is FROZEN (incremental takes
        stage everything before async_take returns) and the caller may
        mutate state again; the storage writes + two-phase commit drain
        on the take's own background thread. Caller holds the
        _capture_busy slot (or is __init__)."""
        from .snapshot import Snapshot

        t0 = time.monotonic()
        with self._lock:
            seq = self._seq if kind == "base" else self._seq + 1
            prev = self._head
        name = member_name(seq)
        path = self._member_path(name)
        extras = {
            "delta": {
                "stream": self.stream_id,
                "seq": seq,
                "parent": prev,
            }
        }
        ctx: Dict[str, Any] = {"kind": kind, "t0": t0, "seq": seq,
                               "name": name}
        if kind == "base":
            # Full base, tile-grain dedup hashes recorded everywhere.
            ctx["snap"] = Snapshot.take(
                path,
                self._app_state,
                replicated=self._replicated,
                storage_options=self._storage_options,
                comm=self._comm,
                _extras=extras,
                _record_dedup_hashes=True,
            )
        else:
            # Micro-commits force DEFENSIVE-CLONE staging (not the
            # process-wide TPUSNAP_ASYNC_COW default): the stream's
            # whole point is that training keeps mutating while the
            # drain runs, with no wait_staged() rendezvous — under COW
            # every free-running capture would fail on the write-time
            # mutation check. Per-take parameter, not an env override:
            # a global flip would race concurrent takes on other
            # threads into silently paying the full clone pass.
            ctx["pending"] = Snapshot.async_take(
                path,
                self._app_state,
                replicated=self._replicated,
                storage_options=self._storage_options,
                comm=self._comm,
                incremental_from=self._member_path(prev),
                _extras=extras,
                _record_dedup_hashes=True,
                _force_clone_staging=True,
            )
        return ctx

    def _finalize_capture(self, ctx: Dict[str, Any]):
        """The commit half: wait out the background drain (ONE commit in
        flight at a time — the capture slot is held until this returns),
        then head/chain bookkeeping and compaction."""
        kind, t0, seq = ctx["kind"], ctx["t0"], ctx["seq"]
        name = ctx["name"]
        snap = ctx.get("snap")
        if snap is None:
            snap = ctx["pending"].wait()
        wall = time.monotonic() - t0
        written = 0
        try:
            written = delta_payload_bytes(snap.metadata)
        except Exception:
            logger.debug("delta payload accounting failed", exc_info=True)
        telemetry.incr("delta.commits")
        if written:
            telemetry.incr("delta.bytes_written", written)
        with self._lock:
            interval = (
                time.monotonic() - self._last_commit_mono
                if self._last_commit_mono
                else None
            )
            self._last_commit_mono = time.monotonic()
            self._seq = seq
            self._head = name
            self._chain.append(name)
            st = self.stats
            st["commits"] += 1
            st["bytes_written_total"] += written
            st["last_commit_bytes"] = written
            st["last_commit_wall_s"] = round(wall, 4)
            if interval is not None:
                st["max_commit_interval_s"] = max(
                    st["max_commit_interval_s"] or 0.0, round(interval, 4)
                )
            chain_len = len(self._chain)
        flight.record(
            "delta",
            op="micro_commit" if kind != "base" else "base_commit",
            stream=self.stream_id,
            seq=seq,
            bytes=written,
            wall_s=round(wall, 4),
        )
        if chain_len > self.max_chain:
            self._compact(snap)
        return snap

    def _compact(self, head_snap) -> None:
        """Chain compaction via the existing materialize path: the head
        becomes self-contained (referenced base blobs copied in,
        checksum-verified, metadata rewritten atomically — a crash
        mid-copy leaves the old metadata and the chain intact), then
        the superseded members are retired. Local-fs roots delete them;
        other backends leave them for `gc`/bucket lifecycle rules."""
        t0 = time.monotonic()
        stats = head_snap.materialize()
        with self._lock:
            head = self._head
            superseded = [m for m in self._chain if m != head]
            self._chain = [head]
        telemetry.incr("delta.compactions")
        flight.record(
            "delta",
            op="compact",
            stream=self.stream_id,
            head=head,
            bytes_copied=stats.get("bytes_copied", 0),
            retired=len(superseded),
            wall_s=round(time.monotonic() - t0, 4),
        )
        self.stats["compactions"] += 1
        parts = urlsplit(self.root)
        if parts.scheme not in ("", "file"):
            logger.info(
                "Delta chain compacted at %r; %d superseded member(s) left "
                "for bucket lifecycle rules / `tpusnap gc`",
                self.root,
                len(superseded),
            )
            return
        import os
        import shutil

        root = os.path.abspath(parts.path or self.root)
        for name in superseded:
            target = os.path.join(root, name)
            # Metadata first: a retire interrupted mid-delete leaves a
            # directory that can never be mistaken for a committed
            # snapshot (resolve_chain reports it as debris; the
            # crash-matrix covers this window).
            try:
                meta = os.path.join(target, ".snapshot_metadata")
                if os.path.exists(meta):
                    os.unlink(meta)
                shutil.rmtree(target, ignore_errors=True)
            except OSError:
                logger.warning(
                    "Failed to retire superseded member %r (reclaim via "
                    "`tpusnap gc` later)",
                    target,
                    exc_info=True,
                )
