"""Type → preparer dispatch for write and Entry → preparer dispatch for read.

Counterpart of /root/reference/torchsnapshot/io_preparer.py:46-165.
Routing (write):

- int/float/bool/str/bytes         → inlined PrimitiveEntry (no I/O)
- sharded jax.Array                → ShardedArrayIOPreparer ("sharded/...")
- dense array above max_chunk_size → ChunkedArrayIOPreparer
- dense array, supported dtype     → ArrayIOPreparer
- anything else                    → ObjectIOPreparer (pickle)

Storage paths: sharded entries under ``sharded/``, replicated entries
under ``replicated/``, everything else under ``<rank>/``
(reference io_preparer.py:46-52).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from .io_types import Future, ReadReq, WriteReq
from .manifest import (
    ChunkedTensorEntry,
    Entry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedEntry,
    TensorEntry,
)
from .io_preparers.array import ArrayIOPreparer, is_supported_array_dtype
from .io_preparers.chunked import ChunkedArrayIOPreparer, should_chunk
from .io_preparers.object import ObjectIOPreparer
from .io_preparers.sharded import ShardedArrayIOPreparer, is_sharded


def get_storage_path(
    logical_path: str, rank: int, replicated: bool, sharded: bool
) -> str:
    if sharded:
        return f"sharded/{logical_path}"
    if replicated:
        return f"replicated/{logical_path}"
    return f"{rank}/{logical_path}"


def prepare_write(
    obj: Any,
    logical_path: str,
    rank: int,
    replicated: bool = False,
    is_async_snapshot: bool = False,
    array_prepare_func: Optional[Any] = None,
    array_prepare_traced: Optional[Tuple[str, Any]] = None,
    prev_entry: Optional[Entry] = None,
    record_dedup_hashes: bool = False,
    allow_tile_dedup: bool = True,
) -> Tuple[Entry, List[WriteReq]]:
    """``array_prepare_func(arr, tracing) -> arr`` is the user save-time
    transform (reference _custom_tensor_prepare_func, snapshot.py:
    170-196); it applies to dense, chunked AND sharded arrays — the
    sharded preparer applies it per local shard, like the reference
    threads its tensor_prepare_func into the sharded path
    (reference io_preparer.py:100-106, sharded_tensor.py:133,159).
    Non-array objects pass through untransformed.
    ``array_prepare_traced`` is the already-traced (dtype, shape) from
    the write-load estimator, so untraceable transforms don't execute a
    second discarded time here.
    ``prev_entry`` is the previous snapshot's entry for this logical path
    (locations rewritten relative to the new snapshot root) for
    incremental-snapshot dedup: blobs whose staged bytes hash identically
    skip their writes and reference the previous snapshot's blob.
    ``record_dedup_hashes`` (incremental takes) records 64-bit per-tile
    dedup hashes so later increments can skip at TILE grain; when
    ``prev_entry`` carries a usable tile map, the dense/chunked write is
    re-chunked on the previous take's checksum-tile grid and each tile
    dedups independently — one changed row of a multi-GB array rewrites
    one tile, not the blob. ``allow_tile_dedup=False`` disables that
    re-chunking (multi-process replicated entries: the write-load
    estimator's unit ids must stay blob-grain on every rank)."""
    if PrimitiveEntry.supported(obj):
        return PrimitiveEntry.from_object(obj, replicated=replicated), []

    if isinstance(obj, np.generic):  # numpy scalar → 0-d array
        obj = np.asarray(obj)

    if isinstance(obj, jax.Array) and is_sharded(obj):
        storage_path = get_storage_path(logical_path, rank, False, sharded=True)
        return ShardedArrayIOPreparer.prepare_write(
            storage_path,
            obj,
            is_async_snapshot=is_async_snapshot,
            array_prepare_func=array_prepare_func,
            array_prepare_traced=array_prepare_traced,
            prev_entry=prev_entry,
            record_dedup_hashes=record_dedup_hashes,
        )

    if isinstance(obj, (jax.Array, np.ndarray)) and is_supported_array_dtype(obj):
        storage_path = get_storage_path(logical_path, rank, replicated, sharded=False)
        if prev_entry is not None and allow_tile_dedup:
            # Tile-grain incremental route: re-chunk on the previous
            # take's checksum-tile grid so each tile skips or writes
            # independently (byte-range references into the base blob
            # for unchanged tiles).
            from .io_preparers.chunked import tile_prev_map
            from .io_preparers.array import trace_array_prepare

            if array_prepare_traced is not None:
                dtype, shape = array_prepare_traced[0], list(array_prepare_traced[1])
            else:
                dtype, shape = trace_array_prepare(obj, array_prepare_func)
                array_prepare_traced = (dtype, shape)
            tiled_prev = tile_prev_map(prev_entry, dtype, shape)
            if tiled_prev is not None:
                grid_rows, prev_tiles = tiled_prev
                return ChunkedArrayIOPreparer.prepare_write(
                    storage_path,
                    obj,
                    replicated,
                    is_async_snapshot,
                    array_prepare_func=array_prepare_func,
                    array_prepare_traced=array_prepare_traced,
                    record_dedup_hashes=record_dedup_hashes,
                    chunk_rows=grid_rows,
                    prev_chunks=prev_tiles,
                )
        if should_chunk(obj):
            return ChunkedArrayIOPreparer.prepare_write(
                storage_path,
                obj,
                replicated,
                is_async_snapshot,
                array_prepare_func=array_prepare_func,
                array_prepare_traced=array_prepare_traced,
                prev_entry=prev_entry,
                record_dedup_hashes=record_dedup_hashes,
            )
        return ArrayIOPreparer.prepare_write(
            storage_path,
            obj,
            replicated,
            is_async_snapshot,
            array_prepare_func=array_prepare_func,
            array_prepare_traced=array_prepare_traced,
            prev_entry=prev_entry,
            record_dedup_hashes=record_dedup_hashes,
        )

    storage_path = get_storage_path(logical_path, rank, replicated, sharded=False)
    return ObjectIOPreparer.prepare_write(
        storage_path, obj, replicated, prev_entry=prev_entry
    )


def prepare_read(
    entry: Entry,
    obj_out: Any = None,
    buffer_size_limit_bytes: Optional[int] = None,
    logical_path: str = "",
) -> Tuple[List[ReadReq], Future]:
    """``logical_path`` labels integrity failures with the user-facing
    manifest path — slab-batched blobs' storage locations are opaque
    uuids, useless in a corruption report."""
    if isinstance(entry, PrimitiveEntry):
        return [], Future(obj=entry.get_value())
    if isinstance(entry, ShardedEntry):
        return ShardedArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes, logical_path=logical_path
        )
    if isinstance(entry, ChunkedTensorEntry):
        return ChunkedArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes, logical_path=logical_path
        )
    if isinstance(entry, TensorEntry):
        return ArrayIOPreparer.prepare_read(
            entry, obj_out, buffer_size_limit_bytes, logical_path=logical_path
        )
    if isinstance(entry, ObjectEntry):
        return ObjectIOPreparer.prepare_read(entry, logical_path=logical_path)
    raise TypeError(f"Cannot prepare read for entry type {type(entry).__name__}")
