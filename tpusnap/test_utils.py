"""Distributed test harness + state-dict equality helpers.

Counterpart of /root/reference/torchsnapshot/test_utils.py. The
reference's key trick (test_utils.py:183-265) launches each test function
under torch elastic as a single-node N-process gloo job; the TPU-native
equivalent spawns N subprocesses that each call
``jax.distributed.initialize`` against a shared coordinator on the CPU
platform — giving a REAL multi-process, multi-device JAX runtime (arrays
spanning processes are genuinely non-fully-addressable) without TPU
hardware.

Usage in tests::

    def _my_world_fn():           # top-level, importable
        import jax ...            # jax.distributed is already initialized

    def test_thing():
        run_subprocess_world(_my_world_fn, world_size=2)

Each subprocess re-imports the function's module and calls it by
qualname (same re-import trick as the reference, test_utils.py:221-224).
"""

from __future__ import annotations

import importlib
import os
import socket
import subprocess
import sys
from typing import Any, Callable, Dict, List, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def rand_array(dtype_str: str, shape=(16, 9), seed: int = 0) -> np.ndarray:
    """Random array of any supported dtype with full bit diversity
    (reference rand_tensor, test_utils.py:104-144)."""
    from .serialization import string_to_dtype

    rng = np.random.default_rng(seed)
    dtype = string_to_dtype(dtype_str)
    if dtype_str == "bool":
        return rng.integers(0, 2, size=shape).astype(bool)
    if dtype_str.startswith(("float", "bfloat")):
        return rng.standard_normal(shape).astype(dtype)
    if dtype_str.startswith("complex"):
        return (rng.standard_normal(shape) + 1j * rng.standard_normal(shape)).astype(
            dtype
        )
    raw = rng.integers(0, 256, size=(*shape, dtype.itemsize), dtype=np.uint8)
    return raw.view(dtype).reshape(*shape, -1)[..., 0].copy()


def check_state_dict_eq(a: Any, b: Any) -> bool:
    """Array-aware deep equality over nested state (reference
    check_state_dict_eq, test_utils.py:41-101)."""
    import jax

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb or len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        if hasattr(x, "shape") or hasattr(y, "shape"):
            xa, ya = np.asarray(x), np.asarray(y)
            if xa.dtype != ya.dtype or xa.shape != ya.shape:
                return False
            if xa.tobytes() != ya.tobytes():
                return False
        elif x != y:
            return False
    return True


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def apply_platform_env() -> None:
    """Honor JAX_PLATFORMS / --xla_force_host_platform_device_count in
    processes where a sitecustomize already registered a TPU backend.

    This environment pre-loads PYTHONPATH=/root/.axon_site whose
    sitecustomize registers the real-TPU "axon" platform at interpreter
    startup — by then the JAX_PLATFORMS env var has already been read.
    ``jax.config.update`` still works until devices are first queried, so
    examples/benchmarks call this before touching jax.devices().
    """
    import re

    import jax

    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        jax.config.update("jax_platforms", platforms)
    match = re.search(
        r"xla_force_host_platform_device_count=(\d+)",
        os.environ.get("XLA_FLAGS", ""),
    )
    if match:
        try:
            jax.config.update("jax_num_cpu_devices", int(match.group(1)))
        except AttributeError:
            # Older JAX: the XLA_FLAGS env var itself is honored at
            # backend init, no config option needed.
            pass


def run_subprocess_world(
    fn: Callable[[], None],
    world_size: int,
    devices_per_process: int = 2,
    timeout: float = 180.0,
    extra_env: Optional[Dict[str, str]] = None,
    args: Optional[List[str]] = None,
    hostnames: Optional[List[str]] = None,
) -> List[str]:
    """Run ``fn`` in ``world_size`` jax.distributed-initialized processes.
    Returns each rank's stdout; raises with full logs if any rank fails.

    ``hostnames`` simulates a MULTI-HOST topology on one machine: rank i
    runs with ``TPUSNAP_NODE_NAME=hostnames[i]``, which the per-host
    memory-budget divisor and take's G1 hostname gather read in place of
    the OS hostname — the closest honest approximation of the
    reference's multi-node scaling available without real nodes."""
    port = find_free_port()
    coordinator = f"127.0.0.1:{port}"
    procs = []
    env_base = dict(os.environ)
    env_base.pop("PYTHONPATH", None)  # drop the TPU sitecustomize
    # The subprocess must be able to re-import fn's defining module even
    # when it lives outside the repo (a user's own script directory).
    module = sys.modules.get(fn.__module__)
    module_dir = ""
    module_name = fn.__module__
    if module is not None and getattr(module, "__file__", None):
        module_path = os.path.abspath(module.__file__)
        module_dir = os.path.dirname(module_path)
        if module_name == "__main__":
            # fn was defined in a directly-run script; the subprocess must
            # re-import it by file name, not as "__main__" (which would be
            # tpusnap.test_utils's own entry point there).
            module_name = os.path.splitext(os.path.basename(module_path))[0]
    for rank in range(world_size):
        env = dict(env_base)
        env.update(
            {
                "PYTHONPATH": _REPO_ROOT,
                "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": f"--xla_force_host_platform_device_count={devices_per_process}",
                "TPUSNAP_TEST_COORDINATOR": coordinator,
                "TPUSNAP_TEST_WORLD_SIZE": str(world_size),
                "TPUSNAP_TEST_RANK": str(rank),
                "TPUSNAP_TEST_MODULE_DIR": module_dir,
            }
        )
        if hostnames is not None:
            env["TPUSNAP_NODE_NAME"] = hostnames[rank]
        if extra_env:
            env.update(extra_env)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable,
                    "-m",
                    "tpusnap.test_utils",
                    module_name,
                    fn.__qualname__,
                    *(args or []),
                ],
                env=env,
                cwd=_REPO_ROOT,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outputs = []
    failed = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
            out += "\n<TIMED OUT>"
        outputs.append(out)
        if proc.returncode != 0:
            failed.append(rank)
    if failed:
        logs = "\n".join(
            f"----- rank {r} (exit {procs[r].returncode}) -----\n{outputs[r]}"
            for r in range(world_size)
        )
        raise RuntimeError(f"Ranks {failed} failed:\n{logs}")
    return outputs


def _subprocess_main() -> None:
    module_name, qualname = sys.argv[1], sys.argv[2]
    # These vars are subprocess-harness plumbing (run_multiprocess →
    # child), not knobs, so they are waived from the knob-access lint.
    coordinator = os.environ["TPUSNAP_TEST_COORDINATOR"]  # tpusnap: waive=TPS001 harness plumbing
    world_size = int(os.environ["TPUSNAP_TEST_WORLD_SIZE"])  # tpusnap: waive=TPS001 harness plumbing
    rank = int(os.environ["TPUSNAP_TEST_RANK"])  # tpusnap: waive=TPS001 harness plumbing

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=world_size,
        process_id=rank,
    )
    # tests/ modules are importable from the repo root; user modules from
    # wherever the launching function was defined.
    sys.path.insert(0, os.path.join(_REPO_ROOT, "tests"))
    module_dir = os.environ.get("TPUSNAP_TEST_MODULE_DIR")  # tpusnap: waive=TPS001 harness plumbing
    if module_dir:
        sys.path.insert(0, module_dir)
    module = importlib.import_module(module_name)
    fn = module
    for part in qualname.split("."):
        fn = getattr(fn, part)
    fn(*sys.argv[3:])


if __name__ == "__main__":
    _subprocess_main()
