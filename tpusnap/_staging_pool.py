"""Reusable aligned staging buffers for async-snapshot clones.

The async take's blocked window is dominated by the defensive clone on
CPU-backend hosts — and most of the CLONE's cost is not the copy but
first-touch page faults on the freshly allocated destination (the
kernel zeroes every 4 KiB page; ~1 GB/s on a single core here, measured
— vs ~3.5 GB/s for the copy into warm pages). A steady-state checkpoint
loop clones buffers of the SAME sizes every take, so this pool keeps
released clone buffers and hands them back warm: from the second async
take on, the blocked window pays the memcpy, not the kernel's page
zeroing.

Deliberately minimal: exact-size matching only (checkpoint loops stage
identical shapes every take), bounded by TPUSNAP_STAGING_POOL_BYTES
(default 4 GiB; 0 disables), and leak-proof — outstanding buffers are
tracked by weakref, so a buffer dropped on an abort path is simply
garbage-collected and forgotten instead of stranded. ``release`` is
safe to call with ANY buffer: non-pool buffers are ignored.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Tuple

import numpy as np

from . import knobs, telemetry

_lock = threading.Lock()
_free: List[Tuple[int, np.ndarray]] = []  # [(nbytes, buffer)]
_free_bytes = 0
# id(buffer) -> weakref: buffers handed out and not yet released. A
# weak ref (not strong) so abort paths leak nothing; dead entries are
# pruned on each acquire.
_outstanding: Dict[int, "weakref.ref"] = {}


def _cap_bytes() -> int:
    return knobs.get_staging_pool_bytes()


def acquire(nbytes: int) -> np.ndarray:
    """An aligned uint8 buffer of exactly ``nbytes`` — reused (warm
    pages) when a previously released buffer matches, fresh otherwise.
    Contents are undefined."""
    global _free_bytes
    from . import _native

    with _lock:
        # Prune outstanding entries whose buffers were dropped (aborts).
        dead = [k for k, r in _outstanding.items() if r() is None]
        for k in dead:
            del _outstanding[k]
        # Newest match first (LIFO): the most recently released buffer
        # has the warmest pages AND is what makes pipelined staging
        # windows allocation-free in steady state — window N+1's clone
        # of a recurring chunk size reuses the buffer window N's write
        # just released, so a whole multi-GB take touches only one
        # window's worth of distinct pages.
        for i in range(len(_free) - 1, -1, -1):
            n, buf = _free[i]
            if n == nbytes:
                _free.pop(i)
                _free_bytes -= n
                _outstanding[id(buf)] = weakref.ref(buf)
                telemetry.incr("staging_pool.hits")
                return buf
    telemetry.incr("staging_pool.misses")
    buf = _native.aligned_empty(nbytes)
    with _lock:
        _outstanding[id(buf)] = weakref.ref(buf)
    return buf


def release(buf) -> bool:
    """Return a buffer to the pool; True when the pool RETAINED it.
    Retained bytes are bounded by TPUSNAP_STAGING_POOL_BYTES, a cache
    budget of its own — the write scheduler's memory budget governs
    in-flight staging buffers only and credits every write back in
    full (see execute_write_reqs). Ignores buffers the pool did not
    hand out (memoryviews of user state, slabs, ...). When the cap is
    exceeded the OLDEST free entries are evicted first, so a process
    whose staged sizes change (model resize, different snapshot
    contents) ages the stale sizes out instead of stranding them
    forever."""
    global _free_bytes
    if not isinstance(buf, np.ndarray):
        return False
    with _lock:
        ref = _outstanding.pop(id(buf), None)
        if ref is None or ref() is not buf:
            return False
        cap = _cap_bytes()
        if buf.nbytes > cap:
            return False
        while _free and _free_bytes + buf.nbytes > cap:
            old_n, _ = _free.pop(0)  # evict oldest
            _free_bytes -= old_n
        _free.append((buf.nbytes, buf))
        _free_bytes += buf.nbytes
        return True


def free_bytes() -> int:
    """Bytes currently RESIDENT in the free list (bounded by
    TPUSNAP_STAGING_POOL_BYTES)."""
    with _lock:
        return _free_bytes


def clear() -> None:
    """Drop all cached buffers (tests; memory-pressure escape hatch)."""
    global _free_bytes
    with _lock:
        _free.clear()
        _free_bytes = 0
