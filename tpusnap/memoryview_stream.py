"""io stream adapter over a memoryview, so zero-copy buffers can be handed
to storage SDKs (S3/GCS) that want file-like objects without copying.

Counterpart of reference /root/reference/torchsnapshot/memoryview_stream.py.
"""

import io
from typing import Optional


class MemoryviewStream(io.IOBase):
    def __init__(self, mv: memoryview) -> None:
        self._mv = mv.cast("B")
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def read(self, size: int = -1) -> bytes:
        if self.closed:
            raise ValueError("I/O operation on closed stream")
        if size is None or size < 0:
            chunk = self._mv[self._pos :]
            self._pos = len(self._mv)
        else:
            chunk = self._mv[self._pos : self._pos + size]
            self._pos = min(self._pos + size, len(self._mv))
        return bytes(chunk)

    def readinto(self, b) -> int:
        data = self.read(len(b))
        n = len(data)
        b[:n] = data
        return n

    def seek(self, pos: int, whence: int = io.SEEK_SET) -> int:
        if self.closed:
            raise ValueError("I/O operation on closed stream")
        if whence == io.SEEK_SET:
            new_pos = pos
        elif whence == io.SEEK_CUR:
            new_pos = self._pos + pos
        elif whence == io.SEEK_END:
            new_pos = len(self._mv) + pos
        else:
            raise ValueError(f"Invalid whence: {whence}")
        if new_pos < 0:
            raise ValueError(f"Negative seek position {new_pos}")
        self._pos = new_pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def __len__(self) -> int:
        return len(self._mv)

    def getbuffer(self) -> Optional[memoryview]:
        return self._mv

    def getvalue(self) -> bytes:
        """BytesIO-compatible whole-buffer copy (ReadIO.buf consumers may
        hold either type)."""
        return bytes(self._mv)
