"""Snapshot operations CLI: ``python -m tpusnap <command> ...``

Operational tooling over the manifest + checksum machinery (no reference
counterpart — torchsnapshot ships no CLI and no integrity checking):

  info        PATH      snapshot version, world size, size breakdown
  ls          PATH [-l] list manifest entries (one line per logical path)
  verify      PATH      stream-verify every blob against recorded CRCs
                        (exit 2 = corruption, 3 = NOTHING was verifiable
                        — checksums disabled at take or a different
                        checksum build; scripts must not read that as
                        "verified clean")
  cat         PATH MANIFEST_PATH  read one object (``read_object``), print it
  materialize PATH      copy base-referenced blobs into an incremental
                        snapshot so its bases can be deleted
  diff        A B       compare two snapshots by recorded checksums only
                        (no data reads; exit 2 = provably different,
                        3 = undecidable without reading data)
  retain ROOT --keep N  keep the newest N snapshots under ROOT; any kept
                        increment referencing a doomed base is
                        materialized first, then the rest are deleted
  fsck        PATH      classify the directory (committed / torn / empty /
                        corrupt-metadata / foreign) from the take journal
                        + self-checksummed metadata, and enumerate orphan
                        blobs unreferenced by the manifest (exit 0 =
                        committed, 2 = corrupt-metadata, 4 = torn, 3 =
                        empty/foreign)
  gc          PATH      reclaim orphan blobs (dry-run by default; --force
                        deletes; --torn additionally discards a torn
                        take's salvageable blobs; --evict-local reclaims
                        a REMOTE-DURABLE tiered snapshot's local payload
                        blobs past the retention window). Safe
                        concurrently with readers: orphans are never
                        referenced
  drain       PATH      write-back tiering: drain a tiered snapshot's
                        local tier to its remote (resumes from the
                        crash-safe upload journal — blobs already proven
                        remote by CRC32C+XXH64 evidence are skipped;
                        bases/delta parents drain first). ``--status``
                        reports durability + upload lag without
                        draining; ``--timeout`` bounds outage patience
                        (exit 0 remote-durable / 2 not converged,
                        resumable / 3 nothing tiered at PATH)
  trace       PATH      render the take's telemetry (per-stage timings,
                        counters, cross-rank rollup, slowest-rank-per-
                        phase straggler attribution) from the traces
                        persisted under .tpusnap/telemetry/ and the
                        metadata extras (``--json`` for machines,
                        ``--rank K`` for one rank's stage detail;
                        ``--restore`` renders the LAST restore's traces
                        from the local TPUSNAP_TELEMETRY_DIR instead;
                        exit 3 = no telemetry recorded)
  watch       PATH      tail an IN-FLIGHT take's heartbeat records
                        (.tpusnap/progress/rank_<k>.json) and render a
                        live per-rank table (phase, % bytes, MB/s,
                        data-at-risk + time-since-last-commit exposure,
                        stragglers flagged), refreshing in place until
                        the take commits (``--once``/``--json`` for one
                        frame; exit 3 = no heartbeat records found)
  history               cross-run take/restore performance history from
                        this host's TPUSNAP_TELEMETRY_DIR/history.jsonl
                        (one event per completed take/restore; bench.py
                        records its runs too): trend table or ``--json``;
                        ``--check`` compares the latest run against the
                        trailing median (``--window``/``--threshold``,
                        cold-run-aware; ``--metric`` repeatable — e.g.
                        ``--metric throughput_gbps --metric
                        storage_write_p99_s``, JSON names each regressed
                        metric) and exits 2 on a regression so CI and
                        cron jobs can gate on it (exit 3 = not enough
                        comparable history / no events)
  analyze     PATH      performance doctor: deterministic critical-path
                        attribution of the take's (or ``--restore``'s)
                        wall-clock to resources (storage write/read,
                        DtoH, stage/clone, checksum, budget waits,
                        barriers) with a bound-by verdict and the
                        concrete knob to turn; tail-latency outliers
                        from the storage-boundary latency histograms;
                        straggler ranks; the in-take probe
                        ``roofline_fraction`` (``TPUSNAP_PROBE=1``);
                        ``--history`` adds trend context; ``--json``
                        for machines; ``--check`` exits 2 when any
                        warn-severity finding fires (exit 3 = no
                        telemetry recorded, matching ``trace``); the
                        restore view also attributes the decode lane
                        and reports ``restore_roofline_fraction``
                        against the in-restore probe READ ceiling
                        (``--min-read-roofline`` gates it)

  tune                  deterministic knob planner for one (backend,
                        kind, world_size) cell: history.jsonl events +
                        probe ceilings (+ ``--snapshot``'s analyze
                        verdict) in, one proposed env value per knob
                        out, each with a one-line rationale (table /
                        ``--json`` / ``--env`` shell exports;
                        ``--check`` exits 0 with a plan, 3 on
                        insufficient history; TPUSNAP_AUTOTUNE=1
                        applies the plan at take/restore begin —
                        explicit env vars always win, and applied
                        knobs are stamped into the history event as
                        ``tuned``)

  timeline    PATH      forensic cross-rank timeline from the flight-
                        recorder sidecars (.tpusnap/flight/rank_<k>.jsonl,
                        falling back to the local TPUSNAP_TELEMETRY_DIR
                        copy): all ranks' event logs merged in causal
                        order using barrier-anchored clock-skew
                        alignment (per-rank offset ± bound reported);
                        for any UNCOMMITTED path a post-mortem verdict
                        names, per rank, the in-flight op, last
                        completed phase, bytes staged/written vs
                        planned, journal.d completion evidence, stall
                        episodes and the missing-rank set
                        (``--rank K`` one rank, ``--last N`` newest N
                        events, ``--around T [--window S]`` events near
                        T seconds into the timeline, ``--json``; exit 0
                        = committed, 4 = uncommitted post-mortem, 3 =
                        no flight data recorded)

  slo                   checkpoint SLO state from this host's per-rank
                        tracker sidecars (TPUSNAP_TELEMETRY_DIR/slo/):
                        per-rank time-since-last-commit, data-at-risk
                        bytes, history-derived estimated RTO, breach
                        flags, and rank 0's fleet worst-case fold
                        (``--json`` for machines; ``--check`` gates:
                        exit 0 healthy, 2 when a set TPUSNAP_SLO_RPO_S
                        / TPUSNAP_SLO_RTO_S threshold — or ``--rpo`` /
                        ``--rto`` — is breached, 3 when no records
                        exist or an RTO objective is set but no
                        estimate could be formed)

  fleet                 cross-job fleet status from the shared
                        TPUSNAP_FLEET_DIR every instrumented job's rank
                        0 mirrors its heartbeat/SLO/tier state into:
                        per-job table (state, since-commit exposure,
                        data-at-risk, upload lag, degraded/paused/dead
                        flags) plus the fleet rollup — worst-case RPO
                        and at-risk across jobs, aggregate upload lag,
                        cross-job merged storage-latency quantiles
                        (``--json`` for machines; ``--prom-out`` writes
                        scope="fleet" Prometheus families; ``--check``
                        gates: exit 0 healthy, 2 when worst RPO /
                        aggregate lag / merged write p99-over-p50 ratio
                        crosses a threshold, 3 when the fleet dir holds
                        no records; ``watch --fleet`` tails the same
                        directory live)

  lint                  AST invariant checker over the package source
                        (``tpusnap/devtools/lint.py``): knob access only
                        through knobs.py, monotonic-only clocks,
                        canonical sidecar constants, no silent swallows
                        in crash-safety modules, no blocking calls in
                        scheduler coroutines, no finalizer-reachable
                        joins, knob/doc drift — with per-line waivers
                        (``# tpusnap: waive=<RULE> reason``);
                        ``--check`` exits 2 on any unwaived finding
                        (``--root`` lints another tree, ``--select``
                        runs a rule subset, ``--json`` for machines)

Exit codes: 0 success / clean, 1 usage or read error, 2 corruption found
(or provably-different diff; history --check: regression; analyze
--check: warn-severity finding; slo --check: SLO breach; fleet --check:
fleet objective breach), 3 undecidable/unverifiable (or no telemetry
recorded — trace and analyze; no flight data — timeline; fsck:
empty/foreign; history: no/insufficient events; slo: no records / no
estimator verdict; fleet: no status records; tune: insufficient
comparable history), 4 torn
take (fsck — salvageable by retaking the path; timeline: uncommitted
path, post-mortem verdict printed).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from .inspect import entry_nbytes, entry_verifiable, verify_snapshot
from .manifest import (
    ChunkedTensorEntry,
    ObjectEntry,
    PrimitiveEntry,
    ShardedEntry,
    TensorEntry,
    is_container_entry,
)
from .snapshot import Snapshot


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def _entry_desc(entry) -> str:
    if isinstance(entry, TensorEntry):
        return f"tensor  {entry.dtype}{entry.shape}"
    if isinstance(entry, ChunkedTensorEntry):
        return f"chunked {entry.dtype}{entry.shape} ({len(entry.chunks)} chunks)"
    if isinstance(entry, ShardedEntry):
        return f"sharded {entry.dtype}{entry.shape} ({len(entry.shards)} shards)"
    if isinstance(entry, ObjectEntry):
        return f"object  {entry.obj_type}"
    if isinstance(entry, PrimitiveEntry):
        val = entry.readable if entry.readable is not None else entry.serialized_value
        return f"primitive {entry.dtype}={val!r}"
    return entry.type


def _print_chain_report(rep) -> int:
    """Render a delta-stream root (``resolve_chain``): member table,
    recovery head, torn tail. Exit 0 with a head, 3 with no members."""
    import datetime

    print(f"path:        {rep.root}")
    print("stream root: "
          f"{len(rep.members)} member(s), chain depth {len(rep.chain)}")
    for m in rep.members:
        mark = (
            "HEAD" if m.name == rep.head
            else "TORN" if m.state == "torn"
            else "????" if m.state == "debris"
            else "    "
        )
        when = (
            datetime.datetime.fromtimestamp(
                m.created_at, tz=datetime.timezone.utc
            ).isoformat(timespec="seconds")
            if m.created_at
            else "-"
        )
        seq = f"seq {m.seq}" if m.seq is not None else "  -  "
        print(
            f"  {mark}  {m.name:<16s} {m.state:<10s} {seq:<8s} "
            f"{_fmt_bytes(m.payload_bytes):>10s}  {when}"
        )
        # Elastic-stream forensics: the participating world of the
        # epoch (size + joins/leaves vs the previous epoch), degraded
        # commits (who died, who adopted), and — for a torn multi-rank
        # epoch — whose journal evidence is missing.
        bits = []
        w = m.world
        if w and w.get("size"):
            b = f"world {w['size']} (ranks {w.get('ranks')})"
            if w.get("joined"):
                b += f", joined {w['joined']}"
            if w.get("left"):
                b += f", left {w['left']}"
            if w.get("expired"):
                b += f", expired {w['expired']}"
            bits.append(b)
        if m.degraded:
            adopters = sorted(
                set((m.degraded.get("adopters") or {}).values())
            )
            bits.append(
                f"DEGRADED: rank(s) {m.degraded.get('dead_ranks')} died "
                f"mid-epoch; "
                f"{len(m.degraded.get('adopted_units') or [])} unit(s) "
                f"adopted by survivor(s) {adopters}"
            )
        if m.state == "torn" and m.missing_ranks:
            bits.append(
                "journal evidence missing from global rank(s) "
                f"{m.missing_ranks}"
            )
        for b in bits:
            print(f"        {b}")
    if rep.head:
        print(f"recovery:    restore {rep.head_path} "
              f"(replays {' + '.join(reversed(rep.chain))})")
    if rep.torn_tail:
        print(
            f"torn tail:   {rep.torn_tail} — a micro-commit was "
            "interrupted; recovery IGNORES it (`fsck`/`timeline` the "
            "member for the post-mortem, retake or `gc --torn` to "
            "reclaim)"
        )
    if rep.superseded:
        print(
            f"superseded:  {', '.join(rep.superseded)} (not referenced "
            "by the head — reclaimable via retention)"
        )
    if rep.debris:
        print(f"debris:      {', '.join(rep.debris)} (half-retired "
              "member dir(s) — reclaim manually)")
    return 0 if rep.head else 3


def cmd_info(args) -> int:
    from .inspect import iter_blobs

    try:
        md = Snapshot(args.path).metadata
    except RuntimeError:
        # Not a snapshot dir itself — a delta-stream ROOT holds chain
        # members one level down; render the chain view instead.
        from .delta import resolve_chain

        rep = resolve_chain(args.path)
        if rep.members:
            return _print_chain_report(rep)
        raise
    counts: dict = {}
    total = 0
    for p, e in md.manifest.items():
        if is_container_entry(e):
            continue
        counts[e.type] = counts.get(e.type, 0) + 1
        total += entry_nbytes(e)
    external = [b for b in iter_blobs(md.manifest) if b.location.startswith("../")]
    print(f"path:        {args.path}")
    print(f"version:     {md.version}")
    if md.created_at is not None:
        import datetime
        import time as _time

        ts = datetime.datetime.fromtimestamp(
            md.created_at, tz=datetime.timezone.utc
        )
        # Snapshot age IS the recovery-point floor: a crash right now
        # rewinds training at least this far.
        age = max(_time.time() - md.created_at, 0.0)
        print(
            f"created:     {ts.isoformat(timespec='seconds')} "
            f"({_fmt_age(age)} ago)"
        )
    print(f"world_size:  {md.world_size}")
    from .delta import delta_fields

    dfields = delta_fields(md)
    if dfields:
        parent = dfields.get("parent")
        print(
            f"delta:       micro-commit seq {dfields.get('seq')} of "
            f"stream {str(dfields.get('stream'))[:8]}"
            + (f", parent {parent}" if parent else " (stream base)")
            + " — `info` the stream root for the chain view"
        )
    print(f"payload:     {_fmt_bytes(total)}")
    print(f"entries:     {sum(counts.values())}")
    for t, c in sorted(counts.items()):
        print(f"  {t:14s} {c}")
    if external:
        from .inspect import base_root_of_location

        bases = sorted(
            {
                base_root_of_location(b.location, md.base_roots)
                for b in external
            }
        )
        print(
            f"external:    {len(external)} blob range(s) reference base "
            f"snapshot(s): {', '.join(bases)} — keep them alive (or "
            f"`materialize` to make this snapshot self-contained)"
        )
    # Telemetry rollup highlights (metadata.extras — no trace reads):
    # the take's headline numbers without a separate `trace` invocation.
    t = (md.extras or {}).get("telemetry")
    if t:
        wall = t.get("take_wall_s")
        bw = t.get("bytes_written") or 0
        if wall:
            line = f"take:        {_fmt_seconds(wall)}"
            if bw:
                line += f", {_fmt_bytes(bw)} written"
                if wall > 0:
                    line += f" ({bw / wall / 1e9:.2f} GB/s)"
            print(line)
        counters = t.get("counters") or {}
        notable = {
            "retries": t.get("retry_attempts") or 0,
            "stall episodes": counters.get("progress.stall_episodes", 0),
            "blobs salvaged": counters.get("salvage.blobs_salvaged", 0),
            "dedup skips": counters.get("scheduler.dedup_skipped", 0),
        }
        notes = [f"{v} {k}" for k, v in notable.items() if v]
        if notes:
            print(f"             {', '.join(notes)}")
        skew = t.get("phase_skew") or {}
        if (t.get("ranks") or 1) > 1 and skew:
            worst_name, worst = max(
                (
                    (name, agg)
                    for name, agg in skew.items()
                    if agg.get("skew")
                ),
                key=lambda kv: kv[1]["skew"],
                default=(None, None),
            )
            if worst is not None and worst["skew"] > 1.0:
                print(
                    f"skew:        {worst_name} rank {worst.get('max_rank')} "
                    f"at {_fmt_seconds(worst.get('max_s'))} "
                    f"({worst['skew']:.2f}x the p50) — "
                    "`trace` for the full breakdown"
                )
    # Write-back tier durability (tpusnap.tiering): first-class state
    # of a tiered snapshot's local tier, plus the restore-source label
    # the RTO estimate below is priced against.
    restore_backend = None
    try:
        from .tiering import parse_tier_url, tier_state_of_dir
        from .tiering import restore_source_label as _rsl

        spec = parse_tier_url(args.path)
        local_dir = spec.local_dir if spec is not None else args.path
        tier = tier_state_of_dir(local_dir)
        if tier:
            line = f"durability:  {tier['durability']}"
            if tier["durability"] == "local-committed":
                line += (
                    f" — {_fmt_bytes(tier.get('lag_bytes') or 0)} awaiting "
                    f"drain to {tier.get('remote')}"
                )
            elif tier.get("remote"):
                line += f" at {tier.get('remote')}"
            print(line)
            restore_backend = _rsl(args.path)
    except Exception:
        pass
    # Content-addressed store refs (tpusnap.cas): how much of this
    # snapshot's payload lives as shared-store refs instead of private
    # copies, and which store holds the blobs.
    try:
        from .cas import read_refs_dir, resolve_store_url
        from .tiering import parse_tier_url as _ptu

        _spec = _ptu(args.path)
        _dir = _spec.local_dir if _spec is not None else args.path
        cas_refs, cas_store = read_refs_dir(_dir)
        if cas_refs:
            dedup = sum(int(r[0]) for r in cas_refs.values())
            print(
                f"cas:         {len(cas_refs)} ref(s) into "
                f"{cas_store or resolve_store_url() or '(unknown store)'}"
            )
            print(
                f"             {_fmt_bytes(dedup)} deduplicated in the "
                f"store, {_fmt_bytes(max(total - dedup, 0))} materialized "
                "as private copies"
            )
    except Exception:
        pass
    # History-derived estimated restore time (the tpusnap.slo RTO
    # estimator over the rank-0 restore view): "how long until training
    # resumes from THIS snapshot" — best-effort, shown only when ≥3
    # comparable restore events exist on this host. Tiered snapshots
    # are priced against the tier a restore would actually read from.
    try:
        from .inspect import rank_payload_nbytes
        from .slo import estimate_rto

        est = estimate_rto(rank_payload_nbytes(md, 0), backend=restore_backend)
        if est.ok:
            src = getattr(est, "source", "history")
            print(
                f"est restore: {_fmt_seconds(est.seconds)} "
                f"({est.reason}"
                + (f", {restore_backend} {src}" if restore_backend else "")
                + "; `slo` for live exposure)"
            )
    except Exception:
        pass
    return 0


def cmd_ls(args) -> int:
    from .inspect import _entry_tensors

    md = Snapshot(args.path).metadata
    for p in sorted(md.manifest):
        e = md.manifest[p]
        if is_container_entry(e) and not args.all:
            continue
        if args.long:
            n = entry_nbytes(e)
            crc = "✓" if entry_verifiable(e) else " "
            ext = (
                "↗"
                if any(
                    t.location.startswith("../") for t in _entry_tensors(e)
                )
                else " "
            )
            print(f"{_fmt_bytes(n):>10s}  {crc}{ext}  {p}  [{_entry_desc(e)}]")
        else:
            print(p)
    return 0


def cmd_verify(args) -> int:
    report = verify_snapshot(args.path)
    for f in report.failures:
        print(
            f"CORRUPT  {f.manifest_path} ({f.location}"
            + (f", {f.detail}" if f.detail else "")
            + ")",
            file=sys.stderr,
        )
    if args.verbose:
        for u in report.unverified_blobs:
            print(f"UNVERIFIED  {u.manifest_path}: {u.detail}")
    print(report.summary())
    if not report.clean:
        return 2
    # "Nothing was verifiable" must not read as "verified clean" in
    # scripts (snapshot taken with TPUSNAP_DISABLE_CHECKSUM=1, or by a
    # build with a different checksum algorithm): exit 3, mirroring
    # diff's 3 = undecidable convention.
    if report.ok == 0 and report.unverified > 0:
        print(
            "nothing verified: no blob carries a checksum this build can "
            "check",
            file=sys.stderr,
        )
        return 3
    return 0


def cmd_materialize(args) -> int:
    from .inspect import materialize_snapshot

    stats = materialize_snapshot(args.path)
    if stats["blobs_copied"] == 0:
        print("already self-contained (no external references)")
    else:
        print(
            f"copied {stats['blobs_copied']} blob(s), "
            f"{_fmt_bytes(stats['bytes_copied'])}; snapshot is now "
            "self-contained"
        )
    return 0


def cmd_diff(args) -> int:
    from .inspect import diff_snapshots

    d = diff_snapshots(args.path_a, args.path_b)
    if not args.quiet:
        for tag, paths in (
            ("~", d.changed),
            ("+", d.added),
            ("-", d.removed),
            ("?", d.unknown),
        ):
            for p in paths:
                print(f"{tag} {p}")
    print(d.summary())
    # 0 = provably identical, 2 = provably different, 3 = undecidable
    # (missing checksums / incomparable layouts) — so scripts can't
    # mistake "couldn't compare" for either verdict.
    if d.differs:
        return 2
    return 0 if d.same else 3


def cmd_retain(args) -> int:
    from .retention import apply_retention

    plan = apply_retention(args.root, args.keep, dry_run=args.dry_run)
    would = "" if plan.executed else "would "
    for s in plan.materialize:
        print(f"{would}materialize {s}")
    for s in plan.delete:
        print(f"{would}delete {s}")
    print(plan.summary())
    return 0


def cmd_fsck(args) -> int:
    from .lifecycle import fsck_snapshot

    if getattr(args, "store", False):
        # Store-wide mode. Exit contract: 0 = clean or merely
        # reclaimable (orphans and torn publishes are NORMAL crash
        # debris gc converges, not corruption); 4 = dangling ref(s) —
        # a committed snapshot references a blob the store no longer
        # holds, restore-breaking; 3 = not a store.
        from .cas import fsck_store

        srep = fsck_store(args.path)
        print(srep.summary())
        if srep.state != "store":
            print(f"error: {srep.detail}", file=sys.stderr)
            return 3
        if args.verbose:
            for d in srep.dangling:
                print(
                    f"DANGLING {d['key']}  ref'd as {d['location']!r} "
                    f"by root {d['root']}"
                )
            for k, sz in sorted(srep.orphans.items()):
                print(f"ORPHAN   {_fmt_bytes(sz):>10s}  blobs/{k}")
            for p in srep.torn_publishes:
                print(f"TORN     {p}")
            for p in srep.stale_roots:
                print(f"STALE    {p}  (snapshot dir gone)")
            for k in srep.refcount_divergence:
                print(f"DIVERGED refcounts.json[{k}] != mark count")
        return 4 if srep.dangling else 0

    report = fsck_snapshot(args.path)
    if report.state in ("foreign", "empty"):
        # Not a take dir itself — a delta-stream ROOT holds classifiable
        # members one level down: fan the classification out per member
        # and grade the chain (torn tail → 4, healthy head → 0).
        from .delta import resolve_chain

        rep = resolve_chain(args.path)
        if any(m.seq is not None for m in rep.members):
            rc = _print_chain_report(rep)
            if rep.torn_tail:
                return 4
            return rc
    print(report.summary())
    if report.journal is not None and report.state == "torn":
        import datetime

        ts = datetime.datetime.fromtimestamp(
            report.journal.started_at, tz=datetime.timezone.utc
        )
        print(f"  take started: {ts.isoformat(timespec='seconds')}")
        if report.journal.incremental_from:
            print(f"  incremental_from: {report.journal.incremental_from}")
        if report.delta:
            print(
                f"  delta: torn micro-commit seq {report.delta.get('seq')} "
                f"over {report.delta.get('parent')!r} — recovery lands on "
                "the last committed increment (`fsck` the stream root)"
            )
        # Rank-failure attribution: when the survivors' black boxes
        # recorded a lease expiry, the torn verdict NAMES the dead
        # rank(s) — "rank 2 died" beats "something tore" at 2 a.m.
        try:
            from .flight import load_flight_logs

            logs = load_flight_logs(args.path, files=report.files)
            take_id = report.journal.take_id
            dead = sorted(
                {
                    e.get("rank")
                    for doc in logs.values()
                    if (doc.get("meta") or {}).get("take_id")
                    in (None, take_id)
                    for e in doc.get("events") or []
                    if e.get("k") == "rank_dead"
                    and isinstance(e.get("rank"), int)
                }
            )
            if dead:
                print(
                    f"  dead rank(s) (lease expired): {dead} — the "
                    "survivors observed the rank die; `tpusnap timeline` "
                    "has the full post-mortem"
                )
        except Exception:
            pass
    if args.verbose:
        for p in report.missing_referenced:
            print(f"MISSING  {p}")
        for p in report.cas_dangling:
            print(
                f"DANGLING {p}  (CAS ref into {report.cas_store}; the "
                "store no longer holds the blob)"
            )
        for p in report.evicted:
            print(f"EVICTED  {p}  (remote-durable; restorable from "
                  f"{report.tier_remote})")
        for p, sz in sorted(report.orphans.items()):
            print(f"ORPHAN   {_fmt_bytes(sz):>10s}  {p}")
    # committed→0; corrupt-metadata→2 (corruption, like verify); torn→4
    # (salvageable — retake the path or `gc --torn`); a committed
    # snapshot with DANGLING CAS refs→4 (the shared store lost blobs it
    # needs — `fsck --store` the store for the other side of the
    # verdict); empty/foreign→3 (nothing tpusnap-shaped to check).
    if report.state == "committed":
        if report.cas_dangling:
            return 4
        return 2 if report.missing_referenced else 0
    if report.state == "corrupt-metadata":
        return 2
    if report.state == "torn":
        return 4
    return 3


def cmd_drain(args) -> int:
    import json as _json

    from .tiering import (
        drain_snapshot,
        parse_tier_url,
        tier_state_of_dir,
    )

    if getattr(args, "store", False):
        # Store-wide drain: upload every blob to the store's remote
        # mirror once store-wide, journaled by hash (a crashed drain
        # skips everything already proven remote on re-run).
        from .cas import drain_store

        srep = drain_store(args.path, remote_url=args.remote)
        for err in srep.errors:
            print(f"error: {err}", file=sys.stderr)
        print(srep.summary())
        if srep.state == "durable":
            return 0
        return 3 if srep.state == "no-remote" else 2

    spec = parse_tier_url(args.path)
    local_dir = spec.local_dir if spec is not None else args.path

    if args.status:
        state = tier_state_of_dir(local_dir)
        if state is None:
            print(
                f"error: {local_dir!r} carries no upload journal — not a "
                "write-back tiered snapshot (or the drain never started)",
                file=sys.stderr,
            )
            return 3
        if args.json:
            print(_json.dumps({"path": local_dir, **state}))
        else:
            print(f"path:        {local_dir}")
            print(f"remote:      {state.get('remote')}")
            print(f"durability:  {state.get('durability')}")
            print(
                f"lag:         {_fmt_bytes(state.get('lag_bytes') or 0)} "
                f"across {state.get('pending_blobs') or 0} blob(s) "
                f"({state.get('evidenced_blobs') or 0} proven remote)"
            )
        return 0 if state.get("durability") == "remote-durable" else 2

    report = drain_snapshot(
        args.path,
        remote_url=args.remote,
        deadline_s=args.timeout,
    )
    if args.json:
        print(_json.dumps(report.to_json()))
    else:
        for base in report.bases:
            print(f"base: {base.summary()}")
        print(report.summary())
    # 0 = remote-durable; 2 = did not converge (outage/degraded — retry
    # later, the journal resumes); 3 = nothing drainable at the path.
    if report.state == "durable":
        return 0
    if report.state == "no-metadata":
        print(f"error: {report.error}", file=sys.stderr)
        return 3
    return 2


def cmd_gc(args) -> int:
    from .lifecycle import gc_snapshot

    if getattr(args, "store", False):
        # Store-wide mark-and-sweep (dry-run unless --force): blobs
        # referenced by any live root's ref records — or named by a
        # publish intent younger than the grace window — survive;
        # everything else past the grace window is swept under the
        # per-store lock lease.
        from .cas import gc_store

        srep = gc_store(args.path, dry_run=not args.force)
        would = "" if args.force else "would "
        for p, sz in sorted(srep.reclaimed.items()):
            print(f"{would}delete  {_fmt_bytes(sz):>10s}  {p}")
        for err in srep.errors:
            print(f"error: {err}", file=sys.stderr)
        print(srep.summary())
        return 1 if srep.errors else 0

    report = gc_snapshot(
        args.path,
        dry_run=not args.force,
        reclaim_torn=args.torn,
        evict_local=args.evict_local,
    )
    would = "" if args.force else "would "
    for p, sz in sorted(report.reclaimed.items()):
        print(f"{would}delete  {_fmt_bytes(sz):>10s}  {p}")
    for err in report.errors:
        print(f"error: {err}", file=sys.stderr)
    print(report.summary())
    return 1 if report.errors else 0


def _fmt_seconds(s) -> str:
    if s is None:
        return "-"
    if s >= 1.0:
        return f"{s:.2f}s"
    return f"{s * 1e3:.1f}ms"


def _render_trace(args, rollup, summaries, ranks, world_size, label) -> int:
    import json as _json

    if args.json:
        print(
            _json.dumps(
                {
                    "path": args.path,
                    "kind": label,
                    "world_size": world_size,
                    "rollup": rollup,
                    "ranks": {str(r): s for r, s in sorted(summaries.items())},
                }
            )
        )
        return 0

    print(f"path:         {args.path}")
    print(f"world_size:   {world_size}")
    print(f"traced ranks: {sorted(ranks) if ranks else '(rollup only)'}")
    multi = bool(rollup) and rollup.get("ranks", 1) > 1
    if rollup:
        print(
            f"{label} wall-clock (slowest rank): "
            f"{_fmt_seconds(rollup.get('take_wall_s'))}"
        )
        cov = rollup.get("phase_coverage_min")
        if cov is not None:
            print(f"phase coverage of wall-clock:   {cov * 100:.1f}%")
        stages = rollup.get("stages") or {}
        if stages:
            head = f"\n{'stage':<24s} {'ranks':>5s} {'p50':>10s} {'max':>10s}"
            print(head + ("  max@" if multi else ""))
            for name, agg in stages.items():
                line = (
                    f"{name:<24s} {agg.get('ranks', 0):>5d} "
                    f"{_fmt_seconds(agg.get('p50_s')):>10s} "
                    f"{_fmt_seconds(agg.get('max_s')):>10s}"
                )
                if multi and agg.get("max_rank") is not None:
                    line += f"  r{agg['max_rank']}"
                print(line)
        # Straggler attribution: the slowest rank per PHASE and how far
        # behind the median it was (the skew the stall watchdog's live
        # warnings pointed at, made durable).
        skew = rollup.get("phase_skew") or {}
        if multi and skew:
            print("\nstragglers (slowest rank per phase):")
            for name, agg in skew.items():
                if not agg.get("max_s"):
                    continue
                ratio = agg.get("skew")
                print(
                    f"  {name:<22s} rank {agg.get('max_rank')} at "
                    f"{_fmt_seconds(agg.get('max_s'))}"
                    + (f" ({ratio:.2f}x the p50)" if ratio else "")
                )
        counters = rollup.get("counters") or {}
        if counters:
            print("\ncounters (summed over ranks):")
            for name, v in sorted(counters.items()):
                print(f"  {name} = {v}")
        bw = rollup.get("bytes_written")
        if bw:
            print(f"\nbytes written:     {_fmt_bytes(bw)}")
        br = (rollup.get("counters") or {}).get("storage.bytes_read")
        if br:
            print(f"bytes read:        {_fmt_bytes(br)}")
        hw = rollup.get("budget_high_water_bytes")
        if hw:
            print(f"budget high-water: {_fmt_bytes(int(hw))}")
        rss = rollup.get("peak_rss_delta_bytes")
        if rss:
            print(f"peak RSS delta:    {_fmt_bytes(int(rss))}")
    if args.rank is not None:
        s = summaries.get(args.rank)
        if s is None:
            print(f"error: no trace for rank {args.rank}", file=sys.stderr)
            return 1
        print(
            f"\nrank {args.rank} stages "
            f"(wall {_fmt_seconds(s.get('take_wall_s'))}, "
            f"coverage {s.get('phase_coverage', 0) * 100:.1f}%):"
        )
        print(f"{'stage':<24s} {'count':>6s} {'total':>10s} {'p50':>10s} {'max':>10s}")
        for name, agg in (s.get("stages") or {}).items():
            print(
                f"{name:<24s} {agg.get('count', 0):>6d} "
                f"{_fmt_seconds(agg.get('total_s')):>10s} "
                f"{_fmt_seconds(agg.get('p50_s')):>10s} "
                f"{_fmt_seconds(agg.get('max_s')):>10s}"
            )
    return 0


def _load_take_traces(path: str):
    """(world_size, rollup-or-None, {rank: trace doc}) for a committed
    snapshot — the shared loader behind ``trace`` and ``analyze``."""
    import json as _json

    from .io_types import ReadIO
    from .telemetry import telemetry_rank_path

    snap = Snapshot(path)
    md = snap.metadata
    rollup = (md.extras or {}).get("telemetry")
    ranks: dict = {}
    with snap._op_lock:
        event_loop, storage = snap._resources()
        for rank in range(md.world_size):
            read_io = ReadIO(path=telemetry_rank_path(rank))
            try:
                storage.sync_read(read_io, event_loop)
                ranks[rank] = _json.loads(read_io.buf.getvalue().decode("utf-8"))
            except Exception:
                continue  # telemetry disabled on this rank, or pre-telemetry snapshot
    return md.world_size, rollup, ranks


def _load_restore_docs(path: str):
    """{rank: trace doc} for the last restore of ``path`` from the
    local telemetry dir, or None (with the explanation printed) when
    nothing was recorded."""
    from .progress import load_restore_traces, restore_trace_dir

    docs = load_restore_traces(path)
    if not docs:
        print(
            "no restore telemetry recorded for this path (no restore "
            "ran from this machine, TPUSNAP_TELEMETRY=0, or a "
            f"different TPUSNAP_TELEMETRY_DIR — looked in "
            f"{restore_trace_dir(path)})",
            file=sys.stderr,
        )
        return None
    return docs


_NO_TELEMETRY_MSG = (
    "no telemetry recorded (taken with TPUSNAP_TELEMETRY=0, or a "
    "pre-telemetry snapshot)"
)


def cmd_trace(args) -> int:
    from .telemetry import rollup_summaries

    if args.restore:
        docs = _load_restore_docs(args.path)
        if docs is None:
            return 3
        summaries = {r: d.get("summary") or {} for r, d in docs.items()}
        rollup = rollup_summaries(list(summaries.values()))
        return _render_trace(
            args, rollup, summaries, sorted(docs), len(docs), "restore"
        )

    world_size, rollup, ranks = _load_take_traces(args.path)
    summaries = {r: d.get("summary") or {} for r, d in ranks.items()}
    if rollup is None and summaries:
        rollup = rollup_summaries(list(summaries.values()))
    # "No telemetry" covers both the pre-telemetry snapshot (no rollup,
    # no traces) and the knob-off take (always-on counters rolled up,
    # but zero spans anywhere): an empty stage table helps nobody —
    # explain and exit with the dedicated code instead.
    has_spans = bool((rollup or {}).get("stages")) or any(
        s.get("stages") for s in summaries.values()
    )
    if not summaries and not has_spans:
        print(_NO_TELEMETRY_MSG, file=sys.stderr)
        return 3
    return _render_trace(
        args, rollup, summaries, sorted(ranks), world_size, "take"
    )


def _render_analyze(path: str, report: dict) -> None:
    kind = report.get("kind", "take")
    print(f"path:   {path}")
    att = report.get("attribution")
    if report.get("bound_by"):
        print(
            f"\nBOUND BY: {report['bound_by']} "
            f"({report.get('bound_pct', 0):.1f}% of {kind} wall-clock, "
            f"rank {report.get('rank')})"
        )
        if report.get("advice"):
            print(f"  → {report['advice']}")
    if att:
        wall = att.get("wall_s") or 0.0
        print(
            f"\nattribution (rank {report.get('rank')}, "
            f"wall {_fmt_seconds(wall)}, "
            f"coverage {att.get('coverage', 0) * 100:.1f}%):"
        )
        print(f"{'resource':<16s} {'attributed':>11s} {'%':>6s} {'busy':>10s}")
        pct = att.get("attributed_pct") or {}
        busy = att.get("busy_s") or {}
        for cat, secs in sorted(
            (att.get("attributed_s") or {}).items(),
            key=lambda kv: -kv[1],
        ):
            print(
                f"{cat:<16s} {_fmt_seconds(secs):>11s} "
                f"{pct.get(cat, 0):>5.1f}% "
                f"{_fmt_seconds(busy.get(cat)):>10s}"
            )
        ua = att.get("unattributed_s") or 0.0
        if wall > 0:
            print(
                f"{'(unattributed)':<16s} {_fmt_seconds(ua):>11s} "
                f"{100.0 * ua / wall:>5.1f}%"
            )
    hist = report.get("io_histograms")
    if hist:
        print("\nstorage-boundary latency (log2 histograms, all ranks):")
        print(
            f"{'op.plugin':<28s} {'count':>6s} {'p50':>9s} {'p95':>9s} "
            f"{'p99':>9s} {'max':>9s}"
        )
        for key, st in sorted(hist.items()):
            print(
                f"{key:<28s} {st.get('count', 0):>6d} "
                f"{_fmt_seconds(st.get('p50_s')):>9s} "
                f"{_fmt_seconds(st.get('p95_s')):>9s} "
                f"{_fmt_seconds(st.get('p99_s')):>9s} "
                f"{_fmt_seconds(st.get('max_s')):>9s}"
            )
    if report.get("roofline_fraction") is not None:
        line = f"\nroofline: {report['roofline_fraction']:.1%} of the in-take probe ceiling"
        probe = report.get("probe") or {}
        if probe.get("write_gbps_p50"):
            line += (
                f" ({probe['write_gbps_p50']:.2f} GB/s over "
                f"{probe.get('probes', 0)} probe(s))"
            )
        print(line)
    if report.get("restore_roofline_fraction") is not None:
        line = (
            f"\nread roofline: {report['restore_roofline_fraction']:.1%} "
            "of the in-restore probe READ ceiling"
        )
        probe = report.get("probe") or {}
        if probe.get("read_gbps_p50"):
            line += (
                f" ({probe['read_gbps_p50']:.2f} GB/s over "
                f"{probe.get('probes', 0)} probe(s))"
            )
        print(line)
    acc = report.get("access")
    if acc and acc.get("bytes_read"):
        print(
            f"\naccess: {acc.get('n_readers', 0)} reader(s), "
            f"{_fmt_bytes(acc.get('bytes_read') or 0)} read over "
            f"{_fmt_bytes(acc.get('snapshot_bytes') or 0)} stored — "
            f"coverage {(acc.get('coverage') or 0) * 100:.1f}%, "
            f"amplification {(acc.get('amplification') or 0):.2f}x "
            "(`tpusnap heatmap` for the per-leaf view)"
        )
    trend = report.get("history")
    if trend and trend.get("events"):
        print(f"\nhistory trend (last {trend['events']} {kind} event(s)):")
        for metric, agg in trend.items():
            if not isinstance(agg, dict):
                continue
            print(
                f"  {metric}: latest {agg.get('latest')} vs median "
                f"{agg.get('median')} (n={agg.get('n')})"
            )
    findings = report.get("findings") or []
    if findings:
        print("\nfindings:")
        for f in findings:
            print(f"  [{f['severity'].upper()}] {f['message']}")
    else:
        print("\nfindings: none — no gate-worthy anomalies")


def cmd_analyze(args) -> int:
    import json as _json

    from .analyze import Thresholds, analyze
    from .telemetry import rollup_summaries

    thresholds = Thresholds(
        p99_ratio=args.p99_ratio,
        min_roofline=args.min_roofline,
        min_read_roofline=args.min_read_roofline,
        max_skew=args.max_skew,
    )
    history_events = None
    if args.history:
        from .history import load_history

        history_events = load_history()
    if args.restore:
        docs = _load_restore_docs(args.path)
        if docs is None:
            return 3
        rank_docs = docs
        rollup = rollup_summaries(
            [d.get("summary") or {} for d in docs.values()]
        )
        kind = "restore"
    else:
        try:
            _world, rollup, rank_docs = _load_take_traces(args.path)
        except Exception:
            # Not a committed snapshot. A torn/killed/aborted path has
            # no telemetry rollup to analyze — but it usually has a
            # black box: fold the flight recorder's post-mortem verdict
            # in instead of a bare load error.
            report, logs, verdict = _load_flight_view(args.path)
            if report.state == "committed":
                raise  # a committed snapshot failing to load is a real error
            if not logs:
                if report.state in ("empty", "foreign"):
                    # Nothing tpusnap-shaped here at all — a typo'd
                    # path must surface the original load error (exit
                    # 1), not a misleading "flight recording was off".
                    raise
                print(_NO_FLIGHT_MSG, file=sys.stderr)
                return 3
            if args.json:
                print(
                    _json.dumps(
                        {
                            "path": args.path,
                            "state": report.state,
                            "verdict": verdict,
                        }
                    )
                )
            else:
                print(f"path:   {args.path}")
                print(
                    f"state:  {report.state} — not a committed snapshot; "
                    "per-phase analysis needs a committed trace"
                )
                _render_verdict(verdict)
                print(
                    "\n(`python -m tpusnap timeline` shows the merged "
                    "cross-rank event timeline)"
                )
            return 4
        if rollup is None and rank_docs:
            rollup = rollup_summaries(
                [d.get("summary") or {} for d in rank_docs.values()]
            )
        kind = "take"
    # Zero spans anywhere (knob-off take OR pre-telemetry snapshot):
    # there is nothing to attribute — one-liner + exit 3, matching
    # `trace`.
    has_spans = bool((rollup or {}).get("stages")) or any(
        (d.get("summary") or {}).get("stages") for d in rank_docs.values()
    )
    if not rank_docs or not has_spans:
        print(_NO_TELEMETRY_MSG, file=sys.stderr)
        return 3
    # Access heatmap context (best-effort): when readers left ledgers
    # for this snapshot, fold coverage/amplification into the report —
    # the partial_access advice needs both the ledgers and the manifest.
    heatmap = None
    try:
        from . import access

        _recs = access.load_ledger_records(args.path)
        if _recs:
            heatmap = access.compute_heatmap(
                _recs, _heatmap_metadata(args.path)
            )
    except Exception:
        heatmap = None
    report = analyze(
        rollup,
        rank_docs,
        kind=kind,
        thresholds=thresholds,
        history_events=history_events,
        heatmap=heatmap,
    )
    if args.json:
        print(_json.dumps({"path": args.path, **report}))
    else:
        _render_analyze(args.path, report)
    if args.check and report.get("check_failed"):
        return 2
    return 0


def cmd_tune(args) -> int:
    import json as _json

    from . import compress
    from .history import history_path, load_history
    from .tune import build_plan

    path = args.file or history_path()
    events = load_history(path)
    kind = args.kind
    if kind is None:
        # Default cell: whatever this host did last.
        kind = next(
            (
                e.get("kind")
                for e in reversed(events)
                if e.get("kind") in ("take", "restore")
            ),
            "take",
        )
    # Best-effort bound verdict from persisted traces (--snapshot):
    # absence degrades the plan (verdict-driven rules skip), never
    # fails it.
    verdict = None
    if args.snapshot:
        try:
            from .analyze import analyze
            from .telemetry import rollup_summaries

            if kind == "restore":
                from .progress import load_restore_traces

                docs = load_restore_traces(args.snapshot)
            else:
                _w, _roll, docs = _load_take_traces(args.snapshot)
            if docs:
                roll = rollup_summaries(
                    [d.get("summary") or {} for d in docs.values()]
                )
                verdict = analyze(roll, docs, kind=kind).get("bound_by")
        except Exception:
            verdict = None
    plan = build_plan(
        events,
        kind,
        backend=args.backend,
        world_size=args.world_size,
        ceilings=compress.pipe_ceilings_snapshot(),
        verdict=verdict,
        window=args.window,
    )
    if args.json:
        print(_json.dumps({"history": path, **plan.to_json()}))
    elif args.env:
        if plan.ok:
            print(f"# tune plan {plan.plan_id}: {plan.reason}")
            for line in plan.env_exports():
                print(line)
        else:
            print(f"# no plan: {plan.reason}")
    else:
        cell = (
            f"backend={plan.backend or 'any'} kind={plan.kind} "
            f"world_size={plan.world_size or 'any'}"
        )
        if not plan.ok:
            print(f"cell:    {cell}")
            print(f"no plan: {plan.reason}")
        else:
            print(f"plan:    {plan.plan_id}")
            print(f"cell:    {cell}")
            print(
                f"evidence: {plan.n_events} event(s)"
                + (f", bound verdict {plan.verdict!r}" if plan.verdict else "")
            )
            if not plan.knobs:
                print(f"\n{plan.reason}")
            else:
                print(f"\n{'knob':<42s} {'current':>14s} {'planned':>14s}")
                for k in plan.knobs:
                    print(
                        f"{k.env:<42s} {(k.current or '(default)'):>14s} "
                        f"{k.value:>14s}"
                    )
                    print(f"    {k.rationale}")
                print(
                    "\napply: eval \"$(python -m tpusnap tune --env)\" — or "
                    "set TPUSNAP_AUTOTUNE=1 to reconcile at take/restore "
                    "begin (explicit env vars always win)"
                )
    if not plan.ok:
        return 3
    return 0


_NO_FLIGHT_MSG = (
    "no flight data recorded (TPUSNAP_FLIGHT=0, a pre-flight-recorder "
    "snapshot, or the take died before its first flush)"
)


def _fmt_rel_bytes(n) -> str:
    return _fmt_bytes(int(n)) if n else "0B"


def _flight_verdict(path: str, fsck_report, logs, resources=None) -> dict:
    """The post-mortem verdict for an uncommitted path (shared by
    ``timeline`` and ``analyze``)."""
    from .flight import _journal_evidence, postmortem_verdict

    world = None
    if fsck_report.journal is not None:
        world = fsck_report.journal.world_size
    elif fsck_report.metadata is not None:
        world = fsck_report.metadata.world_size
    evidence = _journal_evidence(fsck_report.files, path, resources=resources)
    return postmortem_verdict(
        path, fsck_report.state, logs, world_size=world,
        journal_evidence=evidence,
    )


def _load_flight_view(path: str):
    """(fsck_report, logs, verdict_or_None) for ``path``, read through
    ONE storage plugin + event loop — the shared orchestration behind
    ``timeline`` and ``analyze``'s uncommitted-path fold.

    Stale-sidecar filter: a torn take's journal names the current
    take_id; flight logs left by a PREVIOUS take to the same path (a
    retake overwrites only the ranks it runs) would otherwise merge
    into the verdict as live ranks — and their recurring barrier anchor
    strings would poison the skew estimate across takes. Logs whose
    header names a different take are dropped (headerless logs are
    kept, best-effort); the filtered-out ranks then correctly show as
    missing."""
    import asyncio

    from .flight import load_flight_logs
    from .lifecycle import fsck_snapshot
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(path, event_loop)
        try:
            resources = (event_loop, storage)
            report = fsck_snapshot(path, resources=resources)
            logs = load_flight_logs(
                path, files=report.files, resources=resources
            )
            expected = (
                report.journal.take_id if report.journal is not None else None
            )
            if expected is None and logs:
                # Committed path: rank 0 participates in every take and
                # its sidecar is rewritten by the committing take, so
                # its header names the current take — leftover sidecars
                # from a wider previous take must not merge in (their
                # recurring barrier anchor strings would also poison
                # the skew estimate across takes).
                ref = logs.get(min(logs)) or {}
                expected = (ref.get("meta") or {}).get("take_id")
            if expected:
                logs = {
                    rank: doc
                    for rank, doc in logs.items()
                    if (doc.get("meta") or {}).get("take_id")
                    in (None, expected)
                }
            verdict = (
                _flight_verdict(path, report, logs, resources=resources)
                if report.state != "committed" and logs
                else None
            )
        finally:
            storage.sync_close(event_loop)
    finally:
        event_loop.close()
    return report, logs, verdict


def _render_verdict(verdict: dict) -> None:
    print(f"\nPOST-MORTEM (state: {verdict['state']}):")
    for rank, r in sorted(verdict["ranks"].items()):
        ops = r.get("inflight_ops") or []
        op = r.get("inflight_op")
        op_desc = op or "-"
        if op and len(ops) > 1:
            op_desc += f" (+{len(ops) - 1} more in flight)"
        print(
            f"  rank {rank}: state={r.get('state', '?')}  "
            f"phase={r.get('phase') or '-'}  in-flight op={op_desc}"
        )
        planned = r.get("bytes_planned")
        if planned:
            pct = r.get("percent")
            print(
                f"          bytes: {_fmt_rel_bytes(r.get('bytes_written'))} "
                f"written / {_fmt_rel_bytes(planned)} planned"
                + (f" ({pct:.1f}%)" if pct is not None else "")
                + f", {_fmt_rel_bytes(r.get('bytes_staged'))} staged"
            )
        j = r.get("journal")
        if j:
            print(
                f"          journal evidence: {j['blobs_completed']} "
                f"blob(s) fully written "
                f"({_fmt_rel_bytes(j['bytes_completed'])} intact on disk)"
            )
        last = r.get("last_event")
        if last:
            age = last.get("flush_age_s")
            print(
                f"          last event: {last.get('k')} "
                f"{last.get('op') or ''}".rstrip()
                + (
                    f", {age:.2f}s before the final flush (up to one "
                    "flush interval of newer events died with the "
                    "process)"
                    if age is not None
                    else ""
                )
            )
        if r.get("dropped"):
            print(
                f"          ring evicted {r['dropped']} older event(s) "
                "(raise TPUSNAP_FLIGHT_RING for longer black boxes)"
            )
    for rank in verdict.get("missing_ranks", []):
        print(
            f"  rank {rank}: NO FLIGHT DATA — killed before its first "
            "flush, a non-local destination, or the host died with its "
            "telemetry dir"
        )
    left = verdict.get("left_ranks")
    if left:
        print(
            f"  LEFT rank(s) {left}: departed GRACEFULLY (terminal "
            "'left' lease/membership state) — not a failure; the "
            "remaining ranks re-planned without them"
        )
    dead = verdict.get("dead_ranks")
    if dead:
        print(
            f"  DEAD rank(s) {dead}: liveness lease expired — the "
            "survivors observed these ranks die (SIGKILL/host loss), "
            "which is why the take never committed"
        )
    stalls = verdict.get("stall_episodes", 0)
    print(f"  stall episodes across ranks: {stalls}")


def cmd_timeline(args) -> int:
    from .flight import estimate_skew, merge_timeline

    report, logs, verdict = _load_flight_view(args.path)
    if not logs:
        print(_NO_FLIGHT_MSG, file=sys.stderr)
        return 3
    skew = estimate_skew(logs)
    events = merge_timeline(logs, skew)
    t0 = events[0]["wall"] if events else 0.0
    shown = events
    if args.rank is not None:
        shown = [e for e in shown if e["rank"] == args.rank]
    if args.around is not None:
        lo, hi = args.around - args.window, args.around + args.window
        shown = [e for e in shown if lo <= e["wall"] - t0 <= hi]
    if args.last:
        shown = shown[-args.last :]
    if args.json:
        import json as _json

        print(
            _json.dumps(
                {
                    "path": args.path,
                    "state": report.state,
                    "durability": report.durability,
                    "delta": report.delta,
                    "ranks": sorted(logs),
                    "skew": {str(r): s for r, s in sorted(skew.items())},
                    "events": shown,
                    "verdict": verdict,
                }
            )
        )
    else:
        print(f"path:   {args.path}")
        print(f"state:  {report.state} (fsck)")
        if report.cas_refs:
            # CAS verdict line: a post-mortem must say whether the
            # shared store still backs this snapshot's refs — a
            # dangling ref is restore-breaking regardless of how
            # cleanly the take itself committed.
            print(
                f"cas:    {report.cas_refs} ref(s) into "
                f"{report.cas_store}"
                + (
                    f" — {len(report.cas_dangling)} DANGLING "
                    "(the store lost blob(s); `fsck --store` it)"
                    if report.cas_dangling
                    else " (all blobs present in the store)"
                )
            )
        if report.durability is not None:
            # Write-back tiering: a committed-but-local-only snapshot is
            # one host failure away from losing its only copy — the
            # post-mortem must say which side of that line it died on.
            print(
                f"tier:   {report.durability}"
                + (
                    f" — cloud drain to {report.tier_remote} pending "
                    "(`tpusnap drain` resumes it)"
                    if report.durability == "local-committed"
                    else (
                        f" at {report.tier_remote}"
                        if report.tier_remote
                        else ""
                    )
                )
            )
        if report.delta:
            parent = report.delta.get("parent")
            print(
                f"delta:  micro-commit seq {report.delta.get('seq')} of "
                f"stream {str(report.delta.get('stream'))[:8]}"
                + (f" over {parent}" if parent else "")
                + (
                    " — IN FLIGHT when the lights went out; recovery "
                    "lands on the last committed increment"
                    if report.state == "torn"
                    else ""
                )
            )
        print(f"ranks:  {sorted(logs)} with flight data")
        multi = len(logs) > 1
        if multi:
            print("clock alignment (barrier-anchored, relative to the "
                  "lowest rank):")
            for r, s in sorted(skew.items()):
                if s.get("anchors") is None:
                    continue  # the reference rank
                if s["anchors"]:
                    print(
                        f"  rank {r}: {s['offset_s'] * 1e3:+.2f}ms "
                        f"±{s['bound_s'] * 1e3:.2f}ms "
                        f"({s['anchors']} shared barrier anchor(s))"
                    )
                else:
                    print(
                        f"  rank {r}: no shared barrier anchors — "
                        "wall-clock ordering only"
                    )
        print(
            f"\ntimeline ({len(shown)} of {len(events)} event(s); "
            "+seconds since the first):"
        )
        for e in shown:
            extra = " ".join(
                f"{k}={v}"
                for k, v in e.items()
                if k not in ("t", "k", "op", "rank", "wall") and v is not None
            )
            print(
                f"  {e['wall'] - t0:+10.3f}s  r{e['rank']}  "
                f"{e['k']:<14} {e.get('op') or '-'}"
                + (f"  [{extra}]" if extra else "")
            )
        if verdict is not None:
            _render_verdict(verdict)
    if report.state == "committed":
        return 0
    return 4


def cmd_watch(args) -> int:
    import json as _json
    import os
    import time

    if args.fleet:
        return _watch_fleet(args)
    if not args.path:
        print(
            "error: watch needs a snapshot PATH (or --fleet to tail "
            "the cross-job fleet directory)",
            file=sys.stderr,
        )
        return 1

    from .progress import (
        local_root_of,
        read_progress_records,
        render_watch_table,
    )

    from .io_types import PROGRESS_DIR

    root = local_root_of(args.path)
    if root is None:
        print(
            f"error: {args.path!r} is not a local filesystem path — "
            f"`watch` tails the local heartbeat files under "
            f"{PROGRESS_DIR}/",
            file=sys.stderr,
        )
        return 1
    deadline = (
        time.monotonic() + args.max_seconds if args.max_seconds else None
    )
    seen_records = False
    commit_seen_at = None
    prev_lines = 0
    interactive = sys.stdout.isatty() and not args.once and not args.json
    # Tier-lag cache: tier_state_of_dir walks the whole payload tree;
    # recompute only when the upload journal actually changed (evidence
    # appends / durable marker) instead of per frame.
    from .io_types import UPLOAD_JOURNAL_PATH

    tier_cache = {"stat": None, "state": None}
    while True:
        records = read_progress_records(root)
        committed = os.path.exists(os.path.join(root, ".snapshot_metadata"))
        if records:
            seen_records = True
        if args.json:
            print(
                _json.dumps(
                    {"records": records, "metadata_committed": committed}
                )
            )
            return 0 if records else 3
        frame = render_watch_table(
            records, committed, stall_flag_s=args.stall_flag
        )
        # Write-back tiering: the drain's exposure line — a committed
        # take is not cloud-durable until the lag reaches zero.
        try:
            from .tiering import tier_state_of_dir

            st = os.stat(os.path.join(root, UPLOAD_JOURNAL_PATH))
            key = (st.st_mtime_ns, st.st_size)
            if key != tier_cache["stat"]:
                tier_cache["stat"] = key
                tier_cache["state"] = tier_state_of_dir(root)
            tier = tier_cache["state"]
        except Exception:
            tier = None
        if tier:
            if tier["durability"] == "remote-durable":
                frame += "\ntier: remote-durable"
            else:
                frame += (
                    f"\ntier: local-committed — "
                    f"{_fmt_bytes(tier.get('lag_bytes') or 0)} awaiting "
                    f"drain to {tier.get('remote')}"
                )
        if interactive and prev_lines:
            # Refresh in place: move the cursor back over the last frame.
            sys.stdout.write(f"\x1b[{prev_lines}F\x1b[J")
        print(frame, flush=True)
        prev_lines = frame.count("\n") + 1
        if args.once:
            return 0 if records else 3
        done = records and all(
            r.get("state") != "running" for r in records
        )
        if done:
            return 0
        if committed and seen_records:
            # Metadata lands a beat before the final 100% heartbeat —
            # give the publishers a short grace window, then stop.
            if commit_seen_at is None:
                commit_seen_at = time.monotonic()
            elif time.monotonic() - commit_seen_at > 2.0:
                return 0
        if deadline is not None and time.monotonic() > deadline:
            return 0 if seen_records else 3
        time.sleep(args.interval)


def cmd_history(args) -> int:
    import datetime
    import json as _json

    from .history import check_regression, history_path, load_history

    path = args.file or history_path()
    events = load_history(path)
    if args.check:
        if args.kind == "all":
            # Checking pools of incommensurable metrics is meaningless;
            # refuse instead of silently coercing to one kind.
            print(
                "error: --check needs one event kind "
                "(--kind take|restore|bench); run one check per kind",
                file=sys.stderr,
            )
            return 1
        # --metric is repeatable (and comma-splittable): one gate run
        # covers throughput AND the p99 storage-write latency (and any
        # other recorded scalar) in a single invocation.
        metrics: list = []
        for m in args.metric or ["throughput_gbps"]:
            metrics.extend(t.strip() for t in m.split(",") if t.strip())
        reports = [
            check_regression(
                events,
                kind=args.kind,
                metric=m,
                window=args.window,
                threshold=args.threshold,
                min_baseline=args.min_baseline,
            )
            for m in metrics
        ]
        regressed = [r for r in reports if r.regressed]
        any_ok = any(r.ok for r in reports)
        if args.json:
            # Machine-readable contract: every regressed metric is
            # NAMED, with its latest/baseline/window values, so a CI
            # wrapper never has to parse prose.
            print(
                _json.dumps(
                    {
                        "file": path,
                        "kind": args.kind,
                        "ok": any_ok and not regressed,
                        "regressed": [r.metric for r in regressed],
                        "checks": [r.to_json() for r in reports],
                    }
                )
            )
        else:
            for report in reports:
                verdict = (
                    "REGRESSION"
                    if report.regressed
                    else ("OK" if report.ok else "INSUFFICIENT DATA")
                )
                print(f"{verdict} [{report.kind}/{report.metric}]: {report.reason}")
                if report.baseline_median is not None:
                    print(
                        f"  latest {report.latest:.4g} vs trailing-median "
                        f"{report.baseline_median:.4g} over {report.n_baseline} "
                        f"run(s) (threshold {report.threshold:.0%})"
                    )
        # Exit contract unchanged: 2 = any metric regressed, 3 = no
        # metric could form a verdict at all, 0 otherwise (a metric
        # absent from older events does not fail the gate while the
        # checkable ones pass).
        if regressed:
            return 2
        return 0 if any_ok else 3
    shown = [
        e for e in events if args.kind == "all" or e.get("kind") == args.kind
    ]
    if args.limit:
        shown = shown[-args.limit :]
    if args.json:
        print(_json.dumps({"file": path, "events": shown}))
        return 0 if shown else 3
    if not shown:
        print(
            f"no history recorded (kind {args.kind!r}; looked in {path})",
            file=sys.stderr,
        )
        return 3
    print(
        f"{'when':<16} {'kind':<8} {'rank':>4} {'world':>5} "
        f"{'GB':>8} {'wall':>9} {'GB/s':>7}  notes"
    )
    for e in shown:
        ts = e.get("ts")
        when = (
            datetime.datetime.fromtimestamp(ts).strftime("%m-%d %H:%M:%S")
            if ts
            else "-"
        )
        gbps = e.get("throughput_gbps")
        notes = []
        if e.get("cold"):
            notes.append("cold")
        if e.get("stall_episodes"):
            notes.append(f"{e['stall_episodes']} stall(s)")
        if e.get("retry_attempts"):
            notes.append(f"{e['retry_attempts']} retries")
        if e.get("blobs_salvaged"):
            notes.append(f"{e['blobs_salvaged']} salvaged")
        if e.get("dedup_skips"):
            notes.append(f"{e['dedup_skips']} dedup")
        print(
            f"{when:<16} {e.get('kind', '?'):<8} {e.get('rank', 0):>4} "
            f"{e.get('world_size', 1):>5} "
            f"{(e.get('bytes') or 0) / 1e9:>8.2f} "
            f"{_fmt_seconds(e.get('wall_s')):>9} "
            f"{(f'{gbps:.2f}' if gbps is not None else '-'):>7}  "
            f"{' '.join(notes)}"
        )
    print(f"({len(shown)} of {len(events)} event(s) in {path})")
    return 0


def _fmt_age(s: float) -> str:
    if s < 120:
        return f"{s:.0f}s"
    if s < 7200:
        return f"{s / 60:.0f}m"
    if s < 172800:
        return f"{s / 3600:.1f}h"
    return f"{s / 86400:.1f}d"


def cmd_slo(args) -> int:
    import json as _json
    import os as _os

    from .slo import evaluate_records, read_slo_records, slo_dir

    directory = args.dir or slo_dir()
    records = read_slo_records(directory)
    report = evaluate_records(
        records, rpo_threshold_s=args.rpo, rto_threshold_s=args.rto
    )
    # Write-back tier exposure (tpusnap.tiering): a degraded uploader
    # means local-committed bytes whose cloud durability is NOT
    # converging — an SLO risk surfaced (and gated) alongside RPO/RTO.
    import time as _time

    from .knobs import get_tier_backoff_cap_s
    from .tiering import read_tier_status

    tier = read_tier_status(
        _os.path.dirname(directory.rstrip(_os.sep)) if args.dir else None
    )
    # A LIVE degraded drain republishes its status at least once per
    # backoff cycle; a flag older than a few cycles means the uploader
    # process is gone (SIGKILLed, or the job ended) — surface it as
    # stale instead of failing the gate forever on a dead breadcrumb.
    tier_stale = bool(
        tier
        and _time.time() - (tier.get("ts") or 0)
        > 10 * get_tier_backoff_cap_s()
    )
    tier_degraded = bool(tier and tier.get("degraded") and not tier_stale)
    if args.json:
        print(_json.dumps({"dir": directory, "tier": tier, **report}))
    else:
        print(f"slo dir:    {directory}")
        th = report["thresholds"]
        print(
            "thresholds: "
            f"rpo={'%gs' % th['rpo_s'] if th['rpo_s'] else 'unset'} "
            f"rto={'%gs' % th['rto_s'] if th['rto_s'] else 'unset'} "
            f"stream={'%gx cadence' % th['stream_cadence_x'] if th.get('stream_cadence_x') else 'off'} "
            "(TPUSNAP_SLO_RPO_S / TPUSNAP_SLO_RTO_S / "
            "TPUSNAP_SLO_STREAM_CADENCE_X)"
        )
        if report["ranks"]:
            print(
                f"\n{'rank':>4} {'since-commit':>13} {'at-risk':>10} "
                f"{'est-RTO':>9} {'rec-age':>8} {'dead':>6}  breach"
            )
            for r in report["ranks"]:
                flags = [
                    k
                    for k, on in (
                        ("RPO", r["breach_rpo"]),
                        ("RTO", r["breach_rto"]),
                        ("STREAM", r.get("breach_stream")),
                    )
                    if on
                ]
                rto = r.get("estimated_rto_s")
                rto_cell = _fmt_seconds(rto) if rto is not None else "-"
                if rto is not None and r.get("rto_source") == "probe":
                    rto_cell += "~"
                since = (
                    _fmt_age(r["since_commit_s"])
                    if r.get("committed")
                    else f"{_fmt_age(r['since_commit_s'])}*"
                )
                dead = r.get("dead_ranks")
                dead_s = ",".join(str(d) for d in dead) if dead else "-"
                print(
                    f"{r['rank']:>4} {since:>13} "
                    f"{_fmt_bytes(r['data_at_risk_bytes']):>10} "
                    f"{rto_cell:>9} "
                    f"{_fmt_age(r['record_age_s']):>8} {dead_s:>6}  "
                    f"{','.join(flags) or '-'}"
                    + ("  (exited cleanly; exposure frozen)"
                       if r.get("final") else "")
                )
            fleet = next(
                (r["fleet"] for r in report["ranks"] if r.get("fleet")), None
            )
            if fleet:
                print(
                    f"fleet (rank 0 fold over {fleet.get('ranks')} rank(s)): "
                    f"rpo {_fmt_age(fleet.get('rpo_s') or 0)}, "
                    f"{_fmt_bytes(fleet.get('data_at_risk_bytes') or 0)} at "
                    "risk"
                )
            cadence = next(
                (
                    r["stream_cadence_s"]
                    for r in report["ranks"]
                    if r.get("stream_cadence_s")
                ),
                None,
            )
            if cadence:
                print(
                    f"stream:     delta stream active, cadence {cadence:g}s "
                    "— micro-commits anchor the RPO (expect since-commit "
                    "≤ ~2x cadence; --check exits 2 past the stream "
                    "threshold)"
                )
            if any(not r.get("committed") for r in report["ranks"]):
                print("(* = no commit yet; exposure counted from tracker start)")
            if any(r.get("rto_source") == "probe" for r in report["ranks"]):
                print(
                    "(~ = RTO priced from the read-lane probe ceiling — "
                    "no restore history yet, no overhead term)"
                )
        if tier:
            if tier_degraded:
                print(
                    f"tier:       DEGRADED — remote {tier.get('remote')} "
                    f"unavailable, {_fmt_bytes(tier.get('lag_bytes') or 0)} "
                    f"local-committed only "
                    f"({_fmt_age(tier.get('lag_seconds') or 0)} of lag)"
                )
            elif tier.get("state") in ("draining", "degraded"):
                print(
                    f"tier:       {'STALE — last uploader status ' if tier_stale else ''}"
                    f"draining — "
                    f"{_fmt_bytes(tier.get('lag_bytes') or 0)} awaiting "
                    f"remote durability"
                    + (
                        " (uploader gone? `tpusnap drain` resumes it)"
                        if tier_stale
                        else ""
                    )
                )
        print(f"\n{report['verdict'].upper()}: {report['reason']}")
    # A live degraded tier is a breach regardless of whether any SLO
    # rank records exist yet (a drain-only host still has bytes at
    # risk) — checked BEFORE the no-records leg so the gate cannot
    # read exit 3 ("insufficient") out of a real exposure.
    if args.check and tier_degraded:
        return 2
    # Without records there is nothing to render in any mode (exit 3,
    # like watch/trace). The 2-on-breach / 3-on-no-verdict legs are
    # gate semantics and apply under --check only.
    if not records:
        return 3
    if args.check:
        if report["verdict"] == "breach":
            return 2
        if report["verdict"] == "insufficient":
            return 3
    return 0


def _render_fleet_table(rollup: dict) -> str:
    """Per-job fleet status table (shared by ``fleet`` and ``watch
    --fleet``)."""
    lines = [
        f"{'job':<22} {'state':<10} {'phase':<10} {'%':>5} "
        f"{'since-commit':>13} {'at-risk':>9} {'lag':>9} {'read':>9} "
        f"{'rec-age':>8}  flags"
    ]
    for j in rollup.get("jobs") or []:
        flags = []
        if j.get("degraded"):
            flags.append("DEGRADED")
        if j.get("paused"):
            flags.append("PAUSED")
        if j.get("reader"):
            flags.append("READER")
        if j.get("dead_ranks"):
            flags.append(
                "dead:" + ",".join(str(r) for r in j["dead_ranks"])
            )
        pct = j.get("percent")
        lines.append(
            f"{str(j.get('job_id'))[:22]:<22} {j.get('state') or '?':<10} "
            f"{str(j.get('phase') or '-')[:10]:<10} "
            f"{(f'{pct:.0f}' if pct is not None else '-'):>5} "
            f"{_fmt_age(j.get('rpo_s') or 0):>13} "
            f"{_fmt_bytes(j.get('data_at_risk_bytes') or 0):>9} "
            f"{_fmt_bytes(j.get('lag_bytes') or 0):>9} "
            f"{(_fmt_bytes(j['bytes_read']) if j.get('bytes_read') else '-'):>9} "
            f"{_fmt_age(j.get('age_s') or 0):>8}  "
            f"{' '.join(flags) or '-'}"
        )
    return "\n".join(lines)


def _fleet_summary_lines(rollup: dict) -> str:
    """The cross-job rollup footer under the per-job table."""
    worst = rollup.get("worst_rpo_s")
    parts = [
        f"{rollup.get('n_jobs', 0)} job(s), "
        f"{rollup.get('writers', 0)} writing, "
        f"{rollup.get('degraded_jobs', 0)} degraded, "
        f"{rollup.get('paused_jobs', 0)} paused, "
        f"{rollup.get('dead_ranks', 0)} dead rank(s)"
    ]
    if worst is not None:
        parts.append(
            f"worst RPO {_fmt_age(worst)} ({rollup.get('worst_rpo_job')}), "
            f"{_fmt_bytes(rollup.get('worst_data_at_risk_bytes') or 0)} at "
            "risk"
        )
    parts.append(
        f"upload lag {_fmt_bytes(rollup.get('lag_bytes_total') or 0)} "
        f"(oldest {_fmt_age(rollup.get('lag_seconds_max') or 0)})"
    )
    if rollup.get("readers"):
        amp = rollup.get("read_amplification")
        line = (
            f"{rollup['readers']} reader(s), "
            f"{_fmt_bytes(rollup.get('bytes_read_total') or 0)} read"
        )
        if amp is not None:
            line += (
                f", worst read amplification {amp:.2f}x "
                f"(snapshot {rollup.get('read_amplification_digest')})"
            )
        parts.append(line)
    w = (rollup.get("storage") or {}).get("write") or {}
    if w.get("count"):
        parts.append(
            f"storage write p50 {_fmt_seconds(w.get('p50_s'))} / "
            f"p99 {_fmt_seconds(w.get('p99_s'))} over {w['count']} op(s) "
            "(merged across jobs)"
        )
    return "\n".join("fleet:      " + p for p in parts)


def cmd_fleet(args) -> int:
    import json as _json

    from .fleet import (
        evaluate_fleet,
        fold_fleet,
        read_fleet_records,
        write_fleet_prom,
    )
    from .knobs import get_fleet_dir

    directory = args.dir or get_fleet_dir()
    if not directory:
        print(
            "error: no fleet directory (set TPUSNAP_FLEET_DIR or pass "
            "--dir)",
            file=sys.stderr,
        )
        return 1
    records = read_fleet_records(directory)
    rollup = fold_fleet(records)
    report = evaluate_fleet(
        rollup,
        rpo_threshold_s=args.rpo,
        lag_bytes_threshold=args.lag_bytes,
        lag_seconds_threshold=args.lag_s,
        p99_ratio_threshold=args.p99_ratio,
        max_read_amplification=args.max_read_amplification,
    )
    if args.prom_out:
        write_fleet_prom(rollup, args.prom_out)
    if args.json:
        print(_json.dumps({"dir": directory, "rollup": rollup, **report}))
    else:
        print(f"fleet dir:  {directory}")
        th = report["thresholds"]
        print(
            "thresholds: "
            f"rpo={'%gs' % th['rpo_s'] if th['rpo_s'] else 'unset'} "
            f"lag_bytes={th['lag_bytes'] or 'unset'} "
            f"lag_s={'%gs' % th['lag_seconds'] if th['lag_seconds'] else 'unset'} "
            f"p99_ratio={'%gx' % th['p99_ratio'] if th['p99_ratio'] else 'unset'} "
            f"read_amp={'%gx' % th['read_amplification'] if th['read_amplification'] else 'unset'}"
        )
        if records:
            print()
            print(_render_fleet_table(rollup))
            print(_fleet_summary_lines(rollup))
        print(f"\n{report['verdict'].upper()}: {report['reason']}")
    # Without records there is nothing to render in any mode (exit 3,
    # like slo/watch). The 2-on-breach leg is gate semantics under
    # --check only.
    if not records:
        return 3
    if args.check and report["verdict"] == "breach":
        return 2
    return 0


def _watch_fleet(args) -> int:
    """``watch --fleet``: tail the shared fleet directory instead of one
    take's heartbeat files — one row per JOB, refreshed in place."""
    import json as _json
    import time

    from .fleet import fold_fleet, read_fleet_records
    from .knobs import get_fleet_dir

    directory = args.path or get_fleet_dir()
    if not directory:
        print(
            "error: no fleet directory (set TPUSNAP_FLEET_DIR, or "
            "`watch --fleet DIR`)",
            file=sys.stderr,
        )
        return 1
    deadline = (
        time.monotonic() + args.max_seconds if args.max_seconds else None
    )
    interactive = sys.stdout.isatty() and not args.once and not args.json
    prev_lines = 0
    seen_records = False
    while True:
        records = read_fleet_records(directory)
        rollup = fold_fleet(records)
        if records:
            seen_records = True
        if args.json:
            print(_json.dumps({"dir": directory, "rollup": rollup}))
            return 0 if records else 3
        frame = _render_fleet_table(rollup)
        if records:
            frame += "\n" + _fleet_summary_lines(rollup)
        else:
            frame += f"\n(no fleet status records in {directory})"
        if interactive and prev_lines:
            # Refresh in place: move the cursor back over the last frame.
            sys.stdout.write(f"\x1b[{prev_lines}F\x1b[J")
        print(frame, flush=True)
        prev_lines = frame.count("\n") + 1
        if args.once:
            return 0 if records else 3
        # A fleet is open-ended (jobs come and go) — unlike the per-take
        # watch there is no commit to wait for; run until the deadline.
        if deadline is not None and time.monotonic() > deadline:
            return 0 if seen_records else 3
        time.sleep(args.interval)


def _heatmap_metadata(path: str):
    """Own-resources manifest read for the heatmap CLI (the
    verify_snapshot pattern: fresh loop + plugin, closed on exit)."""
    import asyncio

    from .inspect import _read_metadata
    from .storage_plugin import url_to_storage_plugin_in_event_loop

    event_loop = asyncio.new_event_loop()
    try:
        storage = url_to_storage_plugin_in_event_loop(path, event_loop, None)
        try:
            return _read_metadata(storage, event_loop, path)
        finally:
            storage.sync_close(event_loop)
    finally:
        event_loop.close()


def cmd_heatmap(args) -> int:
    import json as _json

    from . import access

    records = access.load_ledger_records(args.path)
    if not records:
        print(
            f"no access ledgers for {args.path} under "
            f"{access.access_dir(args.path)} — readers record only with "
            "TPUSNAP_TELEMETRY=1 (and TPUSNAP_ACCESS_LEDGER not 0)",
            file=sys.stderr,
        )
        return 3
    metadata = _heatmap_metadata(args.path)
    hm = access.compute_heatmap(records, metadata)
    breach = bool(
        args.max_amplification is not None
        and hm["amplification"] > args.max_amplification
    )
    if args.json:
        out = {"path": args.path, **hm}
        if args.max_amplification is not None:
            out["max_amplification"] = args.max_amplification
            out["breach"] = breach
        print(_json.dumps(out))
    else:
        print(f"snapshot:   {args.path}")
        print(f"ledgers:    {access.access_dir(args.path)}")
        print(
            f"readers:    {hm['n_readers']}  "
            f"(bytes read {_fmt_bytes(hm['bytes_read'])} over "
            f"{_fmt_bytes(hm['snapshot_bytes'])} stored)"
        )
        print(
            f"coverage:   {hm['coverage'] * 100:.1f}% of stored bytes "
            "ever read"
        )
        amp_line = f"amplification: {hm['amplification']:.2f}x"
        if args.max_amplification is not None:
            amp_line += (
                f"  (threshold {args.max_amplification:g}x — "
                + ("BREACH" if breach else "ok")
                + ")"
            )
        print(amp_line)
        if hm.get("unattributed_bytes"):
            print(
                f"unattributed: {_fmt_bytes(hm['unattributed_bytes'])} "
                "(ledger paths absent from this manifest — stale "
                "ledgers or a rewritten snapshot)"
            )
        print()
        print(
            f"{'leaf':<44} {'stored':>9} {'read':>9} {'reads':>6} "
            f"{'rdrs':>5} {'cov%':>6} {'amp':>6}  sources"
        )
        for row in hm["leaves"]:
            srcs = ",".join(
                f"{s}:{_fmt_bytes(b)}"
                for s, b in sorted(row["sources"].items())
            )
            print(
                f"{row['path'][:44]:<44} "
                f"{_fmt_bytes(row['stored_bytes']):>9} "
                f"{_fmt_bytes(row['bytes_read']):>9} "
                f"{row['reads']:>6} {row['readers']:>5} "
                f"{row['coverage'] * 100:>5.1f}% "
                f"{row['amplification']:>5.2f}x  {srcs or '-'}"
            )
        hot = hm["hot_ranges"][: args.top]
        if hot:
            print()
            print(f"hottest tile ranges (top {len(hot)}):")
            for h in hot:
                print(
                    f"  {h['path']}  {h['location']}"
                    f"[{h['range'][0]}:{h['range'][1]})  "
                    f"{h['reads']} read(s), {_fmt_bytes(h['bytes'])}"
                )
    if args.check and breach:
        return 2
    return 0


def cmd_cat(args) -> int:
    out = Snapshot(args.path).read_object(args.manifest_path)
    if isinstance(out, np.ndarray):
        print(f"# {out.dtype}{list(out.shape)}")
        print(np.array2string(out, threshold=64, edgeitems=3))
    else:
        print(repr(out))
    return 0


def cmd_lint(args) -> int:
    from .devtools import lint as _lint

    return _lint.main(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpusnap", description=__doc__.split("\n")[0]
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("info", help="snapshot summary")
    p.add_argument("path")
    p.set_defaults(fn=cmd_info)

    p = sub.add_parser("ls", help="list manifest entries")
    p.add_argument("path")
    p.add_argument("-l", "--long", action="store_true", help="sizes/types")
    p.add_argument("-a", "--all", action="store_true", help="include containers")
    p.set_defaults(fn=cmd_ls)

    p = sub.add_parser("verify", help="integrity scrub (checksum every blob)")
    p.add_argument("path")
    p.add_argument("-v", "--verbose", action="store_true")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser("cat", help="print one object")
    p.add_argument("path")
    p.add_argument("manifest_path", help='"<rank>/<logical_path>"')
    p.set_defaults(fn=cmd_cat)

    p = sub.add_parser(
        "materialize",
        help="copy base-referenced blobs into an incremental snapshot, "
        "making it self-contained",
    )
    p.add_argument("path")
    p.set_defaults(fn=cmd_materialize)

    p = sub.add_parser(
        "diff",
        help="compare two snapshots by recorded checksums (no data reads)",
    )
    p.add_argument("path_a")
    p.add_argument("path_b")
    p.add_argument(
        "-q", "--quiet", action="store_true", help="summary line only"
    )
    p.set_defaults(fn=cmd_diff)

    p = sub.add_parser(
        "trace",
        help="render per-take telemetry (stage timings, counters, rollup)",
    )
    p.add_argument("path")
    p.add_argument(
        "--json", action="store_true", help="machine-readable summaries"
    )
    p.add_argument(
        "--rank", type=int, default=None, metavar="K",
        help="also print rank K's per-stage detail",
    )
    p.add_argument(
        "--restore", action="store_true",
        help="render the LAST restore's traces (persisted locally under "
        "TPUSNAP_TELEMETRY_DIR) instead of the take's",
    )
    p.set_defaults(fn=cmd_trace)

    from .io_types import PROGRESS_DIR

    p = sub.add_parser(
        "watch",
        help="live per-rank progress table of an in-flight take "
        f"(tails {PROGRESS_DIR}/ heartbeat records); --fleet tails the "
        "cross-job fleet directory instead (one row per JOB)",
    )
    p.add_argument(
        "path", nargs="?", default=None,
        help="snapshot path (with --fleet: the fleet directory, "
        "default TPUSNAP_FLEET_DIR)",
    )
    p.add_argument(
        "--fleet", action="store_true",
        help="tail the shared fleet directory (TPUSNAP_FLEET_DIR or "
        "PATH): per-job state, since-commit exposure, upload lag, "
        "degraded/paused flags",
    )
    p.add_argument(
        "--interval", type=float, default=1.0, metavar="S",
        help="refresh interval in seconds (default 1.0)",
    )
    p.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    p.add_argument(
        "--json", action="store_true",
        help="print one machine-readable frame and exit",
    )
    p.add_argument(
        "--max-seconds", type=float, default=None, metavar="S",
        help="give up after S seconds (default: wait for the commit)",
    )
    p.add_argument(
        "--stall-flag", type=float, default=10.0, metavar="S",
        help="flag a rank as STALLED? after S seconds without a beat "
        "(default 10)",
    )
    p.set_defaults(fn=cmd_watch)

    p = sub.add_parser(
        "history",
        help="cross-run take/restore performance history "
        "(--check = regression gate for CI/cron)",
    )
    p.add_argument(
        "--file", default=None,
        help="history file (default: TPUSNAP_TELEMETRY_DIR/history.jsonl)",
    )
    p.add_argument(
        "--kind", default="take",
        choices=["take", "restore", "bench", "orbax", "fleet", "all"],
        help="event kind to show/check (default take; orbax = the "
        "orbax_compare benchmark's median/speedup events; fleet = "
        "fleetsim soak events)",
    )
    p.add_argument(
        "-n", "--limit", type=int, default=20, metavar="N",
        help="show the newest N events (default 20; 0 = all)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.add_argument(
        "--check", action="store_true",
        help="compare the latest run against the trailing median; "
        "exit 2 on regression, 3 on insufficient comparable history",
    )
    p.add_argument(
        "--metric", action="append", default=None, metavar="M",
        help="event field(s) to check — repeatable and comma-splittable "
        "(default throughput_gbps; *_s metrics such as "
        "storage_write_p99_s regress upward)",
    )
    p.add_argument(
        "--window", type=int, default=20, metavar="N",
        help="trailing baseline window (default 20 runs)",
    )
    p.add_argument(
        "--threshold", type=float, default=0.25, metavar="F",
        help="regression threshold as a fraction of the trailing median "
        "(default 0.25)",
    )
    p.add_argument(
        "--min-baseline", type=int, default=3, metavar="N",
        dest="min_baseline",
        help="minimum comparable baseline runs to form a verdict "
        "(default 3)",
    )
    p.set_defaults(fn=cmd_history)

    p = sub.add_parser(
        "analyze",
        help="performance doctor: bound-by verdict + knob advice, "
        "tail-latency outliers, stragglers, roofline fraction",
    )
    p.add_argument("path")
    p.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p.add_argument(
        "--check", action="store_true",
        help="exit 2 when any warn-severity finding fires (tail "
        "latency, straggler skew, roofline shortfall) — the CI gate",
    )
    p.add_argument(
        "--restore", action="store_true",
        help="analyze the LAST restore's traces (local "
        "TPUSNAP_TELEMETRY_DIR) instead of the take's",
    )
    p.add_argument(
        "--history", action="store_true",
        help="add trend context from this host's history.jsonl",
    )
    p.add_argument(
        "--p99-ratio", type=float, default=20.0, metavar="R",
        dest="p99_ratio",
        help="flag an op whose p99 latency exceeds R x its p50 "
        "(default 20)",
    )
    p.add_argument(
        "--min-roofline", type=float, default=0.4, metavar="F",
        dest="min_roofline",
        help="flag a take below this fraction of its in-take probe "
        "ceiling (default 0.4; needs TPUSNAP_PROBE=1 at take time)",
    )
    p.add_argument(
        "--min-read-roofline", type=float, default=0.4, metavar="F",
        dest="min_read_roofline",
        help="flag a restore below this fraction of its in-restore "
        "probe READ ceiling (default mirrors --min-roofline's 0.4; "
        "needs TPUSNAP_PROBE=1 at restore time)",
    )
    p.add_argument(
        "--max-skew", type=float, default=2.0, metavar="S",
        dest="max_skew",
        help="flag a phase whose slowest rank exceeds S x the p50 "
        "(default 2.0)",
    )
    p.set_defaults(fn=cmd_analyze)

    p = sub.add_parser(
        "tune",
        help="deterministic knob plan for one (backend, kind, "
        "world_size) cell from history.jsonl + probe ceilings + the "
        "analyze verdict (exit 0 plan / 3 insufficient history)",
    )
    p.add_argument(
        "--file", default=None,
        help="history file (default: TPUSNAP_TELEMETRY_DIR/history.jsonl)",
    )
    p.add_argument(
        "--kind", choices=("take", "restore"), default=None,
        help="plan cell kind (default: this host's newest event's kind)",
    )
    p.add_argument(
        "--backend", default=None, metavar="LABEL",
        help="plan cell backend (innermost plugin class label; "
        "default: the newest matching event's)",
    )
    p.add_argument(
        "--world-size", type=int, default=None, dest="world_size",
        metavar="N",
        help="plan cell world size (default: the newest matching "
        "event's)",
    )
    p.add_argument(
        "--snapshot", default=None, metavar="PATH",
        help="fold the analyze bound verdict from PATH's persisted "
        "traces into the plan (best-effort)",
    )
    p.add_argument(
        "--window", type=int, default=50, metavar="N",
        help="newest N cell events to plan from (default 50)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable plan"
    )
    p.add_argument(
        "--env", action="store_true",
        help="shell-exportable `export TPUSNAP_X=value` lines",
    )
    p.add_argument(
        "--check", action="store_true",
        help="exit 0 when a plan renders, 3 on insufficient "
        "comparable history — the CI contract",
    )
    p.set_defaults(fn=cmd_tune)

    p = sub.add_parser(
        "timeline",
        help="forensic cross-rank event timeline from the flight-"
        "recorder sidecars; post-mortem verdict for uncommitted paths "
        "(exit 0 committed / 4 uncommitted / 3 no flight data)",
    )
    p.add_argument("path")
    p.add_argument(
        "--rank", type=int, default=None, metavar="K",
        help="show only rank K's events (skew/verdict still use all)",
    )
    p.add_argument(
        "--last", type=int, default=0, metavar="N",
        help="show only the newest N merged events (default: all)",
    )
    p.add_argument(
        "--around", type=float, default=None, metavar="T",
        help="show events within --window seconds of T seconds into "
        "the timeline",
    )
    p.add_argument(
        "--window", type=float, default=2.0, metavar="S",
        help="half-width of the --around window (default 2.0s)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser(
        "fsck",
        help="classify a snapshot directory (committed/torn/empty/"
        "corrupt-metadata/foreign) and enumerate orphan blobs",
    )
    p.add_argument("path")
    p.add_argument(
        "-v", "--verbose", action="store_true",
        help="list each orphan/missing file",
    )
    p.add_argument(
        "--store", action="store_true",
        help="treat PATH as a content-addressed STORE directory: "
        "store-wide verdicts (dangling refs, orphan blobs, torn "
        "publishes, stale intents/roots, refcount-cache divergence); "
        "exit 0 clean-or-reclaimable / 4 dangling ref(s) / 3 not a "
        "store",
    )
    p.set_defaults(fn=cmd_fsck)

    p = sub.add_parser(
        "gc",
        help="reclaim orphan blobs (dry-run unless --force)",
    )
    p.add_argument("path")
    p.add_argument(
        "--force", action="store_true", help="actually delete (default: dry-run)"
    )
    p.add_argument(
        "--torn", action="store_true",
        help="also discard a TORN take's blobs (forfeits salvage-resume)",
    )
    p.add_argument(
        "--evict-local", action="store_true",
        help="write-back tiering: also reclaim a REMOTE-DURABLE "
        "snapshot's local payload blobs (refused before the upload "
        "journal's durable marker, and within the "
        "TPUSNAP_TIER_LOCAL_RETENTION_S hot-cache window; metadata and "
        "the journal stay, reads through the tier URL fall back to the "
        "remote)",
    )
    p.add_argument(
        "--store", action="store_true",
        help="treat PATH as a content-addressed STORE directory: "
        "mark-and-sweep over ref records (grace window "
        "TPUSNAP_CAS_GRACE_S, per-store lock lease); sweeps "
        "unreferenced blobs, torn publishes, stale intents and stale "
        "roots",
    )
    p.set_defaults(fn=cmd_gc)

    p = sub.add_parser(
        "drain",
        help="write-back tiering: force-drain a tiered snapshot to its "
        "remote tier (resumes from the crash-safe upload journal; "
        "exit 0 remote-durable / 2 did-not-converge / 3 not tiered)",
    )
    p.add_argument(
        "path",
        help="tier URL (tier+local=...+remote=...://...) or the local "
        "tier directory (the upload journal names the remote)",
    )
    p.add_argument(
        "--store", action="store_true",
        help="treat PATH as a content-addressed STORE directory: "
        "upload each blob ONCE store-wide to the store's remote "
        "mirror (config.json remote / TPUSNAP_CAS_REMOTE), journaled "
        "by hash for crash-safe resume",
    )
    p.add_argument(
        "--remote", default=None, metavar="URL",
        help="override the remote tier URL recorded in the journal",
    )
    p.add_argument(
        "--timeout", type=float, default=None, metavar="SECS",
        help="give up (exit 2, resumable) after this long of sustained "
        "remote unavailability (default: keep probing until durable)",
    )
    p.add_argument(
        "--status", action="store_true",
        help="report the per-snapshot tier state without draining",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser(
        "retain",
        help="keep the newest N snapshots under a directory; materialize "
        "kept increments, then delete the rest (local fs only)",
    )
    p.add_argument("root")
    p.add_argument("--keep", type=int, required=True, metavar="N")
    p.add_argument("--dry-run", action="store_true")
    p.set_defaults(fn=cmd_retain)

    p = sub.add_parser(
        "slo",
        help="checkpoint SLO state (per-rank time-since-commit, "
        "data-at-risk, estimated RTO, breach flags); --check gates "
        "(exit 2 breach / 3 no records or no estimator verdict)",
    )
    p.add_argument(
        "--dir", default=None, metavar="DIR",
        help="SLO sidecar directory (default: TPUSNAP_TELEMETRY_DIR/slo)",
    )
    p.add_argument(
        "--rpo", type=float, default=None, metavar="S",
        help="RPO threshold in seconds (default: TPUSNAP_SLO_RPO_S; "
        "0/unset = no RPO objective)",
    )
    p.add_argument(
        "--rto", type=float, default=None, metavar="S",
        help="RTO threshold in seconds (default: TPUSNAP_SLO_RTO_S; "
        "0/unset = no RTO objective)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p.add_argument(
        "--check", action="store_true",
        help="gate mode: exit 2 on a breached objective, 3 when no "
        "records exist or an RTO objective has no estimate, 0 healthy",
    )
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser(
        "fleet",
        help="cross-job fleet status from the shared TPUSNAP_FLEET_DIR "
        "(per-job table, worst-case RPO/at-risk fold, aggregate upload "
        "lag, merged storage latency); --check gates (exit 2 breach / "
        "3 no records)",
    )
    p.add_argument(
        "--dir", default=None, metavar="DIR",
        help="fleet status directory (default: TPUSNAP_FLEET_DIR)",
    )
    p.add_argument(
        "--rpo", type=float, default=None, metavar="S",
        help="worst-job RPO threshold in seconds (default: "
        "TPUSNAP_SLO_RPO_S; 0/unset = no RPO objective)",
    )
    p.add_argument(
        "--lag-bytes", type=int, default=None, metavar="N",
        dest="lag_bytes",
        help="aggregate upload-lag threshold in bytes summed across "
        "jobs (default: no objective)",
    )
    p.add_argument(
        "--lag-s", type=float, default=None, metavar="S", dest="lag_s",
        help="upload-lag age threshold in seconds — the fleet's oldest "
        "undurable commit (default: no objective)",
    )
    p.add_argument(
        "--p99-ratio", type=float, default=None, metavar="R",
        dest="p99_ratio",
        help="breach when the cross-job merged storage write p99 "
        "exceeds R x its p50 (default: no objective)",
    )
    p.add_argument(
        "--prom-out", default=None, metavar="PATH", dest="prom_out",
        help="also write the rollup as scope=\"fleet\" Prometheus "
        "families to PATH (atomic; point into a node collector's "
        "textfile directory)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    p.add_argument(
        "--max-read-amplification", type=float, default=None, metavar="X",
        dest="max_read_amplification",
        help="breach when any snapshot's merged cross-reader read "
        "amplification (aggregate bytes read / stored bytes) exceeds X "
        "(default: no objective)",
    )
    p.add_argument(
        "--check", action="store_true",
        help="gate mode: exit 2 on a breached fleet objective, 3 when "
        "no status records exist, 0 healthy",
    )
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser(
        "heatmap",
        help="merge reader access ledgers into a per-leaf read heatmap "
        "— counts, bytes, distinct readers, coverage and read "
        "amplification (requires readers run with TPUSNAP_TELEMETRY=1)",
    )
    p.add_argument("path", help="snapshot path the ledgers were recorded for")
    p.add_argument(
        "--top", type=int, default=10, metavar="N",
        help="hottest tile ranges to list (default 10)",
    )
    p.add_argument(
        "--max-amplification", type=float, default=None, metavar="X",
        dest="max_amplification",
        help="flag (and with --check, gate) aggregate read "
        "amplification above X (bytes read / stored bytes)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable heatmap"
    )
    p.add_argument(
        "--check", action="store_true",
        help="gate mode: exit 2 when amplification exceeds "
        "--max-amplification, 3 when no ledgers exist, 0 otherwise",
    )
    p.set_defaults(fn=cmd_heatmap)

    p = sub.add_parser(
        "lint",
        help="AST invariant checker over the package source (knob "
        "access, monotonic clocks, sidecar literals, silent swallows, "
        "async blocking calls, finalizer joins, knob/doc drift); "
        "--check exits 2 on findings",
    )
    p.add_argument(
        "--root", default=None, metavar="DIR",
        help="package directory to lint (default: the installed "
        "tpusnap package)",
    )
    p.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule ids to run (default: all)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.add_argument(
        "--check", action="store_true",
        help="gate mode: exit 2 on any unwaived finding, 0 on clean",
    )
    p.set_defaults(fn=cmd_lint)

    try:
        args = parser.parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage errors, which would collide with the
        # documented "2 = corruption found" contract; --help stays 0.
        return 0 if e.code in (0, None) else 1
    try:
        return args.fn(args)
    except (RuntimeError, KeyError, ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
