"""Hard-timeout subprocess runner for real-accelerator probes.

The PJRT tunnel to the real TPU chip can wedge indefinitely, and its
helper processes inherit any pipes the caller creates.
``subprocess.run(capture_output=True, timeout=...)`` is NOT safe
against that: on timeout it kills the direct child and then blocks
draining the captured pipes — forever, when a surviving grandchild
(the tunnel helper) still holds the write ends open. This cost round 4
one bench leg and a >60-minute wedged test suite.

``run_hard_timeout`` cannot wedge:

- stdout/stderr go to temp FILES, so there is nothing to drain and a
  surviving grandchild can hold its copies open without blocking us;
- the child runs in its own session (process group), and on timeout
  the WHOLE group is SIGKILLed — the tunnel helper dies with it;
- every wait is bounded; optional retries re-run the probe from
  scratch (a wedged tunnel sometimes recovers between attempts).
"""

from __future__ import annotations

import os
import signal
import subprocess
import tempfile
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class ProbeResult:
    timed_out: bool
    returncode: Optional[int]  # None when timed_out
    stdout: str
    stderr: str
    attempts: int = 1


def _read_file(f) -> str:
    try:
        f.seek(0)
        return f.read().decode("utf-8", errors="replace")
    except OSError:
        return ""


def run_hard_timeout(
    cmd: List[str],
    timeout_s: float,
    env: Optional[dict] = None,
    retries: int = 0,
    grace_s: float = 10.0,
) -> ProbeResult:
    """Run ``cmd`` with a timeout that holds even when the child spawns
    pipe-holding, signal-ignoring grandchildren. On timeout the child's
    whole process group is SIGKILLed and (with ``retries`` > 0) the
    command is re-run from scratch. Never raises for child misbehavior;
    the caller branches on ``timed_out`` / ``returncode``."""
    last: Optional[ProbeResult] = None
    for attempt in range(1, retries + 2):
        with tempfile.TemporaryFile() as out_f, tempfile.TemporaryFile() as err_f:
            try:
                proc = subprocess.Popen(
                    cmd,
                    stdout=out_f,
                    stderr=err_f,
                    stdin=subprocess.DEVNULL,
                    env=env,
                    start_new_session=True,
                )
            except OSError as e:
                return ProbeResult(False, 127, "", str(e), attempt)
            try:
                rc = proc.wait(timeout=timeout_s)
                return ProbeResult(
                    False, rc, _read_file(out_f), _read_file(err_f), attempt
                )
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                try:
                    proc.wait(timeout=grace_s)
                except subprocess.TimeoutExpired:
                    pass  # unreaped zombie; we still return on time
                last = ProbeResult(
                    True, None, _read_file(out_f), _read_file(err_f), attempt
                )
    assert last is not None
    return last
